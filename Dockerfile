# Controller image — the reference uses distroless static + CGO off
# (Dockerfile, SURVEY.md §2a #16); the Python analog: slim base, deps baked,
# non-root, no shell entrypoint surprises. The JAX workload half is NOT in
# this image (it runs in the provisioned slice's pods, not the controller).
FROM python:3.12-slim AS base

WORKDIR /app
RUN pip install --no-cache-dir httpx aiohttp pyyaml prometheus-client

COPY gpu_provisioner_tpu/ ./gpu_provisioner_tpu/

RUN useradd --uid 65532 --no-create-home controller
USER 65532

ENV PYTHONUNBUFFERED=1
ENTRYPOINT ["python", "-m", "gpu_provisioner_tpu.operator"]
