# tpu-provisioner build/dev/deploy targets — the GKE analog of the
# reference Makefile (az-mkaks/az-identity-perm/az-federated-credential/
# az-patch-helm cluster bootstrap :63-118, unit-test :172, e2etests :178).

PROJECT_ID    ?= $(shell gcloud config get-value project 2>/dev/null)
LOCATION      ?= us-central2-b
CLUSTER_NAME  ?= kaito-tpu
GSA_NAME      ?= tpu-provisioner
GSA_EMAIL     := $(GSA_NAME)@$(PROJECT_ID).iam.gserviceaccount.com
NAMESPACE     ?= tpu-provisioner
IMG_REPO      ?= ghcr.io/kaito-project/tpu-provisioner
VERSION       ?= 0.1.0
PY            ?= python

.PHONY: help
help: ## Show this help
	@grep -E '^[a-zA-Z_-]+:.*?## .*$$' $(MAKEFILE_LIST) | \
	  awk 'BEGIN {FS = ":.*?## "}; {printf "  %-24s %s\n", $$1, $$2}'

## -------- lint / test / bench ---------------------------------------------

# The baseline layer (ruff/mypy) is ADVISORY until the configs have been
# validated in an image that ships the tools — the dev container doesn't,
# so a committed-but-unexecuted config must not be able to brick `make
# verify` on pre-existing code. Flip LINT_BASELINE_STRICT=1 once validated.
LINT_BASELINE_STRICT ?= 0

.PHONY: lint
lint: ## Static analysis: ruff + mypy (advisory baseline when installed) + provlint + provgraph (docs/STATIC_ANALYSIS.md)
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
	  $(PY) -m ruff check gpu_provisioner_tpu tests \
	    || { echo "lint: ruff baseline found issues"; \
	         [ "$(LINT_BASELINE_STRICT)" = "1" ] && exit 1 || true; }; \
	else echo "lint: ruff not installed; skipping baseline layer"; fi
	@if $(PY) -m mypy --version >/dev/null 2>&1; then \
	  $(PY) -m mypy gpu_provisioner_tpu/runtime gpu_provisioner_tpu/providers \
	    || { echo "lint: mypy baseline found issues"; \
	         [ "$(LINT_BASELINE_STRICT)" = "1" ] && exit 1 || true; }; \
	else echo "lint: mypy not installed; skipping baseline layer"; fi
	$(PY) -m gpu_provisioner_tpu.analysis gpu_provisioner_tpu tests
	$(PY) -m gpu_provisioner_tpu.analysis.provgraph

.PHONY: verify
verify: lint unit-test trace-smoke ## Default verify path: static analysis, the unit suites, then the claimtrace smoke

.PHONY: unit-test
unit-test: ## Unit tests (reference Makefile:171-175)
	$(PY) -m pytest tests/ -q -m "not e2e"

.PHONY: e2etests
e2etests: ## e2e suite: real operator subprocess vs HTTP fakes (Makefile:177-187)
	$(PY) -m pytest tests/e2e -q

CHAOS_SEED ?= 7
FUZZ_SEEDS ?= 20

.PHONY: fuzz
fuzz: ## Deterministic interleaving sweep: schedfuzz scenarios under FUZZ_SEEDS perturbed schedules (docs/STATIC_ANALYSIS.md)
	$(PY) -m gpu_provisioner_tpu.analysis.schedfuzz --seeds $(FUZZ_SEEDS)

.PHONY: chaos
chaos: fuzz brownout ## Interleaving sweep + apiserver-fault soaks, then the chaos soak suite + one crash-restart smoke, fixed seed (docs/FAILURE_MODES.md)
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_chaos.py tests/test_recovery.py -q -m chaos

.PHONY: brownout
brownout: ## Apiserver-fault soaks: brownout/partition/watch-gap profiles + the 200-claim 30s-partition acceptance soak
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_apifaults.py -q -m chaos

.PHONY: recover
recover: ## Crash-restart recovery soaks: crash-point matrix + fenced leader failover
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_recovery.py -q -m recovery

.PHONY: repair
repair: ## Node-fault health soaks: fault-profile × workload matrix + repair regressions
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_health.py -q -m repair

.PHONY: capacity
capacity: ## Capacity soaks: zonal stockout survival, spot reclaim, crash-resume fallback walk
	CHAOS_SEED=$(CHAOS_SEED) $(PY) -m pytest tests/test_placement.py -q -m capacity

.PHONY: e2etests-real
e2etests-real: ## Same specs against a live cluster (suite_test.go:34-45 mode).
	## Prereqs: operator deployed (make helm-install), KUBECONFIG pointing at
	## the cluster, PROJECT_ID/LOCATION/CLUSTER_NAME set, ADC available.
	E2E_TARGET=real PROJECT_ID=$(PROJECT_ID) LOCATION=$(LOCATION) \
	  CLUSTER_NAME=$(CLUSTER_NAME) $(PY) -m pytest tests/e2e -q -p no:cacheprovider

.PHONY: test
test: ## Everything
	$(PY) -m pytest tests/ -q

.PHONY: bench
bench: ## Provisioning benchmarks; fails on BENCH_pr02/pr04 budget regressions or the BENCH_pr09/pr11/pr12/pr14/pr16/pr19 gates
	$(PY) -m bench.bench_megawave --gate --procs
	$(PY) -m bench.bench_provision
	$(PY) -m bench.bench_fleet --gate
	$(PY) -m bench.bench_apifaults --gate

.PHONY: slo
slo: ## fleetscope suite: SLO engine + flight-recorder tests, then the overhead/memory gate
	$(PY) -m pytest tests/test_fleet.py -q
	$(PY) -m bench.bench_fleet --gate

.PHONY: megawave
megawave: ## Mega-wave smoke: reference gates + a 1k-claim 8-shard wave + the multi-process worker tier (full 10k tier: make megawave-full)
	$(PY) -m bench.bench_megawave --gate --procs

.PHONY: megawave-full
megawave-full: ## Full mega-wave tier: 10k claims at in-process shard counts 1/4/8 AND worker-process counts 1/4/8; slow — minutes of wall
	$(PY) -m bench.bench_megawave --full --procs --procs-full

.PHONY: trace
trace: ## 100-claim wave under claimtrace; print the critical-path attribution summary
	$(PY) -m bench.bench_provision --trace --claims 100

.PHONY: trace-smoke
trace-smoke: ## Small traced wave: the claimtrace attribution gate as a verify smoke
	$(PY) -m bench.bench_provision --trace-smoke

.PHONY: bench-headline
bench-headline: ## Fleet-scale headline benchmark JSON line
	$(PY) bench.py

## -------- image -----------------------------------------------------------

.PHONY: docker-build
docker-build: ## Build the controller image
	docker build -t $(IMG_REPO):$(VERSION) .

.PHONY: docker-push
docker-push: docker-build ## Push the controller image
	docker push $(IMG_REPO):$(VERSION)

# Multi-arch release image via buildx, mirroring the reference's
# docker-build-kaito (reference Makefile:134-160: buildx create + multi
# --platform build --push). amd64 for GKE nodes, arm64 for t2a/dev laptops.
PLATFORMS ?= linux/amd64,linux/arm64
BUILDER   ?= tpu-provisioner-builder

.PHONY: docker-buildx
docker-buildx: ## Build+push the multi-arch controller image manifest
	-docker buildx create --name $(BUILDER) --use
	docker buildx build --platform $(PLATFORMS) \
	  -t $(IMG_REPO):$(VERSION) --push .
	docker buildx rm $(BUILDER)

## -------- GKE cluster bootstrap (az-mkaks analog, Makefile:63-118) --------

.PHONY: gke-mkcluster
gke-mkcluster: ## Create a GKE cluster with workload identity enabled
	gcloud container clusters create $(CLUSTER_NAME) \
	  --project $(PROJECT_ID) --location $(LOCATION) \
	  --workload-pool=$(PROJECT_ID).svc.id.goog \
	  --num-nodes 1 --machine-type e2-standard-4

.PHONY: gke-workload-identity
gke-workload-identity: ## GSA + IAM + KSA binding (az-identity-perm + az-federated-credential analog)
	gcloud iam service-accounts create $(GSA_NAME) --project $(PROJECT_ID) || true
	gcloud projects add-iam-policy-binding $(PROJECT_ID) \
	  --member "serviceAccount:$(GSA_EMAIL)" --role roles/container.admin
	gcloud projects add-iam-policy-binding $(PROJECT_ID) \
	  --member "serviceAccount:$(GSA_EMAIL)" --role roles/tpu.admin
	gcloud iam service-accounts add-iam-policy-binding $(GSA_EMAIL) \
	  --project $(PROJECT_ID) --role roles/iam.workloadIdentityUser \
	  --member "serviceAccount:$(PROJECT_ID).svc.id.goog[$(NAMESPACE)/tpu-provisioner]"

.PHONY: helm-install
helm-install: ## Render values from gcloud and install the chart (az-patch-helm analog)
	./hack/deploy/configure-helm-values.sh > /tmp/tpu-provisioner-values.yaml
	helm upgrade --install tpu-provisioner charts/tpu-provisioner \
	  --namespace $(NAMESPACE) --create-namespace \
	  -f /tmp/tpu-provisioner-values.yaml

## -------- release ---------------------------------------------------------

.PHONY: release-manifest
release-manifest: ## Stamp chart + pyproject versions (Makefile:192 analog)
	sed -i 's/^version:.*/version: $(VERSION)/' charts/tpu-provisioner/Chart.yaml
	sed -i 's/^appVersion:.*/appVersion: "$(VERSION)"/' charts/tpu-provisioner/Chart.yaml
	sed -i 's/^version = .*/version = "$(VERSION)"/' pyproject.toml
