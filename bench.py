"""Benchmark: provisioning throughput of the full control plane.

Drives N NodeClaims through the REAL controller set (launch → registration →
initialization → Ready) against the simulated cloud (envtest), then — when an
accelerator is attached — times the flagship workload's forward step on it.

Prints ONE JSON line:
  {"metric": "nodeclaim_ready_p50", "value": <sec>, "unit": "s",
   "vs_baseline": <value/600>, "extra": {...}}

Baseline semantics: the reference encodes NO published numbers (BASELINE.md);
its only hard bound on NodeClaim→Ready is the 10-min e2e Eventually timeout
(reference test/e2e/pkg/environment/common/environment.go:67). vs_baseline is
p50/600s — lower is better. ``extra`` carries the other BASELINE.json
headline metrics (reconcile QPS, TPU chips/min) plus workload tokens/s.

Usage: python bench.py [--fast] [--claims N] [--shape tpu-v5e-8] [--no-tpu]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import statistics
import sys
import time

BASELINE_READY_BOUND_S = 600.0  # reference e2e Eventually timeout


def _p99(samples: list) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, math.ceil(0.99 * len(s)) - 1)]


async def bench_provisioning(n_claims: int, shape: str) -> dict:
    from gpu_provisioner_tpu import catalog
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim

    opts = EnvtestOptions(create_latency=0.05, node_join_delay=0.02,
                          node_ready_delay=0.02,
                          max_concurrent_reconciles=256)
    resolved = catalog.lookup(shape)
    if resolved is None:
        raise SystemExit(f"unknown TPU shape {shape!r} (try tpu-v5e-8, v5p-32)")
    async with Env(opts) as env:

        async def provision(i: int) -> float:
            # per-claim latency stamped at actual readiness, not loop arrival
            t_create = time.perf_counter()
            await env.client.create(
                make_nodeclaim(f"bench{i}", shape, workspace=f"ws{i}"))
            await env.wait_ready(f"bench{i}", timeout=120)
            return time.perf_counter() - t_create

        t0 = time.perf_counter()
        readies = await asyncio.gather(*(provision(i) for i in range(n_claims)))
        elapsed = time.perf_counter() - t0
    return {
        "p50_s": statistics.median(readies),
        "p99_s": _p99(readies),
        "reconcile_qps": n_claims / elapsed,
        "chips_per_min": n_claims * resolved.chips / (elapsed / 60.0),
        "elapsed_s": elapsed,
        "claims": n_claims,
    }


def bench_workload(fast: bool) -> dict:
    """Forward-step throughput of the flagship model on the attached device."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params
    from gpu_provisioner_tpu.models.train import make_forward

    dev = jax.devices()[0]
    # dense attention here: the pallas-kernel-per-layer scan compiles slowly
    # over the remote-compile tunnel; the flash kernel gets its own op-level
    # timing in bench_flash_op where compile cost is one kernel.
    cfg = (LlamaConfig(vocab_size=2048, dim=512, n_layers=4, n_heads=8,
                       n_kv_heads=4, hidden_dim=1408, dtype="bfloat16")
           if fast else
           LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
                       n_kv_heads=8, hidden_dim=5504, dtype="bfloat16"))
    B, S = (4, 512) if fast else (8, 1024)
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    tokens = jax.device_put(jnp.zeros((B, S), jnp.int32), dev)
    fwd = make_forward(cfg)

    def settle(x):
        # On tunneled/experimental platforms block_until_ready can return
        # before execution completes; a scalar host read cannot.
        x.block_until_ready()
        return float(x[0, 0, 0])

    for _ in range(3):                               # compile + settle queue
        settle(fwd(params, tokens))
    iters = 10
    best = float("inf")
    for _ in range(3):                               # best-of-3 against jitter
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fwd(params, tokens)
        settle(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return {"platform": dev.platform, "tokens_per_s": B * S / best,
            "step_ms": best * 1e3}


def bench_flash_op(fast: bool) -> dict:
    """Pallas flash-attention kernel vs the dense lax path, one op."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.ops import flash_attention
    from gpu_provisioner_tpu.parallel.ring import dense_attention

    B, S, Hq, Hkv, D = (4, 1024, 8, 4, 128) if fast else (8, 4096, 16, 8, 128)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.bfloat16)

    def settle(x):
        x.block_until_ready()
        return float(x[0, 0, 0, 0])

    def timeit(fn):
        f = jax.jit(fn)
        settle(f(q, k, v))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(5):
                out = f(q, k, v)
            settle(out)
            best = min(best, (time.perf_counter() - t0) / 5)
        return best * 1e3

    flash_ms = timeit(lambda *a: flash_attention(*a))
    dense_ms = timeit(lambda *a: dense_attention(*a))
    return {"seq_len": S, "flash_ms": flash_ms, "dense_ms": dense_ms,
            "flash_speedup": dense_ms / flash_ms}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true", help="small sizes (CI/verify)")
    ap.add_argument("--claims", type=int, default=None)
    ap.add_argument("--shape", default="tpu-v5e-8")
    ap.add_argument("--no-tpu", action="store_true",
                    help="skip the workload timing (control plane only)")
    args = ap.parse_args(argv)
    n = args.claims or (16 if args.fast else 64)

    prov = asyncio.run(bench_provisioning(n, args.shape))
    extra = {k: round(v, 4) if isinstance(v, float) else v
             for k, v in prov.items() if k != "p50_s"}
    if not args.no_tpu:
        try:
            extra["workload"] = {k: round(v, 2) if isinstance(v, float) else v
                                 for k, v in bench_workload(args.fast).items()}
            extra["flash_attention"] = {
                k: round(v, 2) if isinstance(v, float) else v
                for k, v in bench_flash_op(args.fast).items()}
        except Exception as e:  # no usable accelerator — control plane still counts
            extra["workload_error"] = f"{type(e).__name__}: {e}"

    p50 = prov["p50_s"]
    print(json.dumps({
        "metric": "nodeclaim_ready_p50",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(p50 / BASELINE_READY_BOUND_S, 6),
        "extra": extra,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
