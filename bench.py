"""Benchmark: provisioning throughput of the full control plane.

Drives N NodeClaims through the REAL controller set (launch → registration →
initialization → Ready) against the simulated cloud (envtest), then — when an
accelerator is attached — times the flagship workload's forward step on it.

Prints ONE JSON line:
  {"metric": "nodeclaim_ready_p50", "value": <sec>, "unit": "s",
   "vs_baseline": <value/600>, "extra": {...}}

Baseline semantics: the reference encodes NO published numbers (BASELINE.md);
its only hard bound on NodeClaim→Ready is the 10-min e2e Eventually timeout
(reference test/e2e/pkg/environment/common/environment.go:67). vs_baseline is
p50/600s — lower is better. ``extra`` carries the other BASELINE.json
headline metrics (reconcile QPS, TPU chips/min) plus workload tokens/s.

Usage: python bench.py [--fast] [--claims N] [--shape tpu-v5e-8] [--no-tpu]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import statistics
import sys
import time

BASELINE_READY_BOUND_S = 600.0  # reference e2e Eventually timeout


def _p99(samples: list) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, math.ceil(0.99 * len(s)) - 1)]


async def bench_provisioning(n_claims: int, shape: str,
                             n_grouped: int = 64,
                             group_size: int = 8) -> dict:
    """Wave of n_claims through the full controller set; the first
    ``n_grouped`` claims form slice-groups of ``group_size`` (multi-slice
    identity assignment racing inside the wave — VERDICT r3 asks the
    grouped path to survive fleet concurrency with no p99 regression)."""
    from gpu_provisioner_tpu import catalog
    from gpu_provisioner_tpu.apis import labels as wk
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim

    from gpu_provisioner_tpu.apis.karpenter import NodeClaim

    # Concurrency at the reference's regime: lifecycle runs 1000-5000
    # CPU-scaled concurrent reconciles (lifecycle/controller.go:56-58).
    # GC at a calmer cadence than the unit-test default: at fleet scale each
    # GC cycle enumerates every pool, and a 0.2s loop competes with the wave.
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    # Requeue cadence at fleet scale: registration is EVENT-driven (Node
    # watch → owning claim), so the periodic requeue is a safety net, not
    # the latency path — 1.0s keeps the steady reconcile load at ~1×claims
    # per second. 0.25s (4 Hz × 1024 claims ≈ 4k reconciles/s of pure
    # polling) saturated the loop and tipped node-waits into a retry storm.
    # Node-wait budget 12s: at 1024-concurrency the fake cloud's join tasks
    # queue behind the wave; a 6s budget made misses (→ CreateError → full
    # retry) self-amplifying.
    opts = EnvtestOptions(create_latency=0.05, node_join_delay=0.02,
                          node_ready_delay=0.02, gc_interval=2.0,
                          leak_grace=2.0, node_wait_attempts=600,
                          lifecycle=LifecycleOptions(
                              termination_requeue=1.0,
                              registration_requeue=1.0),
                          termination=TerminationOptions(
                              requeue=1.0, instance_requeue=1.0),
                          max_concurrent_reconciles=2048,
                          use_informer=True,
                          # measurement harness at deliberate saturation:
                          # scheduling-latency spikes are the thing being
                          # measured, not a defect — keep the leak gate,
                          # drop the stall gate
                          stall_budget=0.0)
    resolved = catalog.lookup(shape)
    if resolved is None:
        raise SystemExit(f"unknown TPU shape {shape!r} (try tpu-v5e-8, v5p-32)")
    n_grouped = min(n_grouped, n_claims)
    async with Env(opts) as env:

        def claim(i: int):
            labels = ({wk.TPU_SLICE_GROUP_LABEL: f"bg{i // group_size}"}
                      if i < n_grouped else None)
            return make_nodeclaim(f"bench{i}", shape, workspace=f"ws{i}",
                                  labels=labels)

        async def provision(i: int) -> float:
            # per-claim latency stamped at actual readiness, not loop arrival
            t_create = time.perf_counter()
            await env.client.create(claim(i))
            await env.wait_ready(f"bench{i}", timeout=300, poll=0.25)
            return time.perf_counter() - t_create

        t0 = time.perf_counter()
        readies = await asyncio.gather(*(provision(i) for i in range(n_claims)))
        elapsed = time.perf_counter() - t0
        informer_objects = env.informer_cache_sizes()

        # grouped-identity sanity: every group's indices distinct + gap-free
        collisions = 0
        for g in range(n_grouped // group_size):
            idxs = sorted(
                int(p.config.labels.get(wk.TPU_SLICE_INDEX_LABEL, -1))
                for p in env.cloud.nodepools.pools.values()
                if p.config.labels.get(wk.TPU_SLICE_GROUP_LABEL) == f"bg{g}")
            if idxs != list(range(group_size)):
                collisions += 1

        # Steady-state write churn must stay ZERO at full fleet size: a no-op
        # reconcile that rewrites status would show up here as rv churn (and
        # in production as a self-sustaining watch->reconcile hot loop).
        async def rvs():
            return {c.metadata.name: c.metadata.resource_version
                    for c in await env.client.list(NodeClaim)}
        before = await rvs()
        await asyncio.sleep(1.0)
        after = await rvs()
        churn = sum(1 for k in before if after.get(k) != before[k])
    out = {
        "p50_s": statistics.median(readies),
        "p99_s": _p99(readies),
        "reconcile_qps": n_claims / elapsed,
        "chips_per_min": n_claims * resolved.chips / (elapsed / 60.0),
        "elapsed_s": elapsed,
        "claims": n_claims,
        "steady_rv_writes": churn,
        "informer_cached_objects": informer_objects,
    }
    if n_grouped:
        out.update({
            "grouped_claims": n_grouped,
            "grouped_p99_s": _p99(readies[:n_grouped]),
            "grouped_index_collisions": collisions,
        })
    return out


def bench_workload(fast: bool) -> dict:
    """Forward-step throughput of the flagship model on the attached device."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params
    from gpu_provisioner_tpu.models.train import make_forward

    dev = jax.devices()[0]
    # dense attention here: the pallas-kernel-per-layer scan compiles slowly
    # over the remote-compile tunnel; the flash kernel gets its own op-level
    # timing in bench_flash_op where compile cost is one kernel.
    cfg = (LlamaConfig(vocab_size=2048, dim=512, n_layers=4, n_heads=8,
                       n_kv_heads=4, hidden_dim=1408, dtype="bfloat16")
           if fast else
           LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
                       n_kv_heads=8, hidden_dim=5504, dtype="bfloat16"))
    B, S = (4, 512) if fast else (8, 1024)
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    tokens = jax.device_put(jnp.zeros((B, S), jnp.int32), dev)
    fwd = make_forward(cfg)

    def settle(x):
        # On tunneled/experimental platforms block_until_ready can return
        # before execution completes; a scalar host read cannot.
        x.block_until_ready()
        return float(x[0, 0, 0])

    for _ in range(3):                               # compile + settle queue
        settle(fwd(params, tokens))
    iters = 10
    best = float("inf")
    for _ in range(3):                               # best-of-3 against jitter
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fwd(params, tokens)
        settle(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return {"platform": dev.platform, "tokens_per_s": B * S / best,
            "step_ms": best * 1e3}


# bf16 peak FLOP/s per chip by TPU generation (public spec sheets); MFU is
# reported against the attached chip's peak.
_PEAK_BF16 = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
              "v5p": 459e12, "v6 lite": 918e12, "v6e": 918e12}


def _chip_peak(dev) -> float:
    kind = getattr(dev, "device_kind", "").lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            return peak
    return _PEAK_BF16["v5e"]  # conservative default


def _train_flops(params, cfg, batch: int, seq: int) -> float:
    """Model FLOPs per train step (fwd+bwd ≈ 3× fwd): 6·P per token for the
    matmuls + causal attention scores/values (2·B·S²·H·Dh fwd, ×3)."""
    import jax

    n_params = sum(x.size for x in jax.tree.leaves(params))
    matmul = 6.0 * n_params * batch * seq
    # attention: QK^T + PV are 2 matmuls -> 4*B*S^2*H*Dh fwd, x3 with the
    # backward = 12x; causal halves the live square
    attn = 12.0 * batch * seq * seq * cfg.n_heads * cfg.head_dim * \
        cfg.n_layers * 0.5
    return matmul + attn


def bench_train_step(fast: bool) -> dict:
    """Full train step (forward + backward + adamw update) with the Pallas
    flash kernel + remat — the north-star workload — and its MFU."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.llama import LlamaConfig
    from gpu_provisioner_tpu.models.train import (BATCH_SPEC, make_train_state,
                                                  make_train_step)
    from gpu_provisioner_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding

    dev = jax.devices()[0]
    # Pallas interpret mode (CPU) is far too slow for a whole train step;
    # the kernel path only engages on a real TPU backend.
    impl = "flash" if jax.default_backend() in ("tpu", "axon") else "dense"
    cfg = (LlamaConfig(vocab_size=2048, dim=512, n_layers=4, n_heads=8,
                       n_kv_heads=4, hidden_dim=1408, dtype="bfloat16",
                       attn_impl=impl, remat=True)
           if fast else
           LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
                       n_kv_heads=8, hidden_dim=5504, dtype="bfloat16",
                       attn_impl=impl, remat=True))
    B, S = (4, 512) if fast else (8, 2048)
    mesh = make_mesh(1, devices=[dev])
    # Adam first moment in bf16: the ~1B model + f32 AdamW overflows a v5e
    # chip's 16G HBM by ~0.6G; bf16 mu buys 1.7G with no step-time cost.
    from gpu_provisioner_tpu.models.train import default_optimizer
    opt = default_optimizer(mu_dtype=jnp.bfloat16)
    params, opt_state, opt = make_train_state(jax.random.key(0), cfg, mesh,
                                              optimizer=opt)
    step = make_train_step(mesh, cfg, opt)
    toks = jax.random.randint(jax.random.key(1), (B, S + 1), 0, cfg.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    inp, tgt = put(toks[:, :-1]), put(toks[:, 1:])

    def settle(loss):
        loss.block_until_ready()
        return float(loss)

    for _ in range(2):                               # compile + settle
        params, opt_state, loss = step(params, opt_state, inp, tgt)
        settle(loss)
    iters = 5
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss = step(params, opt_state, inp, tgt)
        settle(loss)
        best = min(best, (time.perf_counter() - t0) / iters)

    flops = _train_flops(params, cfg, B, S)
    mfu = flops / best / _chip_peak(dev)
    return {"platform": dev.platform, "batch": B, "seq_len": S,
            "step_ms": best * 1e3, "tokens_per_s": B * S / best, "mfu": mfu}


def bench_long_context(fast: bool) -> dict:
    """Flash + remat trains at S=8192 on one chip, where dense recompute
    cannot (the S² score matrix alone is 2.1 GB/head-batch in f32)."""
    import jax
    from gpu_provisioner_tpu.models.llama import LlamaConfig
    from gpu_provisioner_tpu.models.train import (BATCH_SPEC, make_train_state,
                                                  make_train_step)
    from gpu_provisioner_tpu.parallel import make_mesh
    from jax.sharding import NamedSharding

    dev = jax.devices()[0]
    impl = "flash" if jax.default_backend() in ("tpu", "axon") else "dense"
    S = 2048 if fast else 8192
    cfg = LlamaConfig(vocab_size=2048, dim=1024, n_layers=4, n_heads=8,
                      n_kv_heads=4, hidden_dim=2816, max_seq_len=S,
                      dtype="bfloat16", attn_impl=impl, remat=True)
    mesh = make_mesh(1, devices=[dev])
    params, opt_state, opt = make_train_state(jax.random.key(0), cfg, mesh)
    step = make_train_step(mesh, cfg, opt)
    toks = jax.random.randint(jax.random.key(1), (1, S + 1), 0, cfg.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    inp, tgt = put(toks[:, :-1]), put(toks[:, 1:])

    def time_step(step, params, opt_state, inp, tgt):
        # TWO warm steps: donation changes the arg layouts after the first
        # call, which triggers a second compile — timing step 2 would
        # measure it.
        for _ in range(2):
            params, opt_state, loss = step(params, opt_state, inp, tgt)
            loss.block_until_ready()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            params, opt_state, loss = step(params, opt_state, inp, tgt)
            loss.block_until_ready()
            float(loss)
            best = min(best, time.perf_counter() - t0)
        return best

    out = {"seq_len": S,
           "step_ms": time_step(step, params, opt_state, inp, tgt) * 1e3}

    if impl == "flash":
        # Mistral-style SWA training: the windowed kernels prune fwd+bwd
        # to the window band, so step time scales with S·window, not S² —
        # the regime where windowed models TRAIN at context lengths the
        # full causal kernel pays quadratically for. S×4 = 32k in the full
        # run: a context no dense attention can even compile on one chip
        # (the 32k² f32 score matrix is 4 GB/head) — the windowed step time
        # stands as a beats-reference-class datapoint on its own (VERDICT
        # r4 item 8). Flash-only: the dense window mask still builds the
        # S² score matrix, so there is nothing meaningful to measure
        # off-TPU.
        import dataclasses
        S2 = S * 4
        cfg_w = dataclasses.replace(cfg, max_seq_len=S2,
                                    sliding_window=1024)
        params, opt_state, opt = make_train_state(jax.random.key(0), cfg_w,
                                                  mesh)
        step = make_train_step(mesh, cfg_w, opt)
        toks = jax.random.randint(jax.random.key(1), (1, S2 + 1), 0,
                                  cfg_w.vocab_size)
        out["swa_seq_len"] = S2
        out["swa_window"] = cfg_w.sliding_window
        out["swa_step_ms"] = time_step(step, params, opt_state,
                                       put(toks[:, :-1]),
                                       put(toks[:, 1:])) * 1e3
    return out


def bench_decode(fast: bool) -> dict:
    """Serving throughput: prefill latency + cached-decode tokens/s on the
    ~1B model (batch decode, greedy)."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.decode import generate
    from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params

    dev = jax.devices()[0]
    # attn_impl="flash": the deployment configuration — prefill takes the
    # cache-aware Pallas kernel (S0 tiles) and S=1 decode steps take the
    # decode kernel (flash_attention_decode: O(start) cache traffic)
    cfg = (LlamaConfig(vocab_size=2048, dim=512, n_layers=4, n_heads=8,
                       n_kv_heads=4, hidden_dim=1408, dtype="bfloat16",
                       attn_impl="flash")
           if fast else
           LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
                       n_kv_heads=8, hidden_dim=5504, dtype="bfloat16",
                       attn_impl="flash"))
    # fast S0=128 so the flash prefill actually engages (blocks need >=128)
    B, S0, NEW = (2, 128, 16) if fast else (8, 512, 128)
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    prompt = jax.device_put(
        jnp.zeros((B, S0), jnp.int32), dev)

    gen = jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=NEW))

    def settle(x):
        x.block_until_ready()
        return int(x[0, 0])

    settle(gen(params, prompt))                       # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = gen(params, prompt)
        settle(out)
        best = min(best, time.perf_counter() - t0)

    # sampled mode: the standard serving configuration (temperature +
    # top-k + nucleus) — the filters run on-device inside the scan
    gen_s = jax.jit(lambda p, t, k: generate(
        p, t, cfg, max_new_tokens=NEW, temperature=0.8, top_k=50,
        top_p=0.95, key=k))
    skey = jax.random.key(1)
    settle(gen_s(params, prompt, skey))               # compile
    best_s = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = gen_s(params, prompt, skey)
        settle(out)
        best_s = min(best_s, time.perf_counter() - t0)
    out = {"batch": B, "prompt_len": S0, "new_tokens": NEW,
           "total_ms": best * 1e3,
           "decode_tokens_per_s": B * NEW / best,
           "sampled_total_ms": best_s * 1e3,
           "decode_tokens_per_s_sampled": B * NEW / best_s}

    # serving-budget shape: a production server pre-allocates the cache at
    # its context budget, not at prompt+new — this is where the decode
    # kernel's O(start) DMA bound beats the dense sweep's O(max_len), and
    # where flash vs dense decode is an HONEST comparison (same budget)
    ML = 1024 if fast else 4096
    import dataclasses
    for impl in ("flash", "dense"):
        cfg_i = dataclasses.replace(cfg, attn_impl=impl)
        gen_b = jax.jit(lambda p, t, c=cfg_i: generate(
            p, t, c, max_new_tokens=NEW, max_len=ML))
        settle(gen_b(params, prompt))                 # compile
        best_b = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            o = gen_b(params, prompt)
            settle(o)
            best_b = min(best_b, time.perf_counter() - t0)
        out[f"budget{ML}_{impl}_total_ms"] = best_b * 1e3
        out[f"budget{ML}_{impl}_tokens_per_s"] = B * NEW / best_b
    return out


def bench_speculative(fast: bool) -> dict:
    """Speculative decoding round-trip cost with a SELF-draft (draft ==
    target ⇒ every proposal accepted): the measured tokens/s is the
    acceptance UPPER BOUND — real deployments sit between this and plain
    decode depending on draft quality. What this times on silicon: the
    k-step draft scan, the wide verify call, and the rollback plumbing."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params
    from gpu_provisioner_tpu.models.speculative import speculative_generate

    dev = jax.devices()[0]
    cfg = (LlamaConfig(vocab_size=2048, dim=512, n_layers=4, n_heads=8,
                       n_kv_heads=4, hidden_dim=1408, dtype="bfloat16")
           if fast else
           LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
                       n_kv_heads=8, hidden_dim=5504, dtype="bfloat16"))
    S0, NEW, K = (64, 16, 3) if fast else (256, 96, 4)
    params = jax.device_put(init_params(jax.random.key(0), cfg), dev)
    prompt = jax.device_put(jnp.zeros((1, S0), jnp.int32), dev)
    f = jax.jit(lambda p, t: speculative_generate(
        p, p, t, cfg, cfg, max_new_tokens=NEW, spec_k=K))

    def settle(r):
        toks, stats = r
        toks.block_until_ready()
        return int(toks[0, 0]), int(stats["target_calls"])

    _, calls = settle(f(params, prompt))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = f(params, prompt)
        settle(r)
        best = min(best, time.perf_counter() - t0)
    out = {"new_tokens": NEW, "spec_k": K, "target_calls": calls,
           "total_ms": best * 1e3, "tokens_per_s_upper_bound": NEW / best}

    # batched speculation (per-row acceptance lengths): the serving-shaped
    # variant — B rows speculate concurrently, draft steps take the
    # per-row-start decode kernel
    Bb = 2 if fast else 8
    promptb = jax.device_put(jnp.zeros((Bb, S0), jnp.int32), dev)
    settle(f(params, promptb))     # same jitted fn; new shape → new program
    best_b = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = f(params, promptb)
        settle(r)
        best_b = min(best_b, time.perf_counter() - t0)
    out.update({"batch": Bb, "batched_total_ms": best_b * 1e3,
                "batched_tokens_per_s_upper_bound": Bb * NEW / best_b})
    return out


def bench_moe_decode(fast: bool) -> dict:
    """MoE-family serving throughput (models/moe_serve.py): greedy batch
    decode on a Mixtral-style config — top-2 of 8 experts, so ~2/8 of the
    FFN weights activate per token while all experts' weights sit in HBM
    (the serving economics MoE buys)."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.decode import generate
    from gpu_provisioner_tpu.models.moe import MoEConfig, init_moe_model

    dev = jax.devices()[0]
    cfg = (MoEConfig(vocab_size=2048, dim=256, n_layers=2, n_heads=8,
                     n_kv_heads=4, hidden_dim=512, n_experts=4,
                     experts_per_token=2, dtype="bfloat16",
                     attn_impl="flash")
           if fast else
           MoEConfig(vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
                     n_kv_heads=8, hidden_dim=2816, n_experts=8,
                     experts_per_token=2, dtype="bfloat16",
                     attn_impl="flash"))
    B, S0, NEW = (2, 128, 16) if fast else (8, 512, 128)
    params = jax.device_put(init_moe_model(jax.random.key(0), cfg), dev)
    prompt = jax.device_put(jnp.zeros((B, S0), jnp.int32), dev)
    gen = jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=NEW))

    def settle(x):
        x.block_until_ready()
        return int(x[0, 0])

    settle(gen(params, prompt))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = gen(params, prompt)
        settle(out)
        best = min(best, time.perf_counter() - t0)
    return {"batch": B, "prompt_len": S0, "new_tokens": NEW,
            "n_experts": cfg.n_experts, "total_ms": best * 1e3,
            "decode_tokens_per_s": B * NEW / best}


def bench_engine(fast: bool) -> dict:
    """Continuous batching vs static batching on a ragged request mix.
    The engine admits a stream of requests with varying prompt/generation
    lengths into slot rows (per-row-start decode kernel); the static
    baseline serves the same mix in slot-sized generate() batches, each
    padded to its batch's max prompt and max_new — the coupling
    continuous batching exists to remove."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.engine import ServeEngine
    from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params
    from gpu_provisioner_tpu.models.decode import generate

    cfg = (LlamaConfig(vocab_size=2048, dim=256, n_layers=2, n_heads=8,
                       n_kv_heads=4, hidden_dim=512, dtype="bfloat16",
                       attn_impl="flash")
           if fast else
           LlamaConfig(vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
                       n_kv_heads=8, hidden_dim=5504, dtype="bfloat16",
                       attn_impl="flash"))
    params = init_params(jax.random.key(0), cfg)
    slots, ML = (2, 512) if fast else (8, 2048)
    N = 6 if fast else 24
    rng = jax.random.split(jax.random.key(1), N)
    lens = [int(64 + 64 * (i % 3)) for i in range(N)]          # ragged
    news = [int(8 + 8 * (i % 4)) if fast else int(16 + 16 * (i % 4))
            for i in range(N)]
    # tokens start at 1: the static baseline infers pads via pad_id=0, so
    # a genuine leading 0 would be misread as padding there
    prompts = [jax.random.randint(rng[i], (lens[i],), 1,
                                  cfg.vocab_size).tolist()
               for i in range(N)]

    # ONE engine for warm-up and timing: its jitted closures live on the
    # instance, so a fresh engine would recompile everything in the timed
    # pass; after run() drains, all slots are free for resubmission
    eng = ServeEngine(params, cfg, slots=slots, max_len=ML,
                      prefill_buckets=(64, 128, 256))

    def run_engine():
        for p, n in zip(prompts, news):
            eng.submit(p, n)
        out = dict(eng.run())      # copy — run() returns the live dict
        eng.finished.clear()
        return out

    run_engine()                                   # compile (all buckets)
    t0 = time.perf_counter()
    out = run_engine()
    dt_engine = time.perf_counter() - t0
    total = sum(len(v) for v in out.values())

    # jitted per distinct (width, new) batch shape — the static side gets
    # the same compiled-program treatment as the engine's jitted closures
    import functools

    @functools.lru_cache(maxsize=None)
    def static_fn(w, new):
        return jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=new,
                                             max_len=ML, pad_id=0))

    def run_static():
        done = 0
        for i in range(0, N, slots):
            batch = list(range(i, min(i + slots, N)))
            w = max(lens[b] for b in batch)
            new = max(news[b] for b in batch)
            toks = jnp.asarray([[0] * (w - lens[b]) + prompts[b]
                                for b in batch], jnp.int32)
            o = static_fn(w, new)(params, toks)
            o.block_until_ready()
            done += sum(min(new, news[b]) for b in batch)
        return done

    run_static()                                   # compile
    t0 = time.perf_counter()
    done = run_static()
    dt_static = time.perf_counter() - t0

    # continuous batching × speculation with a SELF-draft: acceptance is
    # 100%, so this isolates the speculation PLUMBING cost (draft scan +
    # wide verify + rollback) at full acceptance — NOT a speedup bound:
    # the self-draft pays full target cost per draft step, so a real
    # (cheap) draft with good acceptance beats this ratio, and a ratio
    # near spec-cost parity means the machinery itself is lean
    eng_s = ServeEngine(params, cfg, slots=slots, max_len=ML,
                        prefill_buckets=(64, 128, 256),
                        draft_params=params, draft_cfg=cfg, spec_k=3)

    def run_spec():
        for p, n in zip(prompts, news):
            eng_s.submit(p, n)
        out = dict(eng_s.run())
        eng_s.finished.clear()
        return out

    run_spec()                                     # compile
    t0 = time.perf_counter()
    out_s = run_spec()
    dt_spec = time.perf_counter() - t0
    total_s = sum(len(v) for v in out_s.values())

    # prefix caching: the same request mix behind a SHARED system prompt,
    # prefilled once + LRU-reused vs re-prefilled per request
    PFX = 128 if fast else 512
    prefix = jax.random.randint(jax.random.key(2), (PFX,), 1,
                                cfg.vocab_size).tolist()
    eng_c = ServeEngine(params, cfg, slots=slots, max_len=ML,
                        prefill_buckets=(64, 128, 256, PFX))
    # fair buckets for the uncached side: same granularity shifted by the
    # prefix, so the comparison isolates prefix caching (not padding
    # waste from one coarse bucket)
    eng_u = ServeEngine(params, cfg, slots=slots, max_len=ML,
                        prefill_buckets=(PFX + 64, PFX + 128, PFX + 256))

    def run_prefix(eng, cached):
        for p, n in zip(prompts, news):
            if cached:
                eng.submit(p, n, prefix=prefix)
            else:
                eng.submit(prefix + p, n)
        out = dict(eng.run())
        eng.finished.clear()
        return out

    run_prefix(eng_c, True), run_prefix(eng_u, False)   # compile
    t0 = time.perf_counter()
    run_prefix(eng_c, True)
    dt_pc = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_prefix(eng_u, False)
    dt_pu = time.perf_counter() - t0
    return {"requests": N, "slots": slots,
            "engine_tokens": total, "engine_ms": dt_engine * 1e3,
            "engine_tokens_per_s": total / dt_engine,
            "static_ms": dt_static * 1e3,
            "static_tokens_per_s": done / dt_static,
            "speedup_vs_static": (total / dt_engine) / (done / dt_static),
            "spec_engine_selfdraft_ms": dt_spec * 1e3,
            "spec_engine_selfdraft_tokens_per_s": total_s / dt_spec,
            "spec_selfdraft_cost_ratio": (total_s / dt_spec)
                                         / (total / dt_engine),
            "prefix_len": PFX,
            "prefix_cached_ms": dt_pc * 1e3,
            "prefix_uncached_ms": dt_pu * 1e3,
            "prefix_cache_speedup": dt_pu / dt_pc}


def bench_flash_op(fast: bool) -> dict:
    """Pallas flash-attention kernel vs the dense lax path, one op."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.ops import flash_attention
    from gpu_provisioner_tpu.parallel.ring import dense_attention

    B, S, Hq, Hkv, D = (4, 1024, 8, 4, 128) if fast else (8, 4096, 16, 8, 128)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, S, Hkv, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, S, Hkv, D), jnp.bfloat16)

    def settle(x):
        x.block_until_ready()
        return float(x[0, 0, 0, 0])

    def timeit(fn, settle_fn=settle):
        f = jax.jit(fn)
        settle_fn(f(q, k, v))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(5):
                out = f(q, k, v)
            settle_fn(out)
            best = min(best, (time.perf_counter() - t0) / 5)
        return best * 1e3

    flash_ms = timeit(lambda *a: flash_attention(*a))
    dense_ms = timeit(lambda *a: dense_attention(*a))
    out = {"seq_len": S, "flash_ms": flash_ms, "dense_ms": dense_ms,
           "flash_speedup": dense_ms / flash_ms}

    # fwd+bwd: the training path (per-block-recompute Pallas backward vs
    # dense autodiff) — round-3's 4.6× claim, driver-re-verifiable here
    def vjp_of(attn):
        def f(*a):
            return jnp.sum(attn(*a).astype(jnp.float32) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))

    def settle_g(g):
        g[0].block_until_ready()
        return float(g[0][0, 0, 0, 0])

    try:
        out["flash_fwdbwd_ms"] = timeit(vjp_of(flash_attention), settle_g)
        out["dense_fwdbwd_ms"] = timeit(vjp_of(dense_attention), settle_g)
        out["flash_fwdbwd_speedup"] = (out["dense_fwdbwd_ms"]
                                       / out["flash_fwdbwd_ms"])
    except Exception as e:
        out["fwdbwd_error"] = f"{type(e).__name__}: {e}"

    if not fast:
        # STREAMING variant (K/V past the VMEM residency budget): S=32k is
        # where the causal dead-block DMA elision pays (~2x K/V traffic at
        # long S) — no dense reference (a 32k^2 score matrix won't fit),
        # so the ms stands alone for round-over-round comparison.
        S2 = 32768
        ks2 = jax.random.split(jax.random.key(1), 3)
        q2 = jax.random.normal(ks2[0], (1, S2, 8, 128), jnp.bfloat16)
        k2 = jax.random.normal(ks2[1], (1, S2, 4, 128), jnp.bfloat16)
        v2 = jax.random.normal(ks2[2], (1, S2, 4, 128), jnp.bfloat16)

        def time_jitted(fn):
            f = jax.jit(fn)
            settle(f(q2, k2, v2))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                o = f(q2, k2, v2)
                settle(o)
                best = min(best, time.perf_counter() - t0)
            return best * 1e3

        out["streaming_seq_len"] = S2
        out["streaming_ms"] = time_jitted(
            lambda a, b, c: flash_attention(a, b, c))
        try:
            # triangular grid (opt-in, first on-chip validation happens
            # right here): own guard so a lowering failure records an
            # error instead of killing the section
            out["streaming_tri_ms"] = time_jitted(
                lambda a, b, c: flash_attention(a, b, c, triangular=True))
        except Exception as e:
            out["streaming_tri_error"] = f"{type(e).__name__}: {e}"
    return out


def bench_cached_prefill(fast: bool) -> dict:
    """Prefill continuation (multi-turn serving): the cache-aware flash
    kernel vs the dense S×max_len masked sweep it replaces. Two regimes:
    a HALF-FULL cache (the round-3/4 headline — weakest case: the kernel
    still sweeps most of the budget) and a SMALL-PREFIX cache (short
    history, big budget — the structural O(start+S) vs O(max_len) win;
    VERDICT r4 item 8)."""
    import jax
    import jax.numpy as jnp
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import (
        cached_flash_supported, flash_attention_cached)

    B, S, ML, Hq, Hkv, D = ((2, 256, 2048, 8, 4, 128) if fast
                            else (4, 512, 8192, 16, 8, 128))
    assert cached_flash_supported(S, ML, Hq, Hkv)
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D), jnp.bfloat16)
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D), jnp.bfloat16)
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D), jnp.bfloat16)
    scale = D ** -0.5

    def settle(x):
        x.block_until_ready()
        return float(x[0, 0, 0, 0])

    def timeit(fn, start):
        f = jax.jit(fn)
        settle(f(q, kc, vc, start))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(5):
                o = f(q, kc, vc, start)
            settle(o)
            best = min(best, (time.perf_counter() - t0) / 5)
        return best * 1e3

    flash = lambda a, b, c, s: flash_attention_cached(a, b, c, s,
                                                      scale=scale)
    dense = lambda a, b, c, s: _cached_attention(a, b, c, s, scale)
    out = {"new_tokens": S, "cache_len": ML}
    for tag, st in (("", ML // 2), ("small_prefix_", ML // 16)):
        start = jnp.asarray(st, jnp.int32)
        f_ms = timeit(flash, start)
        d_ms = timeit(dense, start)
        out.update({f"{tag}start": st, f"{tag}flash_ms": f_ms,
                    f"{tag}dense_ms": d_ms,
                    f"{tag}flash_speedup": d_ms / f_ms})
    return out


# --- TPU section runner (capture-first, kill-free) -------------------------
#
# Round-4 post-mortem (BENCH_NOTES_r04 caveat 3): a timeout-killed process
# that had attached the tunneled TPU backend wedged the REMOTE server for
# the rest of the round, and the old subprocess probe (subprocess.run with
# timeout=) was exactly that hazard. The on-chip sections also ran AFTER
# the ~15s control-plane wave, so a wedge mid-run lost everything.
#
# This design fixes both:
#   * the TPU sections run FIRST, in a DETACHED child process that appends
#     one JSON line per section to bench_tpu_sections.jsonl as it goes —
#     whatever completed before a wedge is already on disk;
#   * the parent polls that file and, if the child goes silent past the
#     inactivity budget, LEAVES IT RUNNING (an orphan that eventually
#     attaches is harmless; killing it is the documented wedge trigger),
#     keeps the captured sections, and proceeds to the control plane — the
#     final JSON line is guaranteed either way;
#   * there is no separate attach-probe to kill: the child's first output
#     line (after jax.devices() returns) IS the liveness signal.

TPU_SECTIONS_PATH = "bench_tpu_sections.jsonl"

# Ordering: first numbers for the never-measured kernels first (decode-step
# kernel + serving budget, refactored backward, MoE serving, speculative,
# 32k SWA training), then the established headliners (MFU, prefill, fwd).
def _tpu_sections():
    return [
        ("decode", bench_decode, 2),
        ("flash_attention", bench_flash_op, 2),
        ("moe_decode", bench_moe_decode, 2),
        ("speculative", bench_speculative, 2),
        ("engine", bench_engine, 2),
        ("long_context", bench_long_context, 2),
        ("train", bench_train_step, 4),
        ("prefill_cached", bench_cached_prefill, 2),
        ("workload", bench_workload, 2),
    ]


def _rounded(d, nd=2):
    return {k: round(v, nd) if isinstance(v, float) else v
            for k, v in d.items()}


def run_tpu_child(fast: bool, out_path: str) -> int:
    """Child-process entry (--tpu-child): attach the accelerator, then run
    every TPU section, appending one JSON line per section to out_path the
    moment it completes. Never killed by the parent — may outlive it."""
    def emit(rec):
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    import os
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # CI/smoke path: run the sections on host CPU without touching the
        # tunnel (the axon site hook otherwise initializes every backend on
        # the first jax.devices() call — tests/conftest.py's gotcha)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")
        from gpu_provisioner_tpu.parallel.topology import (
            drop_foreign_backend_factories)
        drop_foreign_backend_factories()
    import jax  # the attach happens here; a wedged tunnel hangs HERE,
    dev = jax.devices()[0]  # before any section line is written
    emit({"section": "_attach", "platform": dev.platform,
          "device": str(dev)})
    for name, fn, nd in _tpu_sections():
        try:
            emit({"section": name, "data": _rounded(fn(fast), nd)})
        except Exception as e:
            # recorded in-band; rc stays 0 — a nonzero exit means the
            # child DIED (segfault/OOM), which the parent reports
            emit({"section": name,
                  "error": f"{type(e).__name__}: {e}"})
    return 0


def run_tpu_sections(fast: bool, inactivity_budget_s: float = 900.0) -> dict:
    """Parent side: spawn the detached child, tail its section file, and
    assemble the ``extra`` sub-dicts. Budget counts SILENCE (time since the
    last completed section), not total runtime — remote first-compiles are
    slow but produce a line when done. On budget exhaustion the child is
    left running and the sections captured so far are returned."""
    import os
    import subprocess

    # per-run path: an orphan from a PREVIOUS run (left alive by design)
    # that later un-wedges must not append into this run's file
    path = f"{TPU_SECTIONS_PATH}.{os.getpid()}"
    cmd = [sys.executable, "-u", __file__, "--tpu-child", path]
    if fast:
        cmd.append("--fast")
    with open(path + ".log", "w") as log:
        proc = subprocess.Popen(cmd, stdout=log, stderr=log,
                                start_new_session=True)

    out: dict = {}
    n_seen = 0
    last_progress = time.monotonic()
    while True:
        exited = proc.poll() is not None   # check BEFORE the read: lines
        raw = ""                           # written pre-exit land in it
        try:
            with open(path) as f:
                raw = f.read()
        except FileNotFoundError:
            pass
        # only newline-terminated lines are complete; a torn trailing
        # fragment stays for the next poll
        complete = raw[:raw.rfind("\n") + 1].splitlines() if "\n" in raw \
            else []
        lines = [ln for ln in complete if ln.strip()]
        if len(lines) > n_seen:
            for ln in lines[n_seen:]:
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue               # torn/garbled line: skip it
                name = rec["section"]
                print(f"[bench] tpu section {name}: "
                      f"{'ok' if 'error' not in rec else rec['error']}",
                      file=sys.stderr, flush=True)
                if name == "_attach":
                    out["tpu_platform"] = rec["platform"]
                elif "error" in rec:
                    out[f"{name}_error"] = rec["error"]
                else:
                    out[name] = rec["data"]
            n_seen = len(lines)
            last_progress = time.monotonic()
        expected = 1 + len(_tpu_sections())          # _attach + sections
        if n_seen >= expected:
            break      # full coverage — don't wait out a teardown hang
        if exited and len(lines) == n_seen:
            if n_seen == 0:
                out["workload_error"] = (
                    f"tpu child exited rc={proc.returncode} before attach "
                    f"(see {path}.log)")
            else:   # n_seen < expected here (full coverage broke above)
                # died hard mid-suite (e.g. runtime segfault): surface it
                # instead of silently under-reporting coverage
                out.setdefault("workload_error", (
                    f"tpu child exited rc={proc.returncode} after "
                    f"{n_seen}/{expected} lines (see {path}.log)"))
            break
        if time.monotonic() - last_progress > inactivity_budget_s:
            # NEVER kill it: a killed backend-attached process wedges the
            # remote tunnel (round-4 post-mortem). Orphan it and move on.
            out["workload_error"] = (
                f"tpu child silent for {inactivity_budget_s:.0f}s after "
                f"{n_seen} section(s); left running un-killed (killing a "
                "backend-attached process wedges the tunnel)")
            break
        time.sleep(1.0)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true", help="small sizes (CI/verify)")
    ap.add_argument("--claims", type=int, default=None)
    ap.add_argument("--shape", default="tpu-v5e-8")
    ap.add_argument("--no-tpu", action="store_true",
                    help="skip the workload timing (control plane only)")
    ap.add_argument("--tpu-child", metavar="PATH", default=None,
                    help=argparse.SUPPRESS)  # internal: TPU-section child
    args = ap.parse_args(argv)
    if args.tpu_child:
        return run_tpu_child(args.fast, args.tpu_child)

    # TPU sections FIRST (capture-first): a tunnel that wedges mid-bench
    # must not cost the on-chip numbers already captured, and the control
    # plane (pure asyncio, no jax import) cannot wedge and always runs.
    extra: dict = {}
    if not args.no_tpu:
        # --fast (CI/smoke) bounds a hung attach at the old probe's 240s;
        # full runs keep the generous budget (remote first-compiles)
        extra.update(run_tpu_sections(
            args.fast, inactivity_budget_s=240.0 if args.fast else 900.0))

    # 1024 claims at 2048 concurrency = the reference lifecycle regime
    # (vendor lifecycle/controller.go:56-58); --fast keeps CI snappy
    n = args.claims or (16 if args.fast else 1024)
    prov = asyncio.run(bench_provisioning(n, args.shape))
    extra.update(_rounded({k: v for k, v in prov.items() if k != "p50_s"}, 4))
    if args.claims is None and not args.fast:
        # the scale point the driver record was missing (VERDICT r4 item
        # 6): the same wave at 2048 claims, single asyncio process — the
        # acknowledged ceiling regime. Above this, shard the controller
        # (BENCH_NOTES_r04); uvloop is not in the image.
        s = asyncio.run(bench_provisioning(2048, args.shape))
        extra["scale_2048"] = _rounded(
            {k: v for k, v in s.items()
             if k in ("p50_s", "p99_s", "reconcile_qps", "chips_per_min",
                      "elapsed_s", "steady_rv_writes")}, 4)

    p50 = prov["p50_s"]
    print(json.dumps({
        "metric": "nodeclaim_ready_p50",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(p50 / BASELINE_READY_BOUND_S, 6),
        "extra": extra,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
