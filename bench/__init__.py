"""Focused benchmark harnesses (one module per PR's perf claim).

``bench.py`` at the repo root stays the headline fleet-scale number; modules
here isolate a specific optimization with a before/after harness and write a
``BENCH_prNN.json`` record that ``make bench`` re-checks for regressions.
"""
