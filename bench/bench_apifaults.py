"""Catch-up-storm benchmark (PR 16): a mid-wave apiserver partition at the
100-claim reference scale, gated on degraded-mode invariants.

One harness, envtest + FakeCloud + ApiFaultInjector, no network: half the
wave launches, the apiserver partitions for ``--partition`` seconds while
the other half is created into the outage (their ADDED events die on the
dead watch stream), then the heal drives the informer gap-resync and the
governor's PARTITIONED→CATCHUP→HEALTHY exit. Gates:

- **convergence**: every claim Ready, every pool exists, zero claims lost.
- **zero duplicate creates**: admitted ``begin_create`` == claims (post-heal
  re-walks that 409-adopt a live pool are the safe at-least-once answer and
  do not count).
- **status writes** ≤ 3.0/claim: the widened shed window plus no-op
  suppression must absorb the stale-cache re-derivations.
- **timer wake share** ≤ 0.3 post-heal: the resync's synthesized events
  carry the catch-up wake load, not the workqueue safety net (steady-state
  PR 12 bound is 0.05; catch-up legitimately pays in-flight requeues).
- **partition fencing**: the schedfuzz ``partition-fenced-mutate`` checker
  replays the probe stream — no cloud mutation inside the fenced window.
- **flight recorder**: exactly one bundle per degraded mode entered.
- **wall budget**: 3× headroom over the recorded BENCH_pr16.json wall
  (scales with machine speed; catches a reintroduced convergence stall).

Usage: python -m bench.bench_apifaults [--gate] [--write]
                                       [--claims N] [--partition S]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

BENCH_PR16_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr16.json"

# PR 16 acceptance gates (criteria, not recorded budgets). The timer bound
# is the catch-up regime's, not PR 12's steady-state 0.05: claims born into
# the outage run their whole lifecycle post-heal, and their in-flight
# safety requeues race event delivery while the CATCHUP pace throttles the
# backlog (measured 0.04-0.21 across runs and scales; a resync that stops
# carrying the wake load lands near 1.0). Watch wakes must also outnumber
# timer wakes outright — see check_gates.
STATUS_WRITES_PER_CLAIM_MAX = 3.0
TIMER_WAKE_SHARE_MAX = 0.3
WALL_BUDGET_FACTOR = 3.0


async def catchup_storm(claims: int, partition: float, seed: int) -> dict:
    from gpu_provisioner_tpu.analysis.schedfuzz import (
        TraceRecorder, check_partition_fenced_mutate,
    )
    from gpu_provisioner_tpu.apis.karpenter import NodeClaim
    from gpu_provisioner_tpu.apis.meta import CONDITION_READY
    from gpu_provisioner_tpu.chaos import api_fault_profile
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim
    from gpu_provisioner_tpu.runtime import apihealth, probes
    from gpu_provisioner_tpu.runtime.apihealth import HEALTHY
    from gpu_provisioner_tpu.runtime.wakehub import SOURCE_TIMER, WAKES

    faults = api_fault_profile("apiserver_partition", seed=seed,
                               partition_start=0.6,
                               partition_duration=partition)
    opts = EnvtestOptions(api_faults=faults, use_informer=True,
                          node_ready_delay=0.3, node_join_delay=0.1,
                          gc_interval=0.25, leak_grace=0.25)
    opts.lifecycle.launch_timeout = max(60.0, partition * 3)
    opts.lifecycle.registration_timeout = max(60.0, partition * 3)
    names = [f"cu{i:04d}" for i in range(claims)]
    ledger_before = dict(apihealth.APIHEALTH)
    rec = TraceRecorder()
    probes.add_sink(rec)
    t0 = time.monotonic()
    try:
        async with Env(opts) as env:
            for n in names[: claims // 2]:
                await env.client.create(make_nodeclaim(n))
            while not faults.partition_active():
                await asyncio.sleep(0.02)
            for n in names[claims // 2:]:
                await env.client.create(make_nodeclaim(n))
            while faults.partition_active():
                await asyncio.sleep(0.1)
            wakes_at_heal = dict(WAKES)
            deadline = time.monotonic() + max(90.0, partition * 3)
            ready: set[str] = set()
            while ready != set(names):
                for n in set(names) - ready:
                    nc = await env.client.get(NodeClaim, n)
                    if nc.status_conditions.is_true(CONDITION_READY):
                        ready.add(n)
                if time.monotonic() > deadline:
                    raise SystemExit(
                        f"FAIL converge: {len(ready)}/{claims} ready")
                await asyncio.sleep(0.05)
            wall = time.monotonic() - t0
            gov = env.governor
            admitted = sum(
                v for k, v in env.cloud.nodepools.calls.items()
                if k.startswith("begin_create:"))
            bundle_modes = sorted(
                b["trigger"]["key"].split(":", 1)[1]
                for b in env.flight_recorder.bundles()
                if b["trigger"]["kind"] == "degraded-mode")
            delta = {k: WAKES.get(k, 0) - wakes_at_heal.get(k, 0)
                     for k in WAKES}
            post_heal_wakes = sum(delta.values())
            return {
                "claims": claims,
                "partition_s": partition,
                "seed": seed,
                "wall_s": round(wall, 3),
                "pools": len(env.cloud.nodepools.pools),
                "begin_creates_admitted": admitted,
                "status_writes": env.status_batcher.writes,
                "writes_per_claim": round(
                    env.status_batcher.writes / claims, 3),
                "shed_windows": env.status_batcher.shed_windows,
                "post_heal_wakes": post_heal_wakes,
                "timer_wake_share": round(
                    delta.get(SOURCE_TIMER, 0) / max(post_heal_wakes, 1),
                    4),
                "post_heal_wakes_by_source": delta,
                "governor": {
                    "entries_total": dict(gov.entries_total),
                    "throttles_total": gov.throttles_total,
                    "failures_total": gov.failures_total,
                },
                "degraded_modes_entered": sorted(
                    m for m in gov.entries_total if m != HEALTHY),
                "degraded_bundles": bundle_modes,
                "ledger": {k: apihealth.APIHEALTH[k] - ledger_before[k]
                           for k in apihealth.APIHEALTH},
                "fuzz_violations": [
                    f"{v.checker}@{v.seq}: {v.message}"
                    for v in check_partition_fenced_mutate(rec.events)],
            }
    finally:
        probes.remove_sink(rec)


def check_gates(run: dict) -> list[str]:
    fails: list[str] = []
    if run["pools"] != run["claims"]:
        fails.append(f"pools {run['pools']} != claims {run['claims']}")
    if run["begin_creates_admitted"] != run["claims"]:
        fails.append(
            f"duplicate pool creates: {run['begin_creates_admitted']} "
            f"admitted for {run['claims']} claims")
    if run["writes_per_claim"] > STATUS_WRITES_PER_CLAIM_MAX:
        fails.append(
            f"status-write storm: {run['writes_per_claim']}/claim > "
            f"{STATUS_WRITES_PER_CLAIM_MAX}")
    if run["timer_wake_share"] > TIMER_WAKE_SHARE_MAX:
        fails.append(
            f"catch-up timer share {run['timer_wake_share']} > "
            f"{TIMER_WAKE_SHARE_MAX} — the resync is not carrying the "
            f"wake load")
    by_source = run["post_heal_wakes_by_source"]
    if by_source.get("watch", 0) <= by_source.get("timer", 0):
        fails.append(
            f"watch wakes did not dominate the catch-up: {by_source}")
    if "PARTITIONED" not in run["degraded_modes_entered"]:
        fails.append("partition never tripped the governor")
    if "CATCHUP" not in run["degraded_modes_entered"]:
        fails.append("heal never entered CATCHUP")
    if run["degraded_bundles"] != run["degraded_modes_entered"]:
        fails.append(
            f"flight-recorder bundles {run['degraded_bundles']} != "
            f"degraded modes entered {run['degraded_modes_entered']}")
    if run["ledger"]["relists"] < 1:
        fails.append("heal produced no gap-resync relist")
    if run["fuzz_violations"]:
        fails.append("partition-fenced-mutate: "
                     + "; ".join(run["fuzz_violations"]))
    return fails


def check_budget(run: dict) -> list[str]:
    if not BENCH_PR16_FILE.exists():
        return []
    recorded = json.loads(BENCH_PR16_FILE.read_text())
    budget = recorded.get("budget", {})
    ceiling = budget.get("wall_s")
    if (ceiling is not None
            and run["claims"] == budget.get("claims")
            and run["partition_s"] == budget.get("partition_s")
            and run["wall_s"] > ceiling):
        return [f"catch-up wall regressed: {run['wall_s']}s > "
                f"{ceiling}s budget ({BENCH_PR16_FILE.name})"]
    return []


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--gate", action="store_true",
                    help="enforce the PR 16 gates + recorded wall budget")
    ap.add_argument("--write", action="store_true",
                    help=f"record the run as {BENCH_PR16_FILE.name}")
    ap.add_argument("--claims", type=int, default=100)
    ap.add_argument("--partition", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)

    run = asyncio.run(catchup_storm(args.claims, args.partition, args.seed))
    print(json.dumps(run, indent=2, sort_keys=True))

    fails = check_gates(run)
    if args.gate:
        fails += check_budget(run)
    if args.write and not fails:
        doc = {
            "bench": "apifaults-catchup-storm",
            "pr": 16,
            "reference": run,
            "gates": {
                "status_writes_per_claim_max": STATUS_WRITES_PER_CLAIM_MAX,
                "timer_wake_share_max": TIMER_WAKE_SHARE_MAX,
            },
            "budget": {
                "claims": run["claims"],
                "partition_s": run["partition_s"],
                "wall_s": round(WALL_BUDGET_FACTOR * run["wall_s"], 1),
            },
        }
        BENCH_PR16_FILE.write_text(json.dumps(doc, indent=2,
                                              sort_keys=True) + "\n")
        print(f"recorded {BENCH_PR16_FILE.name}")
    for f in fails:
        print(f"GATE FAIL: {f}", file=sys.stderr)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
