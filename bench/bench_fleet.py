"""fleetscope benchmark (PR 14): SLO-engine + flight-recorder overhead and
digest-memory flatness.

Two harnesses, both envtest + FakeCloud, no network:

- **overhead pairs**: the PR 9/PR 12 methodology verbatim — interleaved
  enabled/disabled PAIRS of a latency-bound 25-claim wave (tracing stays ON
  in both modes; only the fleet aggregator + flight recorder toggle),
  medians compared. The fleetscope tax per ready claim is one
  ``analyze_trace`` + a handful of digest increments, plus a frozenset test
  per probe emit — gated at ≤ 2% of wave wall.
- **reference wave**: the 100-claim BENCH_pr09 wave with fleetscope on;
  its ``/slo`` snapshot (fleet percentiles per placement key, objective
  burn state) and recorder stats are what ``--write-pr14`` records as
  ``BENCH_pr14.json``.

The digest-memory check is synthetic and exact: a ``LatencyDigest`` fed
100 vs 10 000 observations must have the identical bucket structure and
byte size — O(buckets) streaming state, the property that lets the SLO
engine outlive the 512-trace ring at mega-wave scale.

Usage: python -m bench.bench_fleet [--gate] [--claims N] [--repeats R]
                                   [--write-pr14]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from pathlib import Path

BENCH_PR14_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr14.json"

# Acceptance gates (criteria, not machine-scaled budgets).
PR14_OVERHEAD_MAX = 0.02
# Latency-bound wave size, reused from bench_provision's PR 9 overhead
# pairs: saturation quantizes the wall and measures the box, not the code.
OVERHEAD_CLAIMS = 25


async def bench_wave(n_claims: int, observability: bool = True) -> dict:
    """One claim wave with tracing always ON; ``observability`` toggles the
    fleet aggregator + flight recorder (the PR 14 delta under test)."""
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim

    opts = EnvtestOptions(
        create_latency=0.05, node_join_delay=0.01, node_ready_delay=0.01,
        gc_interval=1.0, leak_grace=1.0, node_wait_attempts=600,
        lifecycle=LifecycleOptions(termination_requeue=0.5,
                                   registration_requeue=0.5),
        termination=TerminationOptions(requeue=0.5, instance_requeue=0.5),
        max_concurrent_reconciles=1024, use_informer=True,
        tracing=True, trace_buffer=max(2 * n_claims, 64),
        fleet=observability, flight_recorder=observability,
        # measurement at saturation: stall gate off, leak gate stays on
        stall_budget=0.0)
    async with Env(opts) as env:
        async def provision(i: int) -> None:
            await env.client.create(make_nodeclaim(f"t{i:04d}", "tpu-v5e-8",
                                                   workspace=f"ws{i}"))
            await env.wait_ready(f"t{i:04d}", timeout=120, poll=0.1)

        wall0 = time.perf_counter()
        await asyncio.gather(*(provision(i) for i in range(n_claims)))
        ready_wall = time.perf_counter() - wall0

        slo = env.fleet.snapshot() if env.fleet is not None else None
        recorder = (env.flight_recorder.stats()
                    if env.flight_recorder is not None else None)
    return {
        "claims": n_claims,
        "observability": observability,
        "ready_wall_s": round(ready_wall, 3),
        "slo": slo,
        "recorder": recorder,
    }


def digest_memory_check() -> dict:
    """100 vs 10k observations into a LatencyDigest: identical structure,
    identical bytes — streaming state must not scale with claim count."""
    import sys as _sys

    from gpu_provisioner_tpu.observability.fleet import LatencyDigest

    def sized(n: int) -> tuple[dict, LatencyDigest]:
        d = LatencyDigest()
        for i in range(n):
            d.record(0.01 + (i % 97) * 0.013)
        return {
            "observations": n,
            "buckets": len(d.counts),
            "counts_bytes": _sys.getsizeof(d.counts),
            "p95_s": round(d.quantile(0.95), 4),
        }, d

    small, _ = sized(100)
    big, _ = sized(10_000)
    return {
        "small": small,
        "big": big,
        "flat": (small["buckets"] == big["buckets"]
                 and small["counts_bytes"] == big["counts_bytes"]),
    }


async def run_gate(n_claims: int, repeats: int = 3) -> dict:
    """Reference wave (recorded), then interleaved enabled/disabled pairs
    for the overhead gate, then the synthetic memory check."""
    reference = await bench_wave(n_claims, observability=True)

    oh_claims = min(n_claims, OVERHEAD_CLAIMS)
    # one discarded warm-up pair absorbs allocator/import warm-up
    await bench_wave(oh_claims, observability=True)
    await bench_wave(oh_claims, observability=False)
    enabled_walls: list[float] = []
    disabled_walls: list[float] = []
    for _ in range(repeats):
        e = await bench_wave(oh_claims, observability=True)
        d = await bench_wave(oh_claims, observability=False)
        enabled_walls.append(e["ready_wall_s"])
        disabled_walls.append(d["ready_wall_s"])

    def median(walls: list[float]) -> float:
        return sorted(walls)[len(walls) // 2]

    overhead = (median(enabled_walls)
                / max(median(disabled_walls), 1e-9) - 1.0)
    return {
        "bench": "fleetscope",
        "pr": 14,
        "reference": reference,
        "overhead": {
            "claims": oh_claims,
            "repeats": repeats,
            "pairing": "interleaved",
            "statistic": "median",
            "enabled_walls_s": enabled_walls,
            "disabled_walls_s": disabled_walls,
        },
        "observability_overhead_fraction": round(overhead, 4),
        "digest_memory": digest_memory_check(),
        "gates": {"overhead_max": PR14_OVERHEAD_MAX,
                  "digest_memory_flat": True},
    }


def check_gate(results: dict) -> list[str]:
    out: list[str] = []
    overhead = results["observability_overhead_fraction"]
    if overhead > PR14_OVERHEAD_MAX:
        out.append(
            f"fleetscope overhead regressed: {100 * overhead:.1f}% > "
            f"{100 * PR14_OVERHEAD_MAX:.0f}% wall vs disabled "
            f"(walls: {results['overhead']})")
    if not results["digest_memory"]["flat"]:
        out.append(
            f"digest memory is not flat across observation counts: "
            f"{results['digest_memory']} — streaming state must be "
            "O(buckets), not O(claims)")
    slo = results["reference"].get("slo")
    if not slo or slo.get("claims_observed") != results["reference"]["claims"]:
        out.append(
            f"reference wave not fully observed by the SLO engine: "
            f"{None if not slo else slo.get('claims_observed')} of "
            f"{results['reference']['claims']} claims folded into digests")
    elif not slo.get("objectives"):
        out.append("reference snapshot carries no SLO objectives")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--claims", type=int, default=100,
                    help="reference-wave size (the recorded tier)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="interleaved overhead pairs after the warm-up pair")
    ap.add_argument("--gate", action="store_true",
                    help="reference wave + overhead pairs + memory check, "
                         "gate-enforced (the make bench tier)")
    ap.add_argument("--write-pr14", action="store_true",
                    help="record the gate run (SLO percentiles + burn rate "
                         "+ overhead) as BENCH_pr14.json")
    args = ap.parse_args(argv)

    results = asyncio.run(run_gate(args.claims, repeats=args.repeats))
    print(json.dumps(results, indent=2))
    violations = check_gate(results)
    if args.write_pr14:
        BENCH_PR14_FILE.write_text(json.dumps(results, indent=2) + "\n")
        print(f"wrote {BENCH_PR14_FILE}", file=sys.stderr)

    for v in violations:
        print(f"FLEETSCOPE GATE: {v}", file=sys.stderr)
    if violations:
        return 1
    slo = results["reference"]["slo"]
    print(f"fleetscope gates OK (overhead "
          f"{100 * results['observability_overhead_fraction']:+.1f}%, "
          f"fleet p95 {slo['fleet']['p95']}s over "
          f"{slo['claims_observed']} claims, burn "
          f"{slo['objectives'][0]['burn']})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
