"""Mega-wave control-plane benchmark (PR 11): the event-driven wake graph
and status-write batching, proved at the 100-claim reference and at 10k.

Three harnesses, all envtest + FakeCloud, no network:

- **reference wave** (100 claims, the BENCH_pr09 configuration verbatim):
  the traced wave whose critical-path attribution showed requeue-idle-gap
  at 57% of wave wall. With the WakeHub + StatusWriteBatcher in place the
  idle phase splits into ``idle-gap:woken`` (an event ended the park — the
  hub working as designed) vs ``idle-gap:timer`` (the safety net actually
  fired) vs residual ``requeue-idle-gap``. The PR gate is honest about
  relabeling: ALL THREE idle flavors summed must be ≤ 15% of the critical
  claim's attributed wall — the wave must actually get faster, not just
  better-labeled.
- **mega-wave** (``n`` claims across ``shards`` shard Envs sharing ONE
  store + fake cloud): each Env runs one shard's full controller set with
  its own WakeHub and StatusWriteBatcher (the hub-per-process constraint:
  inject bypasses the watch map-fns' shard filter). Reports wall, per-shard
  peak queue depth (the shard-0 pile-up fix made visible), NodeClaim status
  -patch counts (the batcher gate: ≤ 3 per claim), wake-source ledger, and
  claimtrace attribution over the shard-0 sampled subset.
- The ``--gate`` tier (run by ``make bench``) is the reference wave plus a
  1k-claim smoke mega-wave at 8 shards, budget-enforced against
  ``BENCH_pr11.json``; ``--full`` is the recorded 10k × {1,4,8} run.

Caveat recorded in the JSON: in-process shard Envs share one event loop, so
shard scaling here measures partitioning overhead/fairness (watch fan-out,
queue balance), NOT parallel speedup — see docs/PERFORMANCE.md.

PR 12 adds ``timer_wake_share`` to every harness: the fraction of requeue
wakes fired by the workqueue's safety-net timer rather than an event
producer. The event-driven graph keeps it near zero; a producer that falls
off the hub pushes its entire wake class onto the timer, so the share is
gated (≤ 5%) on the reference wave and the gate-tier mega-wave, and the
gate-tier run is recorded as ``BENCH_pr12.json`` via ``--write-pr12``.

Usage: python -m bench.bench_megawave [--gate | --full] [--claims N]
                                      [--shards 8] [--write-pr11]
                                      [--write-pr12]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import time
from pathlib import Path

BENCH_PR11_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr11.json"
BENCH_PR12_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr12.json"
BENCH_PR19_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr19.json"

# PR 11 acceptance gates (criteria, not recorded budgets).
IDLE_FRACTION_MAX = 0.15          # all idle flavors / attributed wall
ATTRIBUTION_MIN = 0.95
STATUS_PATCHES_PER_CLAIM_MAX = 3.0
# PR 12: share of requeue wakes fired by the safety-net timer instead of an
# event producer. Healthy waves measure ~0.01% (2 of ~15k wakes at 1k
# claims); a single unregistered producer sends its whole wake class to the
# timer fallback, so even this generous ceiling is a loud tripwire.
TIMER_WAKE_SHARE_MAX = 0.05


def _idle_phases(phases: dict) -> float:
    from gpu_provisioner_tpu.observability.critical_path import (
        IDLE, IDLE_TIMER, IDLE_WOKEN,
    )
    return sum(phases.get(p, 0.0) for p in (IDLE, IDLE_WOKEN, IDLE_TIMER))


def _wake_ledger_snapshot() -> dict:
    from gpu_provisioner_tpu.runtime import wakehub
    return dict(wakehub.WAKES)


def _wake_delta(before: dict) -> dict:
    from gpu_provisioner_tpu.runtime import wakehub
    return {k: v - before.get(k, 0) for k, v in wakehub.WAKES.items()
            if v - before.get(k, 0) > 0}


def _timer_wake_share(wakes: dict) -> float:
    # timer-arm-skipped is BOOKKEEPING (a safety net never armed, PR 19's
    # timer diet), not a delivered wake — excluded from the denominator so
    # the diet shrinks the timer numerator without inflating the total.
    total = sum(v for k, v in wakes.items() if k != "timer-arm-skipped")
    return round(wakes.get("timer", 0) / total, 4) if total else 0.0


# ----------------------------------------------------------- reference wave

async def bench_reference(n_claims: int = 100) -> dict:
    """The BENCH_pr09 traced wave, re-run on the event-driven control
    plane. Same envtest parameters as bench_provision.bench_traced_wave so
    the idle numbers are directly comparable."""
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim
    from gpu_provisioner_tpu.observability import wave_attribution

    opts = EnvtestOptions(
        create_latency=0.05, node_join_delay=0.01, node_ready_delay=0.01,
        gc_interval=1.0, leak_grace=1.0, node_wait_attempts=600,
        lifecycle=LifecycleOptions(termination_requeue=0.5,
                                   registration_requeue=0.5),
        termination=TerminationOptions(requeue=0.5, instance_requeue=0.5),
        max_concurrent_reconciles=1024, use_informer=True,
        tracing=True, trace_buffer=max(2 * n_claims, 64),
        # measurement at saturation: stall gate off, leak gate stays on
        stall_budget=0.0)
    wakes_before = _wake_ledger_snapshot()
    async with Env(opts) as env:
        async def provision(i: int) -> float:
            t = time.perf_counter()
            await env.client.create(make_nodeclaim(f"t{i:04d}", "tpu-v5e-8",
                                                   workspace=f"ws{i}"))
            await env.wait_ready(f"t{i:04d}", timeout=120, poll=0.1)
            return time.perf_counter() - t

        t0 = asyncio.get_event_loop().time()
        wall0 = time.perf_counter()
        readies = await asyncio.gather(*(provision(i)
                                         for i in range(n_claims)))
        ready_wall = time.perf_counter() - wall0

        attribution = wave_attribution(env.trace_store.traces(), t0)
        stale_drops = sum(c.queue.stale_timer_drops
                          for c in env.manager.controllers)
        batcher = env.status_batcher
        batcher_stats = {
            "submitted": batcher.submitted, "coalesced": batcher.coalesced,
            "writes": batcher.writes, "flushes": batcher.flushes,
        } if batcher is not None else None
    idle = _idle_phases(attribution["phases"]) if attribution else None
    wakes = _wake_delta(wakes_before)
    return {
        "claims": n_claims,
        "ready_p50_s": round(statistics.median(readies), 4),
        "ready_p95_s": round(sorted(readies)[int(0.95 * n_claims) - 1], 4),
        "ready_wall_s": round(ready_wall, 3),
        "attribution": attribution,
        "idle_all_flavors_s": round(idle, 6) if idle is not None else None,
        "idle_fraction": (round(idle / attribution["wall"], 4)
                          if attribution else None),
        "wakes_by_source": wakes,
        "timer_wake_share": _timer_wake_share(wakes),
        "stale_timer_drops": stale_drops,
        "status_batcher": batcher_stats,
    }


def check_timer_share(res: dict, label: str) -> list[str]:
    share = res.get("timer_wake_share")
    if share is None or share <= TIMER_WAKE_SHARE_MAX:
        return []
    return [f"{label}: timer wakes are {100 * share:.1f}% of all requeue "
            f"wakes > {100 * TIMER_WAKE_SHARE_MAX:.0f}% — an event producer "
            "fell off the hub and its wake class is riding the safety-net "
            f"timer (ledger: {res.get('wakes_by_source')})"]


def check_reference(ref: dict) -> list[str]:
    out: list[str] = []
    attribution = ref.get("attribution")
    if attribution is None:
        return ["reference wave produced no attribution"]
    if attribution["attributed_fraction"] < ATTRIBUTION_MIN:
        out.append(
            f"attribution too low: {attribution['attributed_fraction']:.3f}"
            f" < {ATTRIBUTION_MIN} (a new unnamed phase in the hot path?)")
    if ref["idle_fraction"] > IDLE_FRACTION_MAX:
        out.append(
            f"requeue idle regressed: all idle flavors are "
            f"{100 * ref['idle_fraction']:.1f}% of the critical claim's "
            f"wall > {100 * IDLE_FRACTION_MAX:.0f}% (BENCH_pr09 baseline "
            "was 57% — are wake producers still registered on the hub?)")
    out += check_timer_share(ref, "reference")
    return out


# -------------------------------------------------------------- mega-wave

class _CountingClient:
    """Shared-store client wrapper counting NodeClaim write traffic; the
    megawave's status-patch gate reads ``update_status`` (each flush lands
    at most one per claim) and watch-churn context reads ``update``."""

    def __init__(self, inner):
        self.inner = inner
        self.store = inner.store
        self.updates = 0
        self.status_updates = 0

    def __getattr__(self, name):
        return getattr(self.inner, name)

    async def update(self, obj):
        self.updates += 1
        return await self.inner.update(obj)

    async def update_status(self, obj):
        self.status_updates += 1
        return await self.inner.update_status(obj)


async def bench_megawave(n_claims: int, shards: int,
                         trace_samples: int = 512) -> dict:
    """``n_claims`` through ``shards`` shard Envs over ONE shared store +
    fake cloud. Tracing is enabled only on shard 0 (its ring buffer is the
    sampled subset); per-shard queue depth is sampled by a side task."""
    from gpu_provisioner_tpu.apis.karpenter import NodeClaim
    from gpu_provisioner_tpu.apis.meta import CONDITION_READY
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions, _make_cloud
    from gpu_provisioner_tpu.fake import make_nodeclaim
    from gpu_provisioner_tpu.observability import wave_attribution
    from gpu_provisioner_tpu.runtime import InMemoryClient

    # The tracked-create budget is node_wait_attempts * node_wait_interval
    # (0.02 s in envtest) — scale it to the wave deadline, or a 10k wave on
    # one event loop expires mid-wave node-waits and turns the tail of the
    # wave into a create-retry storm that measures the retry ladder, not
    # the control plane.
    wait_deadline = max(120.0, n_claims * 0.2)
    wait_attempts = max(1200, int(wait_deadline / 0.02))

    def shard_opts(i: int) -> EnvtestOptions:
        return EnvtestOptions(
            create_latency=0.05, node_join_delay=0.01, node_ready_delay=0.01,
            gc_interval=10.0, leak_grace=10.0,
            node_wait_attempts=wait_attempts,
            lifecycle=LifecycleOptions(termination_requeue=0.5,
                                       registration_requeue=0.5,
                                       # production window (lifecycle.py
                                       # default), not envtest's 0.01 s —
                                       # the mega-wave measures the batcher
                                       # at its shipped coalescing horizon
                                       status_flush_window=0.05),
            termination=TerminationOptions(requeue=0.5, instance_requeue=0.5),
            max_concurrent_reconciles=1024, use_informer=True,
            shards=shards, shard_index=i,
            tracing=(i == 0), trace_buffer=trace_samples,
            stall_budget=0.0)

    raw = InMemoryClient()
    kube = _CountingClient(raw)
    cloud = _make_cloud(shard_opts(0), raw)  # the world writes uncounted
    wakes_before = _wake_ledger_snapshot()
    envs = [Env(shard_opts(i), client=kube, cloud=cloud)
            for i in range(shards)]
    for env in envs:
        await env.__aenter__()

    depth_peak = {i: 0 for i in range(shards)}

    async def depth_sampler():
        while True:
            for i, env in enumerate(envs):
                d = sum(c.queue.depth() for c in env.manager.controllers)
                depth_peak[i] = max(depth_peak[i], d)
            await asyncio.sleep(0.1)

    sampler = asyncio.create_task(depth_sampler())
    try:
        names = [f"m{i:05d}" for i in range(n_claims)]
        t0 = asyncio.get_event_loop().time()
        wall0 = time.perf_counter()
        create0_updates = kube.status_updates

        sem = asyncio.Semaphore(512)

        async def create(i: int):
            async with sem:
                await raw.create(make_nodeclaim(names[i], "tpu-v5e-8",
                                                workspace=f"ws{i}"))

        await asyncio.gather(*(create(i) for i in range(n_claims)))

        # one store scan per poll instead of n_claims pollers at 100 Hz
        deadline = time.perf_counter() + wait_deadline
        while True:
            objs = await raw.list(NodeClaim)
            ready = sum(1 for o in objs
                        if o.status_conditions.is_true(CONDITION_READY))
            if ready >= n_claims:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"mega-wave stalled: {ready}/{n_claims} ready")
            await asyncio.sleep(0.25)
        ready_wall = time.perf_counter() - wall0
        status_patches = kube.status_updates - create0_updates

        attribution = wave_attribution(envs[0].trace_store.traces(), t0)
        stale_drops = sum(c.queue.stale_timer_drops
                          for env in envs for c in env.manager.controllers)
        batch = {
            "submitted": sum(e.status_batcher.submitted for e in envs),
            "coalesced": sum(e.status_batcher.coalesced for e in envs),
            "writes": sum(e.status_batcher.writes for e in envs),
        }
    finally:
        sampler.cancel()
        try:
            await sampler
        except asyncio.CancelledError:
            pass
        for env in reversed(envs):
            await env.__aexit__(None, None, None)

    depths = [depth_peak[i] for i in range(shards)]
    idle = _idle_phases(attribution["phases"]) if attribution else None
    wakes = _wake_delta(wakes_before)
    return {
        "claims": n_claims,
        "shards": shards,
        "ready_wall_s": round(ready_wall, 3),
        "status_patches": status_patches,
        "status_patches_per_claim": round(status_patches / n_claims, 3),
        "meta_patches": kube.updates,
        "peak_queue_depth_by_shard": depths,
        "peak_depth_imbalance": (round(max(depths) / max(min(depths), 1), 2)
                                 if shards > 1 else 1.0),
        "wakes_by_source": wakes,
        "timer_wake_share": _timer_wake_share(wakes),
        "stale_timer_drops": stale_drops,
        "status_batcher": batch,
        "traced_sample": {
            "claims": attribution["claims"] if attribution else 0,
            "idle_all_flavors_s": (round(idle, 6)
                                   if idle is not None else None),
            "idle_fraction": (round(idle / attribution["wall"], 4)
                              if attribution else None),
            "attributed_fraction": (attribution["attributed_fraction"]
                                    if attribution else None),
            "phases": attribution["phases"] if attribution else None,
        },
    }


def check_megawave(res: dict) -> list[str]:
    out: list[str] = []
    if res["status_patches_per_claim"] > STATUS_PATCHES_PER_CLAIM_MAX:
        out.append(
            f"status-patch volume regressed: "
            f"{res['status_patches_per_claim']:.2f}/claim > "
            f"{STATUS_PATCHES_PER_CLAIM_MAX} (batcher not coalescing?)")
    out += check_timer_share(res, f"mega-wave@{res['shards']}sh")
    return out


# ----------------------------------------------------------- process wave

# PR 19 gates for the multi-process tier.
PROC_IMBALANCE_MAX = 2.0      # peak queue depth, busiest/quietest worker
# Monotone wall scaling (1→4→8 workers) is a PHYSICAL claim: it needs as
# many cores as workers. On a smaller host the tier still runs and records,
# but the scaling gate degrades to an overhead bound: the N-worker wall may
# not exceed this multiple of the 1-worker wall (the IPC/relay/lease tax).
PROC_OVERHEAD_MAX = 1.35
PROC_MONOTONE_SLACK = 1.05    # 5% noise tolerance on the monotone gate


async def bench_procwave(n_claims: int, workers: int) -> dict:
    """``n_claims`` through ``workers`` REAL worker processes: the parent
    owns the store + fake cloud and serves the shard IPC socket
    (operator/supervisor.py); each worker is a full operator stack over its
    lease-owned claim ranges (operator/shardworker.py). The in-process
    mega-wave above stays as the fairness baseline — this tier is the one
    with actual parallel event loops."""
    from gpu_provisioner_tpu.apis.karpenter import NodeClaim
    from gpu_provisioner_tpu.apis.meta import CONDITION_READY
    from gpu_provisioner_tpu.fake import make_nodeclaim
    from gpu_provisioner_tpu.fake.cloud import FakeCloud
    from gpu_provisioner_tpu.operator.supervisor import ShardSupervisor
    from gpu_provisioner_tpu.runtime import InMemoryClient

    wait_deadline = max(120.0, n_claims * 0.2)
    worker_opts = {
        "max_concurrent_reconciles": 256,
        "gc_interval": 10.0, "leak_grace": 10.0,
        "node_wait_attempts": max(1200, int(wait_deadline / 0.02)),
        "operation_poll_interval": 0.1,
        "lifecycle.termination_requeue": 0.5,
        "lifecycle.registration_requeue": 0.5,
        "lifecycle.status_flush_window": 0.05,
        "termination.requeue": 0.5,
        "termination.instance_requeue": 0.5,
    }
    raw = InMemoryClient()
    kube = _CountingClient(raw)
    cloud = FakeCloud(raw, create_latency=0.05, node_join_delay=0.01,
                      node_ready_delay=0.01)
    sup = ShardSupervisor(kube, cloud, worker_opts=worker_opts)
    await sup.start()
    depth_peak: dict[str, int] = {}

    async def depth_sampler():
        while True:
            for w, snap in sup.snapshots().items():
                d = sum(snap.get("depths", {}).values())
                depth_peak[w] = max(depth_peak.get(w, 0), d)
            await asyncio.sleep(0.1)

    sampler = asyncio.create_task(depth_sampler())
    try:
        await sup.spawn(workers)
        await sup.wait_covered(timeout=90.0, workers=workers)
        names = [f"p{i:05d}" for i in range(n_claims)]
        wall0 = time.perf_counter()
        create0_updates = kube.status_updates
        sem = asyncio.Semaphore(512)

        async def create(i: int):
            async with sem:
                await raw.create(make_nodeclaim(names[i], "tpu-v5e-8",
                                                workspace=f"ws{i}"))

        await asyncio.gather(*(create(i) for i in range(n_claims)))

        deadline = time.perf_counter() + wait_deadline
        while True:
            objs = await raw.list(NodeClaim)
            ready = sum(1 for o in objs
                        if o.status_conditions.is_true(CONDITION_READY))
            if ready >= n_claims:
                break
            if time.perf_counter() > deadline:
                raise TimeoutError(
                    f"proc-wave stalled: {ready}/{n_claims} ready")
            await asyncio.sleep(0.25)
        ready_wall = time.perf_counter() - wall0
        status_patches = kube.status_updates - create0_updates
        # settle one snapshot interval so every worker's final cumulative
        # ledger (fresh processes: totals ARE the wave delta) is in
        await asyncio.sleep(0.5)
        snaps = sup.snapshots()
    finally:
        sampler.cancel()
        try:
            await sampler
        except asyncio.CancelledError:
            pass
        routed, dropped = sup.server.wakes_routed, sup.server.wakes_dropped
        await sup.stop()

    wakes: dict[str, int] = {}
    forwarded = delivered = 0
    batch = {"submitted": 0, "coalesced": 0}
    for snap in snaps.values():
        for source, n in snap.get("wakes", {}).items():
            wakes[source] = wakes.get(source, 0) + n
        hub = snap.get("hub", {})
        forwarded += hub.get("forwarded", 0)
        delivered += hub.get("delivered", 0)
        for k in batch:
            batch[k] += snap.get("batcher", {}).get(k, 0)
    depths = [depth_peak.get(w, 0) for w in sorted(depth_peak)]
    return {
        "claims": n_claims,
        "workers": workers,
        "ready_wall_s": round(ready_wall, 3),
        "status_patches": status_patches,
        "status_patches_per_claim": round(status_patches / n_claims, 3),
        "peak_queue_depth_by_worker": depths,
        "peak_depth_imbalance": (round(max(depths) / max(min(depths), 1), 2)
                                 if workers > 1 and depths else 1.0),
        "wakes_by_source": wakes,
        "timer_wake_share": _timer_wake_share(wakes),
        "timer_arm_skipped": wakes.get("timer-arm-skipped", 0),
        "wakes_delivered": delivered,
        "wakes_forwarded_cross_process": forwarded,
        "ipc_wakes_routed": routed,
        "ipc_wakes_dropped": dropped,
        "status_batcher": batch,
    }


def check_procwave(waves: list[dict], cores: int) -> list[str]:
    out: list[str] = []
    for w in waves:
        out += check_timer_share(w, f"proc-wave@{w['workers']}w")
        if (w["workers"] > 1
                and w["peak_depth_imbalance"] > PROC_IMBALANCE_MAX):
            out.append(
                f"proc-wave@{w['workers']}w: peak depth imbalance "
                f"{w['peak_depth_imbalance']}x > {PROC_IMBALANCE_MAX}x — "
                f"lease fair-share is not spreading the wave "
                f"(peaks {w['peak_queue_depth_by_worker']})")
    walls = {w["workers"]: w["ready_wall_s"] for w in waves}
    if len(walls) < 2:
        return out
    counts = sorted(walls)
    if cores >= max(counts):
        for lo, hi in zip(counts, counts[1:]):
            if walls[hi] > walls[lo] * PROC_MONOTONE_SLACK:
                out.append(
                    f"proc-wave wall NOT monotone: {walls[hi]}s @ {hi}w > "
                    f"{walls[lo]}s @ {lo}w (+5% slack) on a {cores}-core "
                    f"host — worker processes are not scaling")
    else:
        base = walls[counts[0]]
        for c in counts[1:]:
            if walls[c] > base * PROC_OVERHEAD_MAX:
                out.append(
                    f"proc-wave@{c}w wall {walls[c]}s > "
                    f"{PROC_OVERHEAD_MAX}x the 1-worker {base}s on a "
                    f"{cores}-core host — the IPC/relay/lease tax grew "
                    f"(monotone-speedup gate needs >= {max(counts)} cores)")
    return out


# ------------------------------------------------------------------- budget

def make_proc_budget(gate_procs: list[dict]) -> dict:
    """3× headroom over the gate-tier proc-wave walls, keyed by worker
    count — the cross-machine-tolerant regression tripwire."""
    return {
        "claims": gate_procs[0]["claims"],
        "wall_ceiling_s": {str(w["workers"]): round(3.0 * w["ready_wall_s"],
                                                    1)
                           for w in gate_procs},
    }


def check_proc_budget(gate_procs: list[dict], recorded: dict) -> list[str]:
    budget = recorded.get("budget", {})
    ceilings = budget.get("wall_ceiling_s", {})
    out: list[str] = []
    for w in gate_procs:
        ceiling = ceilings.get(str(w["workers"]))
        if (ceiling is not None and w["claims"] == budget.get("claims")
                and w["ready_wall_s"] > ceiling):
            out.append(
                f"proc-wave wall regressed: {w['ready_wall_s']}s > budget "
                f"{ceiling}s at {w['claims']} claims / "
                f"{w['workers']} workers")
    return out


def make_budget(gate_wave: dict) -> dict:
    """3× headroom over the gate-tier mega-wave wall (scales with machine
    speed; the gate catches a reintroduced idle park or patch storm, not a
    loaded CI box)."""
    return {
        "gate_wave_wall_s": round(3.0 * gate_wave["ready_wall_s"], 1),
        "gate_wave_claims": gate_wave["claims"],
        "gate_wave_shards": gate_wave["shards"],
    }


def check_budget(gate_wave: dict, recorded: dict) -> list[str]:
    budget = recorded.get("budget", {})
    out: list[str] = []
    ceiling = budget.get("gate_wave_wall_s")
    if (ceiling is not None
            and gate_wave["claims"] == budget.get("gate_wave_claims")
            and gate_wave["shards"] == budget.get("gate_wave_shards")
            and gate_wave["ready_wall_s"] > ceiling):
        out.append(
            f"mega-wave wall regressed: {gate_wave['ready_wall_s']}s > "
            f"budget {ceiling}s at {gate_wave['claims']} claims / "
            f"{gate_wave['shards']} shards")
    return out


async def run_gate(claims: int, shards: int) -> dict:
    reference = await bench_reference(100)
    gate_wave = await bench_megawave(claims, shards)
    return {
        "bench": "megawave-gate",
        "pr": 12,
        "reference": reference,
        "gate_wave": gate_wave,
        "gates": {"idle_fraction_max": IDLE_FRACTION_MAX,
                  "attribution_min": ATTRIBUTION_MIN,
                  "status_patches_per_claim_max":
                      STATUS_PATCHES_PER_CLAIM_MAX,
                  "timer_wake_share_max": TIMER_WAKE_SHARE_MAX},
    }


async def run_full(shard_counts: tuple[int, ...] = (1, 4, 8),
                   n_claims: int = 10_000) -> dict:
    reference = await bench_reference(100)
    waves = []
    for s in shard_counts:
        waves.append(await bench_megawave(n_claims, s))
        print(f"  mega-wave {n_claims} claims @ {s} shard(s): "
              f"{waves[-1]['ready_wall_s']}s", file=sys.stderr)
    return {
        "bench": "megawave",
        "pr": 11,
        "note": ("in-process shard Envs share one event loop: the shard "
                 "axis measures partitioning fairness (queue balance, "
                 "watch fan-out), not parallel speedup — see "
                 "docs/PERFORMANCE.md"),
        "reference": reference,
        "megawave": waves,
        "gates": {"idle_fraction_max": IDLE_FRACTION_MAX,
                  "attribution_min": ATTRIBUTION_MIN,
                  "status_patches_per_claim_max":
                      STATUS_PATCHES_PER_CLAIM_MAX,
                  "timer_wake_share_max": TIMER_WAKE_SHARE_MAX},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--claims", type=int, default=1000,
                    help="gate-tier mega-wave size (the full tier is 10k)")
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--gate", action="store_true",
                    help="reference wave + smoke mega-wave, budget-enforced"
                         " (the make bench tier)")
    ap.add_argument("--full", action="store_true",
                    help="the recorded 10k x {1,4,8} run (slow)")
    ap.add_argument("--full-claims", type=int, default=10_000)
    ap.add_argument("--shard-counts", type=str, default="1,4,8",
                    help="comma-separated shard counts for the full tier")
    ap.add_argument("--write-pr11", action="store_true",
                    help="rewrite BENCH_pr11.json with fresh numbers+budget")
    ap.add_argument("--write-pr12", action="store_true",
                    help="record the gate-tier run (wake-source ledger + "
                         "timer_wake_share) as BENCH_pr12.json")
    ap.add_argument("--procs", action="store_true",
                    help="multi-process shard tier: REAL worker processes "
                         "over the shard IPC socket, gate-sized")
    ap.add_argument("--procs-claims", type=int, default=300,
                    help="gate-tier proc-wave size")
    ap.add_argument("--procs-workers", type=str, default="1,2",
                    help="comma-separated worker counts for the gate "
                         "proc tier")
    ap.add_argument("--procs-full", action="store_true",
                    help="full proc tier: --full-claims claims at worker "
                         "counts 1/4/8 (slow)")
    ap.add_argument("--procs-full-workers", type=str, default="1,4,8")
    ap.add_argument("--write-pr19", action="store_true",
                    help="record the proc-tier runs + budget as "
                         "BENCH_pr19.json")
    args = ap.parse_args(argv)

    rc = 0
    if args.full:
        counts = tuple(int(s) for s in args.shard_counts.split(","))
        results = asyncio.run(run_full(counts, n_claims=args.full_claims))
        # the budget make bench enforces comes from a gate-tier wave
        gate_wave = asyncio.run(bench_megawave(args.claims, args.shards))
        results["gate_wave"] = gate_wave
        print(json.dumps(results, indent=2))
        violations = check_reference(results["reference"])
        for w in results["megawave"]:
            # The status-patch ceiling binds at the sharded configuration
            # the acceptance names (8 shards). A 1-shard 10k wave stretches
            # minutes long, so a claim's registration and initialization
            # laps land in flush windows minutes apart — nothing for the
            # batcher to coalesce — and the natural floor drifts past 3x.
            # The smaller shard counts are the partitioning-fairness axis,
            # recorded but not patch-gated.
            if w["shards"] == args.shards:
                violations += check_megawave(w)
        if args.write_pr11:
            results["budget"] = make_budget(gate_wave)
            BENCH_PR11_FILE.write_text(json.dumps(results, indent=2) + "\n")
            print(f"wrote {BENCH_PR11_FILE}", file=sys.stderr)
    else:
        results = asyncio.run(run_gate(args.claims, args.shards))
        print(json.dumps(results, indent=2))
        violations = (check_reference(results["reference"])
                      + check_megawave(results["gate_wave"]))
        if BENCH_PR11_FILE.exists():
            recorded = json.loads(BENCH_PR11_FILE.read_text())
            violations += check_budget(results["gate_wave"], recorded)
        if args.write_pr12:
            BENCH_PR12_FILE.write_text(json.dumps(results, indent=2) + "\n")
            print(f"wrote {BENCH_PR12_FILE}", file=sys.stderr)

    if args.procs or args.procs_full:
        import os
        cores = os.cpu_count() or 1
        gate_procs = []
        for n in (int(s) for s in args.procs_workers.split(",")):
            gate_procs.append(asyncio.run(bench_procwave(args.procs_claims,
                                                         n)))
            print(f"  proc-wave {args.procs_claims} claims @ {n} worker"
                  f"(s): {gate_procs[-1]['ready_wall_s']}s",
                  file=sys.stderr)
        violations += check_procwave(gate_procs, cores)
        procs_results = {
            "bench": "megawave-procs",
            "pr": 19,
            "host_cores": cores,
            "note": ("worker processes have their OWN event loops — this "
                     "tier measures real parallel scaling. The monotone-"
                     "speedup gate applies only when host_cores >= the "
                     "largest worker count; below that it degrades to the "
                     f"{PROC_OVERHEAD_MAX}x IPC-overhead bound (see "
                     "docs/PERFORMANCE.md, Multi-process shards)"),
            "gate_procs": gate_procs,
            "gates": {"timer_wake_share_max": TIMER_WAKE_SHARE_MAX,
                      "peak_depth_imbalance_max": PROC_IMBALANCE_MAX,
                      "monotone_slack": PROC_MONOTONE_SLACK,
                      "overhead_max_sub_core": PROC_OVERHEAD_MAX},
        }
        if args.procs_full:
            full_procs = []
            for n in (int(s) for s in args.procs_full_workers.split(",")):
                full_procs.append(asyncio.run(
                    bench_procwave(args.full_claims, n)))
                print(f"  proc-wave {args.full_claims} claims @ {n} "
                      f"worker(s): {full_procs[-1]['ready_wall_s']}s",
                      file=sys.stderr)
            violations += check_procwave(full_procs, cores)
            procs_results["full_procs"] = full_procs
        results["procs"] = procs_results
        print(json.dumps({"procs": procs_results}, indent=2))
        if BENCH_PR19_FILE.exists():
            recorded = json.loads(BENCH_PR19_FILE.read_text())
            violations += check_proc_budget(gate_procs, recorded)
        if args.write_pr19:
            procs_results["budget"] = make_proc_budget(gate_procs)
            BENCH_PR19_FILE.write_text(
                json.dumps(procs_results, indent=2) + "\n")
            print(f"wrote {BENCH_PR19_FILE}", file=sys.stderr)

    for v in violations:
        print(f"MEGAWAVE GATE: {v}", file=sys.stderr)
    if violations:
        rc = 1
    else:
        ref = results["reference"]
        print(f"megawave gates OK (idle {100 * ref['idle_fraction']:.1f}% "
              f"of critical wall, attribution "
              f"{ref['attribution']['attributed_fraction']:.3f})",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
