"""Provisioning fast-path benchmark (PR 2): list fan-out + instance cache.

Two harnesses, both envtest + FakeCloud, no network:

- **wave**: N NodeClaims through the REAL controller set (create → Registered
  → Ready), then all deleted and verified gone from the cloud. Reports
  p50/p95 claim-ready latency, wall clock, total cloud calls by endpoint
  (from the provider's per-endpoint ``CountingAPI``), and the read-through
  cache's hit/miss/coalesced counters.
- **gc_pass**: M pools provisioned, then ONE full ``InstanceGCController``
  pass timed with a simulated apiserver RTT on every kube call — once with
  the pre-change list path (``legacy_list``: one kube Node list PER POOL,
  serially) and once with the fast path (one bulk list + bounded fan-out).
  The before/after ratio is the PR's headline claim.

PR 4 adds the **worker-constrained wave** (``BENCH_pr04.json``): the same
claim wave with the lifecycle worker pool squeezed to 8 and slow simulated
LROs, run once against the blocking create/delete shape
(``EnvtestOptions.blocking_create`` — a worker pinned per create for the
full slice-create duration, client-side LRO polling per operation) and once
against the operation tracker (non-blocking state machines, one batched
``nodepools.list`` per tick). Reports ready_p95 / ready_wall,
**pinned-worker-seconds** (total time lifecycle workers spent inside
reconcile), and the wave-wide poll-call count
(``nodepools.get`` + ``nodepools.list`` + client-side LRO polls).

PR 9 adds the **traced wave** (``BENCH_pr09.json``): the claim wave under
claimtrace, its ready-wall decomposed into named phases by the critical-path
analyzer (observability/critical_path.py), plus an untraced re-run as the
overhead baseline. Gates: named phases explain ≥95% of the wall; tracing
costs ≤5% wall vs disabled. ``--trace`` prints the attribution summary for
one traced wave (``make trace``); ``--trace-smoke`` is the small-wave
variant ``make verify`` runs.

Writes ``BENCH_pr02.json`` with ``--write``, ``BENCH_pr04.json`` with
``--write-pr04`` and ``BENCH_pr09.json`` with ``--write-pr09``; by default
(and under ``make bench``) it re-measures and REFUSES to pass if cloud-call
counts regress beyond the recorded budgets or the claimtrace gates fail.

Usage: python -m bench.bench_provision [--claims 100] [--pools 100]
                                       [--write] [--write-pr04]
                                       [--write-pr09] [--trace] [--fast]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import statistics
import sys
import time
from collections import defaultdict
from pathlib import Path

BENCH_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr02.json"
BENCH_PR04_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr04.json"
BENCH_PR09_FILE = Path(__file__).resolve().parent.parent / "BENCH_pr09.json"

# PR 9 claimtrace gates (acceptance criteria, not recorded budgets): the
# named phases must explain ≥95% of the traced wave's ready-wall, and
# tracing must cost ≤5% wall vs the tracer disabled.
PR09_ATTRIBUTION_MIN = 0.95
PR09_OVERHEAD_MAX = 0.05

# Simulated apiserver round-trip for the GC-pass harness. The in-memory
# store answers in microseconds; a serial-per-pool list path only shows its
# real cost when each call carries a wire RTT (1 ms is conservative — GKE
# apiservers answer list calls in 5-50 ms).
KUBE_RTT_S = 0.001


def _pctl(samples: list[float], q: float) -> float:
    s = sorted(samples)
    return s[min(len(s) - 1, math.ceil(q * len(s)) - 1)]


class InstrumentedKube:
    """Counting + fixed-latency wrapper over the kube ``Client`` seam.

    ``calls`` keys are ``"<verb>:<Kind>"`` so the list-path accounting can
    distinguish Node lists (the per-pool amplification this PR removes)
    from NodeClaim lists.
    """

    def __init__(self, inner, latency: float = 0.0):
        self.inner = inner
        self.latency = latency
        self.calls: dict[str, int] = defaultdict(int)
        self.store = getattr(inner, "store", None)

    async def _hit(self, verb: str, cls: type) -> None:
        self.calls[f"{verb}:{getattr(cls, '__name__', cls)}"] += 1
        if self.latency > 0:
            await asyncio.sleep(self.latency)

    def lists(self, kind: str | None = None) -> int:
        return sum(n for k, n in self.calls.items()
                   if k.startswith("list:") and (kind is None or
                                                 k == f"list:{kind}"))

    async def get(self, cls, name, namespace=""):
        await self._hit("get", cls)
        return await self.inner.get(cls, name, namespace)

    async def list(self, cls, labels=None, namespace=None, index=None):
        await self._hit("list", cls)
        return await self.inner.list(cls, labels=labels, namespace=namespace,
                                     index=index)

    async def create(self, obj):
        await self._hit("create", type(obj))
        return await self.inner.create(obj)

    async def update(self, obj):
        await self._hit("update", type(obj))
        return await self.inner.update(obj)

    async def update_status(self, obj):
        await self._hit("update_status", type(obj))
        return await self.inner.update_status(obj)

    async def delete(self, cls, name, namespace=""):
        await self._hit("delete", cls)
        return await self.inner.delete(cls, name, namespace)

    async def evict(self, name, namespace="", uid=""):
        return await self.inner.evict(name, namespace, uid=uid)

    def watch(self, cls):
        return self.inner.watch(cls)


# ------------------------------------------------------------------ gc pass

async def bench_gc_pass(n_pools: int, legacy: bool,
                        kube_rtt: float = KUBE_RTT_S) -> dict:
    """Provision ``n_pools`` slices, then time ONE InstanceGCController pass
    (cloud list + claim diff + orphan-node scan) with ``kube_rtt`` on every
    kube call. Returns wall clock + call counts for the pass only."""
    from gpu_provisioner_tpu.cloudprovider import TPUCloudProvider
    from gpu_provisioner_tpu.controllers.gc import GCOptions, InstanceGCController
    from gpu_provisioner_tpu.fake import FakeCloud, make_nodeclaim
    from gpu_provisioner_tpu.providers.instance import (
        InstanceProvider, ProviderConfig,
    )
    from gpu_provisioner_tpu.apis.core import Node
    from gpu_provisioner_tpu.runtime import InMemoryClient

    raw = InMemoryClient()
    raw.store.add_index(Node, "spec.providerID",
                        lambda o: [o.spec.provider_id])
    kube = InstrumentedKube(raw, latency=kube_rtt)
    cloud = FakeCloud(raw, create_latency=0.0, delete_latency=0.0)
    provider = InstanceProvider(
        cloud.nodepools, kube,
        ProviderConfig(node_wait_interval=0.001, node_wait_attempts=50,
                       legacy_list=legacy),
        queued=cloud.queuedresources)
    cp = TPUCloudProvider(provider)

    sem = asyncio.Semaphore(32)

    async def one(i: int):
        async with sem:
            await provider.create(make_nodeclaim(f"bp{i:04d}", "tpu-v5e-8"))

    await asyncio.gather(*(one(i) for i in range(n_pools)))

    gc = InstanceGCController(kube, cp, GCOptions(leak_grace=3600.0))
    kube.calls.clear()
    provider.nodepools.calls.clear()
    t0 = time.perf_counter()
    await gc._collect()
    wall = time.perf_counter() - t0
    assert len(cloud.nodepools.pools) == n_pools, "GC pass must reap nothing"
    return {
        "pools": n_pools,
        "wall_s": round(wall, 6),
        "kube_node_lists": kube.lists("Node"),
        "kube_lists_total": kube.lists(),
        "cloud_calls": dict(provider.nodepools.calls),
        "list_path_calls": kube.lists("Node")
        + provider.nodepools.calls.get("list", 0),
    }


# --------------------------------------------------------------------- wave

async def bench_wave(n_claims: int, shape: str = "tpu-v5e-8") -> dict:
    """The 100-claim wave: created → reconciled to Ready by the real
    controllers → deleted → verified gone from the cloud."""
    from gpu_provisioner_tpu.apis.karpenter import NodeClaim
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim

    opts = EnvtestOptions(
        create_latency=0.05, node_join_delay=0.01, node_ready_delay=0.01,
        gc_interval=1.0, leak_grace=1.0, node_wait_attempts=600,
        lifecycle=LifecycleOptions(termination_requeue=0.5,
                                   registration_requeue=0.5),
        termination=TerminationOptions(requeue=0.5, instance_requeue=0.5),
        max_concurrent_reconciles=1024, use_informer=True,
        # measurement at saturation: stall gate off, leak gate stays on
        stall_budget=0.0)
    async with Env(opts) as env:
        async def provision(i: int) -> float:
            t = time.perf_counter()
            await env.client.create(make_nodeclaim(f"w{i:04d}", shape,
                                                   workspace=f"ws{i}"))
            await env.wait_ready(f"w{i:04d}", timeout=120, poll=0.1)
            return time.perf_counter() - t

        t0 = time.perf_counter()
        readies = await asyncio.gather(*(provision(i)
                                         for i in range(n_claims)))
        ready_wall = time.perf_counter() - t0

        t1 = time.perf_counter()
        for i in range(n_claims):
            await env.client.delete(NodeClaim, f"w{i:04d}")
        await asyncio.gather(*(env.wait_gone(f"w{i:04d}", timeout=60)
                               for i in range(n_claims)))
        delete_wall = time.perf_counter() - t1
        leaked_pools = len(env.cloud.nodepools.pools)
        leaked_qrs = len(env.cloud.queuedresources.resources)

        cloud_calls = {f"nodepools.{m}": n
                       for m, n in env.provider.nodepools.calls.items()}
        cloud_calls.update({f"queuedresources.{m}": n
                            for m, n in env.provider.queued.calls.items()})
        cache = {"pool_cache": dict(env.provider._pool_cache.stats),
                 "qr_cache": dict(env.provider._qr_cache.stats)}
    return {
        "claims": n_claims,
        "shape": shape,
        "ready_p50_s": round(statistics.median(readies), 4),
        "ready_p95_s": round(_pctl(readies, 0.95), 4),
        "ready_wall_s": round(ready_wall, 3),
        "delete_wall_s": round(delete_wall, 3),
        "cloud_calls": cloud_calls,
        "cloud_calls_total": sum(cloud_calls.values()),
        "cache": cache,
        "leaked_pools": leaked_pools,
        "leaked_queued_resources": leaked_qrs,
    }


# --------------------------------------------------------------- traced wave

async def bench_traced_wave(n_claims: int, tracing: bool = True,
                            shape: str = "tpu-v5e-8") -> dict:
    """PR 9 claimtrace: the claim wave with per-claim tracing on (or off,
    for the overhead baseline). With tracing on, the wave's ready-wall is
    decomposed by the critical-path analyzer over the trace store."""
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim

    opts = EnvtestOptions(
        create_latency=0.05, node_join_delay=0.01, node_ready_delay=0.01,
        gc_interval=1.0, leak_grace=1.0, node_wait_attempts=600,
        lifecycle=LifecycleOptions(termination_requeue=0.5,
                                   registration_requeue=0.5),
        termination=TerminationOptions(requeue=0.5, instance_requeue=0.5),
        max_concurrent_reconciles=1024, use_informer=True,
        tracing=tracing, trace_buffer=max(2 * n_claims, 64),
        # measurement at saturation: stall gate off, leak gate stays on
        stall_budget=0.0)
    async with Env(opts) as env:
        async def provision(i: int) -> float:
            t = time.perf_counter()
            await env.client.create(make_nodeclaim(f"t{i:04d}", shape,
                                                   workspace=f"ws{i}"))
            await env.wait_ready(f"t{i:04d}", timeout=120, poll=0.1)
            return time.perf_counter() - t

        # wave start on the LOOP clock: span timestamps are loop time, so
        # the attribution window must anchor on the same base
        t0 = asyncio.get_event_loop().time()
        wall0 = time.perf_counter()
        readies = await asyncio.gather(*(provision(i)
                                         for i in range(n_claims)))
        ready_wall = time.perf_counter() - wall0

        attribution = None
        if tracing:
            from gpu_provisioner_tpu.observability import wave_attribution
            attribution = wave_attribution(env.trace_store.traces(), t0)
    return {
        "claims": n_claims,
        "tracing": tracing,
        "ready_p50_s": round(statistics.median(readies), 4),
        "ready_p95_s": round(_pctl(readies, 0.95), 4),
        "ready_wall_s": round(ready_wall, 3),
        "attribution": attribution,
    }


# Claim count for the overhead pairs: sized so the wave stays LATENCY-bound
# on a single busy core (~50-60% loop utilization). The attribution wave
# runs at the full ``--claims`` size regardless.
PR09_OVERHEAD_CLAIMS = 25


async def run_pr09(n_claims: int, repeats: int = 3) -> dict:
    """One full-size traced wave for the attribution gate, then the tracing
    overhead measured on interleaved traced/untraced PAIRS of a smaller,
    latency-bound wave, medians compared.

    The previous shape — all traced runs then all untraced at the full
    wave size, min-of-2 per group — flaked the 5% gate three ways. The
    groups ran minutes apart, so machine-weather drift landed entirely on
    one group and read as tracing overhead. Min-of-2 is an extreme
    statistic, so one lucky untraced run shrank the denominator. Worst,
    the full wave SATURATES a 1-core box (~95% loop utilization), where
    the wall is step-quantized by poll/requeue boundaries — ~0.2s jumps on
    a ~0.5s wave — so any extra CPU tips a quantum and reads as a 30-40%
    "overhead" (the documented 37.9%-on-a-loaded-box failure). The pairs
    therefore run a wave sized to keep the loop latency-bound, where wall
    overhead actually measures tracing's cost rather than the box's
    saturation threshold; pairing runs the modes back-to-back under the
    same weather, and the median is robust to a single bad round. One
    discarded warm-up pair absorbs allocator/import warm-up."""
    traced = await bench_traced_wave(n_claims, tracing=True)

    oh_claims = min(n_claims, PR09_OVERHEAD_CLAIMS)
    await bench_traced_wave(oh_claims, tracing=True)
    await bench_traced_wave(oh_claims, tracing=False)
    traced_walls: list[float] = []
    untraced_walls: list[float] = []
    for _ in range(repeats):
        t = await bench_traced_wave(oh_claims, tracing=True)
        u = await bench_traced_wave(oh_claims, tracing=False)
        traced_walls.append(t["ready_wall_s"])
        untraced_walls.append(u["ready_wall_s"])

    def median(walls: list[float]) -> float:
        return sorted(walls)[len(walls) // 2]

    overhead = median(traced_walls) / max(median(untraced_walls), 1e-9) - 1.0
    return {
        "bench": "claimtrace",
        "pr": 9,
        "traced": traced,
        "overhead": {
            "claims": oh_claims,
            "repeats": repeats,
            "pairing": "interleaved",
            "statistic": "median",
            "traced_walls_s": traced_walls,
            "untraced_walls_s": untraced_walls,
        },
        "tracing_overhead_fraction": round(overhead, 4),
        "attribution": traced["attribution"],
        "gates": {"attributed_fraction_min": PR09_ATTRIBUTION_MIN,
                  "overhead_max": PR09_OVERHEAD_MAX},
    }


def check_pr09(results: dict) -> list[str]:
    out: list[str] = []
    attribution = results.get("attribution")
    if attribution is None:
        return ["traced wave produced no attribution (no claim reached "
                "ready with a trace)"]
    frac = attribution["attributed_fraction"]
    if frac < PR09_ATTRIBUTION_MIN:
        out.append(
            f"critical-path attribution too low: {frac:.3f} < "
            f"{PR09_ATTRIBUTION_MIN} of the ready-wall explained by named "
            "phases (a new unnamed phase crept into the hot path?)")
    overhead = results["tracing_overhead_fraction"]
    if overhead > PR09_OVERHEAD_MAX:
        out.append(
            f"tracing overhead regressed: {100 * overhead:.1f}% > "
            f"{100 * PR09_OVERHEAD_MAX:.0f}% wall vs disabled")
    return out


# ----------------------------------------------------- worker-constrained wave

async def bench_constrained_wave(n_claims: int = 200, workers: int = 8,
                                 blocking: bool = False,
                                 create_latency: float = 0.4) -> dict:
    """The PR 4 scenario: ``n_claims`` through a lifecycle pool squeezed to
    ``workers`` with slow simulated LROs. Blocking mode pins one worker per
    create for the whole LRO + node wait (wave throughput bounded by worker
    count); tracker mode frees the worker after ``begin_create`` (throughput
    bounded by cloud latency). Reports latency, pinned-worker-seconds, and
    the wave-wide poll-call shape."""
    from gpu_provisioner_tpu.apis.karpenter import NodeClaim
    from gpu_provisioner_tpu.controllers.lifecycle import LifecycleOptions
    from gpu_provisioner_tpu.controllers.termination import TerminationOptions
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.fake import make_nodeclaim

    opts = EnvtestOptions(
        create_latency=create_latency, delete_latency=0.05,
        node_join_delay=0.0, node_ready_delay=0.0,
        node_wait_interval=0.02, node_wait_attempts=600,
        gc_interval=5.0, leak_grace=5.0,
        max_concurrent_reconciles=workers,
        blocking_create=blocking,
        lifecycle=LifecycleOptions(termination_requeue=0.2,
                                   registration_requeue=0.2,
                                   inprogress_requeue=0.2),
        termination=TerminationOptions(requeue=0.2, instance_requeue=0.2),
        # measurement at saturation: stall gate off, leak gate stays on
        stall_budget=0.0)
    async with Env(opts) as env:
        # pinned-worker-seconds: total wall time lifecycle workers spend
        # INSIDE reconcile — the resource the blocking shape burns (a
        # parked worker is pinned; a requeued claim costs nothing)
        pinned = {"seconds": 0.0}
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        prev_hook = lifecycle._metrics_hook

        def hook(name, duration, err):
            pinned["seconds"] += duration
            if prev_hook is not None:
                prev_hook(name, duration, err)
        lifecycle.set_metrics_hook(hook)

        async def provision(i: int) -> float:
            t = time.perf_counter()
            await env.client.create(make_nodeclaim(f"cw{i:04d}", "tpu-v5e-8",
                                                   workspace=f"ws{i}"))
            await env.wait_ready(f"cw{i:04d}", timeout=600, poll=0.1)
            return time.perf_counter() - t

        t0 = time.perf_counter()
        readies = await asyncio.gather(*(provision(i)
                                         for i in range(n_claims)))
        ready_wall = time.perf_counter() - t0
        ready_pinned = pinned["seconds"]
        # poll-call shape at the end of the up-wave: point gets + batched
        # lists + client-side LRO polls (operations.get against a real API)
        np_calls = env.cloud.nodepools.calls
        polls = {k: np_calls.get(k, 0)
                 for k in ("get", "list", "operation_poll")}

        t1 = time.perf_counter()
        for i in range(n_claims):
            await env.client.delete(NodeClaim, f"cw{i:04d}")
        await asyncio.gather(*(env.wait_gone(f"cw{i:04d}", timeout=600)
                               for i in range(n_claims)))
        delete_wall = time.perf_counter() - t1
        leaked = len(env.cloud.nodepools.pools)
        total_pinned = pinned["seconds"]
    return {
        "claims": n_claims,
        "workers": workers,
        "blocking": blocking,
        "create_latency_s": create_latency,
        "ready_p50_s": round(statistics.median(readies), 4),
        "ready_p95_s": round(_pctl(readies, 0.95), 4),
        "ready_wall_s": round(ready_wall, 3),
        "delete_wall_s": round(delete_wall, 3),
        "pinned_worker_seconds_ready": round(ready_pinned, 3),
        "pinned_worker_seconds_total": round(total_pinned, 3),
        "poll_calls": polls,
        "poll_calls_total": sum(polls.values()),
        "leaked_pools": leaked,
    }


async def run_constrained(n_claims: int, workers: int = 8) -> dict:
    before = await bench_constrained_wave(n_claims, workers, blocking=True)
    after = await bench_constrained_wave(n_claims, workers, blocking=False)
    return {
        "bench": "nonblocking-provisioning",
        "pr": 4,
        "before": before,
        "after": after,
        "ready_wall_speedup": round(
            before["ready_wall_s"] / max(after["ready_wall_s"], 1e-9), 2),
        "pinned_worker_reduction": round(
            before["pinned_worker_seconds_total"]
            / max(after["pinned_worker_seconds_total"], 1e-9), 2),
        "poll_call_reduction": round(
            before["poll_calls_total"] / max(after["poll_calls_total"], 1),
            2),
    }


def make_pr04_budget(results: dict) -> dict:
    """3× headroom over the tracker-mode measurement (both ceilings scale
    with wall clock — the gate catches a reintroduced per-operation polling
    loop or worker-pinning path, not a slow CI box)."""
    after = results["after"]
    return {
        "constrained_wave_poll_calls": 3 * after["poll_calls_total"],
        "constrained_wave_pinned_worker_seconds": round(
            3.0 * after["pinned_worker_seconds_total"], 1),
    }


def check_pr04_budget(results: dict, recorded: dict) -> list[str]:
    budget = recorded.get("budget", {})
    after = results["after"]
    out: list[str] = []
    ceiling = budget.get("constrained_wave_poll_calls")
    if ceiling is not None and after["poll_calls_total"] > ceiling:
        out.append(
            f"constrained wave poll calls regressed: "
            f"{after['poll_calls_total']} > budget {ceiling} "
            "(per-operation polling back?)")
    ceiling = budget.get("constrained_wave_pinned_worker_seconds")
    if ceiling is not None and \
            after["pinned_worker_seconds_total"] > ceiling:
        out.append(
            f"constrained wave pinned-worker-seconds regressed: "
            f"{after['pinned_worker_seconds_total']} > budget {ceiling} "
            "(workers parked inside reconcile again?)")
    return out


# ------------------------------------------------------------------- budget

def check_budget(results: dict, recorded: dict) -> list[str]:
    """Compare a fresh measurement against the budget block recorded in
    BENCH_pr02.json. Returns human-readable violations (empty == pass)."""
    budget = recorded.get("budget", {})
    out: list[str] = []
    gc_after = results["gc_pass"]["after"]
    if budget.get("gc_pass_kube_lists") is not None and \
            gc_after["kube_lists_total"] > budget["gc_pass_kube_lists"]:
        out.append(
            f"gc pass kube lists regressed: {gc_after['kube_lists_total']} > "
            f"budget {budget['gc_pass_kube_lists']} (per-pool lists back?)")
    if budget.get("gc_pass_cloud_calls") is not None and \
            sum(gc_after["cloud_calls"].values()) > budget["gc_pass_cloud_calls"]:
        out.append(
            f"gc pass cloud calls regressed: {sum(gc_after['cloud_calls'].values())} "
            f"> budget {budget['gc_pass_cloud_calls']}")
    wave = results.get("wave")
    if wave and budget.get("wave_cloud_calls_per_claim") is not None:
        per_claim = wave["cloud_calls_total"] / wave["claims"]
        if per_claim > budget["wave_cloud_calls_per_claim"]:
            out.append(
                f"wave cloud calls regressed: {per_claim:.1f}/claim > "
                f"budget {budget['wave_cloud_calls_per_claim']}/claim")
    return out


async def run(n_claims: int, n_pools: int, with_wave: bool = True) -> dict:
    before = await bench_gc_pass(n_pools, legacy=True)
    after = await bench_gc_pass(n_pools, legacy=False)
    results: dict = {
        "bench": "provisioning-fast-path",
        "pr": 2,
        "kube_rtt_s": KUBE_RTT_S,
        "gc_pass": {
            "before": before,
            "after": after,
            "wall_speedup": round(before["wall_s"] / max(after["wall_s"],
                                                         1e-9), 2),
            "list_path_call_reduction": round(
                before["list_path_calls"] / max(after["list_path_calls"], 1),
                2),
        },
    }
    if with_wave:
        results["wave"] = await bench_wave(n_claims)
    return results


def make_budget(results: dict) -> dict:
    """Derive the regression budget from a fresh measurement: exact for the
    deterministic gc-pass counts; 3× headroom for the wave totals, which
    scale with wall clock (requeue polling during the ready window) — the
    gate must catch O(n) regressions like a reintroduced hot loop, not a
    loaded CI machine doubling the wave's duration."""
    after = results["gc_pass"]["after"]
    budget = {
        "gc_pass_kube_lists": after["kube_lists_total"],
        "gc_pass_cloud_calls": sum(after["cloud_calls"].values()),
    }
    wave = results.get("wave")
    if wave:
        budget["wave_cloud_calls_per_claim"] = round(
            3.0 * wave["cloud_calls_total"] / wave["claims"], 1)
    return budget


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--claims", type=int, default=100)
    ap.add_argument("--pools", type=int, default=100)
    ap.add_argument("--constrained-claims", type=int, default=200)
    ap.add_argument("--workers", type=int, default=8,
                    help="lifecycle worker pool for the constrained wave")
    ap.add_argument("--fast", action="store_true",
                    help="small sizes for smoke runs")
    ap.add_argument("--no-wave", action="store_true")
    ap.add_argument("--no-constrained", action="store_true",
                    help="skip the PR 4 worker-constrained wave")
    ap.add_argument("--write", action="store_true",
                    help="rewrite BENCH_pr02.json with fresh numbers+budget")
    ap.add_argument("--write-pr04", action="store_true",
                    help="rewrite BENCH_pr04.json with fresh numbers+budget")
    ap.add_argument("--trace", action="store_true",
                    help="traced wave only: print the critical-path "
                         "attribution summary and exit")
    ap.add_argument("--trace-smoke", action="store_true",
                    help="small traced wave for make verify "
                         "(attribution gate only, no overhead baseline)")
    ap.add_argument("--no-traced", action="store_true",
                    help="skip the PR 9 traced-wave attribution/overhead "
                         "gates")
    ap.add_argument("--write-pr09", action="store_true",
                    help="rewrite BENCH_pr09.json with fresh numbers")
    args = ap.parse_args(argv)
    if args.fast:
        args.claims, args.pools = 10, 20
        args.constrained_claims = 24

    if args.trace or args.trace_smoke:
        from gpu_provisioner_tpu.observability import render_attribution
        n = 12 if args.trace_smoke else args.claims
        res = asyncio.run(bench_traced_wave(n, tracing=True))
        if res["attribution"] is None:
            print("no attribution: no traced claim reached ready",
                  file=sys.stderr)
            return 1
        print(render_attribution(res["attribution"]))
        frac = res["attribution"]["attributed_fraction"]
        if frac < PR09_ATTRIBUTION_MIN:
            print(f"TRACE GATE: attributed fraction {frac:.3f} < "
                  f"{PR09_ATTRIBUTION_MIN}", file=sys.stderr)
            return 1
        print(f"attribution OK: {100 * frac:.1f}% of the "
              f"{res['ready_wall_s']}s ready-wall named "
              f"({n} claims)", file=sys.stderr)
        return 0

    results = asyncio.run(run(args.claims, args.pools,
                              with_wave=not args.no_wave))
    print(json.dumps(results, indent=2))

    rc = 0
    if args.write:
        results["budget"] = make_budget(results)
        BENCH_FILE.write_text(json.dumps(results, indent=2) + "\n")
        print(f"wrote {BENCH_FILE}", file=sys.stderr)
    elif BENCH_FILE.exists():
        recorded = json.loads(BENCH_FILE.read_text())
        violations = check_budget(results, recorded)
        for v in violations:
            print(f"BUDGET REGRESSION: {v}", file=sys.stderr)
        if violations:
            rc = 1
        else:
            print("cloud-call budget OK "
                  f"(recorded in {BENCH_FILE.name})", file=sys.stderr)

    if args.no_constrained:
        return rc

    pr04 = asyncio.run(run_constrained(args.constrained_claims,
                                       args.workers))
    print(json.dumps(pr04, indent=2))
    if args.write_pr04:
        pr04["budget"] = make_pr04_budget(pr04)
        BENCH_PR04_FILE.write_text(json.dumps(pr04, indent=2) + "\n")
        print(f"wrote {BENCH_PR04_FILE}", file=sys.stderr)
    elif BENCH_PR04_FILE.exists():
        recorded = json.loads(BENCH_PR04_FILE.read_text())
        violations = check_pr04_budget(pr04, recorded)
        for v in violations:
            print(f"BUDGET REGRESSION: {v}", file=sys.stderr)
        if violations:
            rc = 1
        else:
            print("constrained-wave budget OK "
                  f"(recorded in {BENCH_PR04_FILE.name})", file=sys.stderr)

    if args.no_traced:
        return rc

    pr09 = asyncio.run(run_pr09(args.claims))
    print(json.dumps(pr09, indent=2))
    violations = check_pr09(pr09)
    for v in violations:
        print(f"CLAIMTRACE GATE: {v}", file=sys.stderr)
    if violations:
        rc = 1
    else:
        print(f"claimtrace gates OK (attribution "
              f"{pr09['attribution']['attributed_fraction']:.3f}, overhead "
              f"{100 * pr09['tracing_overhead_fraction']:+.1f}%)",
              file=sys.stderr)
    if args.write_pr09:
        BENCH_PR09_FILE.write_text(json.dumps(pr09, indent=2) + "\n")
        print(f"wrote {BENCH_PR09_FILE}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
