{{- define "tpu-provisioner.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "tpu-provisioner.fullname" -}}
{{- if .Values.fullnameOverride -}}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- printf "%s" (include "tpu-provisioner.name" .) | trunc 63 | trimSuffix "-" -}}
{{- end -}}
{{- end -}}

{{- define "tpu-provisioner.labels" -}}
helm.sh/chart: {{ printf "%s-%s" .Chart.Name .Chart.Version }}
app.kubernetes.io/name: {{ include "tpu-provisioner.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "tpu-provisioner.selectorLabels" -}}
app.kubernetes.io/name: {{ include "tpu-provisioner.name" . }}
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end -}}

{{- define "tpu-provisioner.serviceAccountName" -}}
{{- default (include "tpu-provisioner.fullname" .) .Values.serviceAccount.name -}}
{{- end -}}
