"""End-to-end serving example: provisioned slice → tp mesh → KV-cache serve.

Run it anywhere (defaults to a CPU mesh when no TPU slice is attached):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/workloads/serve.py

On a provisioner-created slice the same script bootstraps from the stamped
node labels exactly like train_resume.py (TPU_KAITO_BOOTSTRAP=auto).

Demonstrates the serving surface KAITO provisions slices for:
  1. tensor-parallel weight placement on the mesh (cache shards with them)
  2. one-shot generation: fresh-cache flash prefill + lax.scan decode,
     greedy and sampled (temperature / top-k / top-p)
  3. multi-turn chat shape: prefill turn 1 → decode → prefill turn 2
     against the partially-filled cache (the cache-aware flash kernel path
     when the shapes tile — models/decode.py:_cached_attention)
"""

from __future__ import annotations

import os
from dataclasses import replace

import jax
import jax.numpy as jnp

from jax.sharding import NamedSharding

from gpu_provisioner_tpu.models.decode import (cached_forward, generate,
                                               init_kv_cache, kv_cache_specs)
from gpu_provisioner_tpu.models.llama import PRESETS, init_params
from gpu_provisioner_tpu.models.train import shard_params
from gpu_provisioner_tpu.parallel import make_mesh


def get_mesh():
    if os.environ.get("TPU_KAITO_BOOTSTRAP", "") == "auto":
        import asyncio

        from gpu_provisioner_tpu.parallel import bootstrap
        asyncio.run(bootstrap.bootstrap())
        return make_mesh(len(jax.devices()), tp=2)
    n = min(8, len(jax.devices()))
    return make_mesh(n, tp=2 if n % 2 == 0 else 1)


def main():
    cfg = replace(PRESETS["tiny"], max_seq_len=512)
    mesh = get_mesh()
    params = shard_params(init_params(jax.random.key(0), cfg), mesh, cfg)
    print(f"serving on mesh {dict(mesh.shape)}")

    # real tokens from [1, vocab): 0 is the ragged demo's pad id and must
    # not occur in prompts (a leading real 0 would be miscounted as pad)
    prompt = jax.random.randint(jax.random.key(1), (2, 16), 1,
                                cfg.vocab_size)

    # one-shot: greedy and sampled generation (single compiled scan each)
    greedy = generate(params, prompt, cfg, max_new_tokens=8)
    sampled = generate(params, prompt, cfg, max_new_tokens=8,
                       temperature=0.8, top_k=32, top_p=0.95,
                       key=jax.random.key(7))
    print("greedy :", greedy[0].tolist())
    print("sampled:", sampled[0].tolist())

    # ragged batch: left-pad mixed-length prompts (pad_id), finish rows at
    # eos (eos_id), report per-token logprobs — each padded row generates
    # exactly what it would alone
    short = prompt[:1, :6]
    ragged = jnp.concatenate(
        [jnp.concatenate([jnp.zeros((1, 10), short.dtype), short], 1),
         prompt[1:, :16]], 0)
    out, lps = generate(params, ragged, cfg, max_new_tokens=8, pad_id=0,
                        eos_id=int(greedy[0, -1]), return_logprobs=True)
    print("ragged :", out.tolist())
    print("logprob:", [round(float(x), 2) for x in lps[0]])

    # memory-constrained serving: int8 cache (half the HBM) — same API
    cfg8 = replace(cfg, kv_cache_dtype="int8")
    out8 = generate(params, prompt, cfg8, max_new_tokens=8)
    print("int8   :", out8[0].tolist())

    # multi-turn: turn-1 prefill → decode 2 → turn-2 prefill continues the
    # SAME cache (flash-kernel path for block-sized turns under
    # attn_impl="flash"; exact dense path otherwise)
    # place the cache with the weights' tp layout (kv heads over ``model``)
    # — at real max_len this is the memory win of tp-sharding the cache
    cache = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        init_kv_cache(cfg, 2, 256), kv_cache_specs(cfg))
    logits, cache = jax.jit(cached_forward, static_argnums=3)(
        params, prompt, cache, cfg)
    tok = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
    for _ in range(2):
        logits, cache = jax.jit(cached_forward, static_argnums=3)(
            params, tok, cache, cfg)
        tok = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
    turn2 = jax.random.randint(jax.random.key(2), (2, 16), 0, cfg.vocab_size)
    logits, cache = jax.jit(cached_forward, static_argnums=3)(
        params, turn2, cache, cfg)
    assert int(cache.length) == 16 + 2 + 16
    print(f"multi-turn cache length: {int(cache.length)}")

    # MoE family: the SAME generate() serves a Mixtral-style model —
    # routing is dropless per decode step, pad rows claim no expert
    # capacity (models/moe_serve.py)
    from gpu_provisioner_tpu.models.moe import (PRESETS_MOE,
                                                init_moe_model)
    moe_cfg = PRESETS_MOE["tiny-moe"]
    moe_params = init_moe_model(jax.random.key(3), moe_cfg)
    moe_prompt = jax.random.randint(jax.random.key(4), (2, 12), 1,
                                    moe_cfg.vocab_size)
    moe_out = generate(moe_params, moe_prompt, moe_cfg, max_new_tokens=8,
                       max_len=64)
    print("moe    :", moe_out[0].tolist())

    # sliding-window serving (Mistral-style): O(window) cache DMA per
    # step at any context length — same generate(), one config knob
    swa_cfg = replace(cfg, sliding_window=8)
    swa_out = generate(params, prompt, swa_cfg, max_new_tokens=8)
    print("swa    :", swa_out[0].tolist())

    # speculative decoding: a draft proposes, the target verifies — the
    # emitted stream is EXACTLY plain greedy's. BATCHED: per-row
    # acceptance via per-row cache lengths (here self-draft: every
    # proposal accepted, so target calls collapse ~5x)
    from gpu_provisioner_tpu.models.speculative import speculative_generate
    spec_out, stats = speculative_generate(
        params, params, prompt, cfg, cfg, max_new_tokens=8, spec_k=4)
    assert (spec_out == greedy[:, :8]).all()
    print(f"spec   : {spec_out[0].tolist()} "
          f"(target calls: {int(stats['target_calls'])} for 8 tokens/row)")

    # continuous batching: a STREAM of ragged requests through slot rows —
    # each request's tokens equal its solo stream; give the engine a
    # draft and every step is one speculative round across all slots
    from gpu_provisioner_tpu.models.engine import ServeEngine
    eng = ServeEngine(params, cfg, slots=2, max_len=128,
                      prefill_buckets=(16, 32),
                      draft_params=params, draft_cfg=cfg, spec_k=3)
    rids = [eng.submit(prompt[0, :n].tolist(), new)
            for n, new in ((9, 6), (16, 8), (12, 5))]   # 3 reqs, 2 slots
    served = eng.run()
    assert served[rids[1]] == greedy[0, :8].tolist()    # == solo stream
    print(f"engine : {len(served)} requests served; "
          f"req1 {served[rids[1]]}")
    print("done")


if __name__ == "__main__":
    main()
