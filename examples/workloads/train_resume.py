"""End-to-end workload example: provisioned slice → mesh → train → resume.

Run it anywhere (defaults to a CPU mesh when no TPU slice is attached):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/workloads/train_resume.py

On a provisioner-created slice (see jobset-multislice.yaml for the pod
wiring), the same script bootstraps jax.distributed from the node labels
the provisioner stamped — no manual env — and shards over every axis the
attached topology supports.

Demonstrates the full loop a production trainer needs:
  1. topology bootstrap (parallel/bootstrap.py) or explicit local mesh
  2. sharded init + train steps (tensor/sequence parallel per the mesh)
  3. periodic checkpointing (models/checkpoint.py)
  4. crash + resume onto a *different* mesh layout (restore reshards)
"""

from __future__ import annotations

import os
import tempfile

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from gpu_provisioner_tpu.models.checkpoint import (restore_train_state,
                                                   save_train_state)
from gpu_provisioner_tpu.models.llama import PRESETS
from gpu_provisioner_tpu.models.train import (BATCH_SPEC, default_optimizer,
                                              make_train_state,
                                              make_train_step)
from gpu_provisioner_tpu.parallel import make_mesh

CFG = PRESETS["tiny"]
STEPS, SAVE_EVERY = 6, 3


def get_mesh():
    """On a slice: bootstrap from provisioner labels. Locally: 8-way dp."""
    if os.environ.get("TPU_KAITO_BOOTSTRAP", "") == "auto":
        import asyncio

        from gpu_provisioner_tpu.parallel import bootstrap
        # node labels → SliceTopology → jax.distributed.initialize
        asyncio.run(bootstrap.bootstrap())
        return make_mesh(len(jax.devices()))
    return make_mesh(min(8, len(jax.devices())))


def batch(mesh, step_idx):
    toks = jax.random.randint(jax.random.key(100 + step_idx),
                              (8, CFG.max_seq_len // 32 + 1), 0,
                              CFG.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    return put(toks[:, :-1]), put(toks[:, 1:])


def main():
    ckdir = tempfile.mkdtemp(prefix="tpu-train-")
    opt = default_optimizer()

    mesh = get_mesh()
    print(f"mesh: {dict(mesh.shape)}")
    params, opt_state, _ = make_train_state(jax.random.key(0), CFG, mesh,
                                            optimizer=opt)
    step_fn = make_train_step(mesh, CFG, opt)

    done = 0
    for i in range(STEPS):
        params, opt_state, loss = step_fn(params, opt_state, *batch(mesh, i))
        done = i + 1
        print(f"step {done}: loss {float(loss):.4f}")
        if done % SAVE_EVERY == 0:
            save_train_state(f"{ckdir}/step{done}", params, opt_state, done)
            print(f"checkpointed at step {done}")
        if done == SAVE_EVERY:
            break                        # simulate preemption mid-run

    # --- "repair replaced the slice": resume on a DIFFERENT layout --------
    n = len(mesh.devices.flatten())
    mesh2 = make_mesh(n, tp=2) if n >= 2 else mesh
    print(f"resuming on mesh: {dict(mesh2.shape)}")
    params, opt_state, start = restore_train_state(
        f"{ckdir}/step{SAVE_EVERY}", mesh2, CFG, opt)
    step_fn2 = make_train_step(mesh2, CFG, opt)
    for i in range(start, STEPS):
        params, opt_state, loss = step_fn2(params, opt_state,
                                           *batch(mesh2, i))
        print(f"step {i + 1} (resumed): loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()
