"""gpu_provisioner_tpu — a TPU-native accelerator provisioner.

A from-scratch rebuild of the capabilities of Azure/gpu-provisioner (a Karpenter
``CloudProvider`` materializing AKS GPU agent pools for the KAITO operator —
see SURVEY.md) re-designed for Google Cloud TPUs: NodeClaim custom resources
resolve through an accelerator catalog to GKE TPU node pools / Cloud TPU slices
(v4/v5e/v5p/v6e, single-chip through multi-host), with slice-topology labels
propagated so JAX/XLA workloads can bootstrap ``jax.distributed`` and build a
device mesh over ICI/DCN.

Package map (control plane → workload; subpackages land incrementally — see
git history for what is already built):

- ``apis``            Kubernetes-style API types: karpenter.sh/v1 NodeClaim,
                      kaito.sh/v1alpha1 KaitoNodeClass, core/v1 subset.
- ``scheduling``      Requirement/label/taint algebra used to resolve NodeClaims.
- ``catalog``         The TPU accelerator catalog (requirements → slice shape).
- ``runtime``         From-scratch controller runtime: object store with watch
                      semantics, client, rate-limited workqueue, manager.
- ``cloudprovider``   CloudProvider contract, error taxonomy, metrics decorator,
                      and the TPU implementation.
- ``providers``       Instance provider (NodeClaim ⇄ node-pool mapping) and the
                      narrow GKE/Cloud-TPU client seams + LRO helpers.
- ``controllers``     NodeClaim lifecycle, node termination, node health/repair,
                      bidirectional garbage collection.
- ``operator``        Process runtime: options, logging, probes, metrics server.
- ``auth``            GCP credential plumbing (ADC / metadata / federated token).
- ``fake``            Fault-injecting fakes for the cloud APIs and cluster.
- ``parallel``        Workload side: topology labels → jax Mesh, distributed init.
- ``ops``             TPU compute primitives (rmsnorm, rope, attention, pallas).
- ``models``          KAITO-servable model families (Llama, ...) with sharded
                      train/infer steps.
"""

__version__ = "0.1.0"
