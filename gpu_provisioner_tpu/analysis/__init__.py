"""provlint: project-specific static analysis + runtime detectors.

PRs 1-5 hardened the provisioner with invariants that lived only in review
comments: fence checks before cloud mutations (PR 3), never swallowing
``asyncio.CancelledError``/``SimulatedCrash`` (the PR 5 bpo-42130 teardown
hang), injected clocks in controllers, a never-blocked event loop (the
BENCH_NOTES r04/r05 scaling ceiling), tracked background tasks (the PR 4/5
tracker-poller bug class). This package makes them mechanical:

- :mod:`.provlint` — the AST engine: rule registry, the inline-waiver
  comment syntax (``provlint: disable=<rule> — <reason>``), file walking,
  CLI.
- :mod:`.rules` — the project rule catalog (see docs/STATIC_ANALYSIS.md).
- :mod:`.detectors` — runtime enforcement wired into envtest: the
  event-loop stall detector and the background task/thread leak gate.

Run it: ``python -m gpu_provisioner_tpu.analysis [paths...]`` or
``make lint``.
"""

from .detectors import (
    EventLoopStallError, StallDetector, TaskLeakError, ThreadLeakError,
)
from .provlint import Finding, lint_file, lint_paths, main
from .rules import RULES

__all__ = [
    "EventLoopStallError", "Finding", "RULES", "StallDetector",
    "TaskLeakError", "ThreadLeakError", "lint_file", "lint_paths", "main",
]
