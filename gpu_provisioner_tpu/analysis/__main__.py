"""``python -m gpu_provisioner_tpu.analysis`` — run provlint."""

import sys

from .provlint import main

if __name__ == "__main__":
    sys.exit(main())
