"""Runtime detectors: the dynamic half of provlint, wired into envtest.

Static rules catch the *patterns* that block the loop or leak tasks; these
detectors catch the *instances* the rules can't see (blocking work behind a
seam, a task spawned by third-party code, a teardown path that forgot one
component). Both are armed by default in :class:`~..envtest.Env`:

- :class:`StallDetector` — a sentinel coroutine sleeps ``interval`` seconds
  and measures how late the loop woke it. Oversleep beyond scheduler noise
  means something held the loop — ``time.sleep``, sync I/O, a pathological
  CPU section. The worst stall is checked against a budget at Env teardown
  and raises :class:`EventLoopStallError` (BENCH_NOTES r04/r05: the single
  event loop IS the scaling ceiling; blocking it is the one unforgivable
  sin here).
- Task/thread leak gate — the PR 4 tracker-only "poller outlived its Env"
  check, generalized: every component's background-task seam is enumerated
  at teardown and any survivor raises :class:`TaskLeakError`
  (:class:`ThreadLeakError` for threads). Scoped to the Env's OWN
  components so a RestartableEnv zombie's rival incarnation — deliberately
  kept alive in failover soaks — never false-positives.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Iterable, Optional


class EventLoopStallError(RuntimeError):
    """The event loop was blocked longer than the stall budget."""


class TaskLeakError(RuntimeError):
    """A component's background task outlived its Env."""


class ThreadLeakError(RuntimeError):
    """A non-daemon thread started during the Env outlived it."""


class StallDetector:
    """Measure event-loop responsiveness via sentinel-sleep overshoot.

    ``worst`` is the largest observed stall (seconds the loop was held
    beyond the sentinel's requested sleep); ``stalls`` records every
    observation above ``budget``. ``check()`` raises when the budget was
    exceeded — callers decide *when* to fail (envtest: at teardown, so the
    stall surfaces as a test failure with the worst offender's timing).
    """

    def __init__(self, budget: float = 1.0, interval: float = 0.05):
        self.budget = budget
        self.interval = interval
        self.worst = 0.0
        self.stalls: list[tuple[float, float]] = []   # (loop time, lag)
        self._task: Optional[asyncio.Task] = None
        # fired (synchronously, with the lag) the moment an over-budget
        # stall is OBSERVED — the flight recorder's stall trigger, so the
        # diagnostic bundle snapshots live state instead of waiting for the
        # teardown-time check() to fail the test after the evidence is gone.
        self.on_stall = None

    def _notify(self, lag: float) -> None:
        if self.on_stall is not None:
            try:
                self.on_stall(lag)
            except Exception:  # noqa: BLE001 — a broken hook must not
                pass           # crash the sentinel loop

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(
                self._run(), name="provlint-stall-detector")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        last = loop.time()
        while True:
            await asyncio.sleep(self.interval)
            now = loop.time()
            lag = now - last - self.interval
            if lag > self.worst:
                self.worst = lag
            if lag > self.budget:
                self.stalls.append((now, lag))
                self._notify(lag)
            last = now

    def check(self) -> None:
        if self.worst > self.budget:
            raise EventLoopStallError(
                f"event loop blocked for {self.worst:.3f}s "
                f"(budget {self.budget:.3f}s, {len(self.stalls)} stall(s) "
                f"over budget) — something ran sync work on the loop; see "
                f"docs/STATIC_ANALYSIS.md (stall detector)")


def _task_label(task: asyncio.Task) -> str:
    name = task.get_name()
    coro = getattr(task, "get_coro", lambda: None)()
    code = getattr(coro, "cr_code", None)
    where = f" ({code.co_filename}:{code.co_firstlineno})" if code else ""
    return f"{name}{where}"


def alive_tasks(named: Iterable[tuple[str, Optional[asyncio.Task]]]
                ) -> list[str]:
    """Filter a (component, task) enumeration down to survivors, rendered
    for the error message."""
    return [f"{component}: {_task_label(t)}"
            for component, t in named
            if t is not None and not t.done()]


def check_no_leaked_tasks(named: Iterable[tuple[str, Optional[asyncio.Task]]],
                          who: str = "Env") -> None:
    leaked = alive_tasks(named)
    if leaked:
        raise TaskLeakError(
            f"{len(leaked)} background task(s) outlived their {who}: "
            + "; ".join(leaked))


def thread_snapshot() -> set[int]:
    return {t.ident for t in threading.enumerate() if t.ident is not None}


def check_no_leaked_threads(before: set[int], who: str = "Env") -> None:
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.is_alive() and not t.daemon]
    if leaked:
        raise ThreadLeakError(
            f"{len(leaked)} non-daemon thread(s) started during the {who} "
            f"outlived it: {[t.name for t in leaked]}")
