"""provgraph: whole-program invariant analysis over the package graph.

provlint (PL001–PL014) is one parsed module at a time — by design: each
rule is a pure function over a single file, so the fixture corpus can drive
any rule against any snippet. But every ordering/architecture bug PR 11
root-caused crossed a module boundary, and the invariants that guard those
bugs are *relations between files*: an import edge, a declared wake with a
producer somewhere else, a fence check in the caller of a mutating helper,
a metric name and its doc entry. provgraph is the second analysis
generation for exactly those: it builds one :class:`ProgramGraph` over the
package (import edges, a module-local call graph, wake annotations and
producers, metric-name literals) and runs interprocedural rules against it.

Rules (docs/STATIC_ANALYSIS.md#provgraph has the full catalog):

- **PG001 layering-violation** — the paper's L1–L5 layer map (SURVEY §1)
  as an enforced DAG: ``runtime/`` imports nothing above itself;
  ``controllers/``, ``cloudprovider/`` and ``runtime/`` never import the
  cloud-specific modules (``providers/gcp.py``, ``providers/rest.py`` —
  the ROADMAP item-4 provider seam); ``providers/`` never imports
  ``controllers/``; nothing imports ``operator/`` (the composition root).
- **PG002 unproduced-wake-edge** — every ``# wakes: <source>`` annotation
  (the PL014 contract at a ``requeue_after`` site) must have at least one
  producer call site somewhere in the package that wakes with that source:
  ``WakeHub.wake()/wake_after()``, ``Controller.inject``, a workqueue
  enqueue ``source=...``, or a watch registered with ``wake_source=...``.
  A declared-but-unproduced edge is the silent timer-only-path bug class
  PR 11 killed.
- **PG003 unfenced-mutation-path** — interprocedural PL003: a call into a
  helper that (transitively) issues a cloud mutation without its own fence
  check must itself be preceded by a fence check in the caller. PL003 only
  sees the function containing the ``begin_create``; a helper that waives
  PL003 with "caller holds the fence" is exactly what this rule audits.
- **PG004 metrics-docs-drift** — every ``tpu_provisioner_*`` metric-name
  literal in code appears in docs/OBSERVABILITY.md, and every
  ``tpu_provisioner_*`` name the doc claims exists in code.

Waivers use the provlint grammar with the ``provgraph`` tag::

    from ..providers.gcp import parse_op  # provgraph: disable=PG001 — <why>

The reason is mandatory; a malformed waiver is a **PG000** finding. The
whole-tree run (``make lint`` / ``python -m
gpu_provisioner_tpu.analysis.provgraph``) must be clean — zero unwaived
findings — the same gate contract as provlint's.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Callable, Iterable, Optional

from .provlint import (
    FIXTURE_DIR, Finding, _comment_lines, _display, dotted_name,
    parse_waivers,
)
from .rules import _is_cloud_mutation, _is_fence_call

WAIVER_TAG = "provgraph"
DEFAULT_DOC = "docs/OBSERVABILITY.md"

# ------------------------------------------------------------------- graph


@dataclasses.dataclass
class ModuleInfo:
    name: str                    # dotted: gpu_provisioner_tpu.runtime.informer
    path: Path
    display: str
    source: str
    lines: list[str]
    tree: ast.Module
    is_package: bool             # __init__.py


@dataclasses.dataclass(frozen=True)
class ImportEdge:
    src: str                     # importing module (dotted)
    dst: str                     # imported module (dotted, absolute)
    line: int


@dataclasses.dataclass
class FunctionInfo:
    qual: str                    # "module:Class.method" / "module:func"
    module: str
    display: str
    line: int
    mutation_lines: list[int] = dataclasses.field(default_factory=list)
    fence_lines: list[int] = dataclasses.field(default_factory=list)
    # module-local calls this function makes: (callee qual, line) — only
    # self.method() within the same class and bare module-function calls
    # resolve (anything dynamic is out of scope, documented)
    calls: list[tuple[str, int]] = dataclasses.field(default_factory=list)


@dataclasses.dataclass(frozen=True)
class WakeAnnotation:
    module: str
    display: str
    line: int
    source: str


@dataclasses.dataclass
class ProgramGraph:
    """Everything the interprocedural rules consume, built in one pass."""

    package: str
    root: Path
    modules: dict[str, ModuleInfo]
    import_edges: list[ImportEdge]
    functions: dict[str, FunctionInfo]
    wake_annotations: list[WakeAnnotation]
    wake_producers: set[str]            # resolved source values produced
    metric_literals: list[tuple[str, str, int]]   # (name, display, line)
    doc_path: Optional[Path]
    doc_display: str
    doc_metrics: dict[str, int]         # name -> first line in the doc

    def segment(self, module: str) -> str:
        """First path segment under the package root ('' for the root
        module itself): 'runtime', 'controllers', 'transport', ..."""
        parts = module.split(".")
        return parts[1] if len(parts) > 1 else ""


_WAKES_SRC_RE = re.compile(r"#\s*wakes:\s*([A-Za-z][\w-]*)")
_METRIC_RE = re.compile(r"tpu_provisioner_[a-z0-9_]+")
# doc-side mentions may carry alternation and label-selector braces:
# `tpu_provisioner_workqueue_{depth,delayed}` / `..._wakes_total{source}`
_DOC_METRIC_RE = re.compile(r"tpu_provisioner_[a-z0-9_{},]+")


def _module_name(root: Path, f: Path) -> tuple[str, bool]:
    rel = f.relative_to(root.parent)
    parts = list(rel.parts)
    is_pkg = parts[-1] == "__init__.py"
    if is_pkg:
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts), is_pkg


def _resolve_from(mod: ModuleInfo, node: ast.ImportFrom) -> Optional[str]:
    """Absolute dotted target of a (possibly relative) ``from X import``."""
    if node.level == 0:
        return node.module
    base = mod.name.split(".")
    if not mod.is_package:
        base = base[:-1]
    drop = node.level - 1
    if drop:
        base = base[:-drop] if drop < len(base) else []
    if not base:
        return None  # relative import escaping the package — not our edge
    return ".".join(base + (node.module.split(".") if node.module else []))


def _expand_doc_token(token: str) -> list[str]:
    """``a_{x,y}_b{label}`` → ``[a_x_b, a_y_b]``: comma-braces are
    alternation (the doc's shorthand for metric families that differ in one
    segment), comma-less braces are label selectors and are stripped."""
    m = re.search(r"\{([^{}]*,[^{}]*)\}", token)
    if m:
        out: list[str] = []
        for alt in m.group(1).split(","):
            out.extend(_expand_doc_token(
                token[:m.start()] + alt.strip() + token[m.end():]))
        return out
    return [re.sub(r"\{[^{}]*\}", "", token)]


def _source_values(mod_imports: "_ImportTable", expr: ast.AST,
                   consts: dict[str, str]) -> list[str]:
    """Resolvable wake-source value(s) of an argument expression. String
    literals and ``SOURCE_*`` constants resolve; ``a or b`` yields every
    resolvable arm; variables/pass-throughs yield nothing (a producer is an
    ORIGIN — ``sink(name, source=source)`` relays, it does not produce)."""
    if isinstance(expr, ast.BoolOp):
        out: list[str] = []
        for v in expr.values:
            out.extend(_source_values(mod_imports, v, consts))
        return out
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    d = dotted_name(expr)
    if d is not None:
        last = mod_imports.resolve(d).split(".")[-1]
        if last in consts:
            return [consts[last]]
    return []


class _ImportTable:
    """Per-module alias map for resolving ``SOURCE_*`` names (the provlint
    Imports resolver, minus the ImportFrom-module ambiguity we don't
    need)."""

    def __init__(self, tree: ast.Module):
        self.names: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    self.names[a.asname or a.name] = a.name
            elif isinstance(node, ast.Import):
                for a in node.names:
                    self.names[a.asname or a.name.split(".")[0]] = a.name

    def resolve(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        head = self.names.get(head, head)
        return f"{head}.{rest}" if rest else head


def _collect_functions(mod: ModuleInfo) -> dict[str, FunctionInfo]:
    """Top-level functions and one level of methods, with their direct
    mutation/fence call lines and module-local call edges."""
    out: dict[str, FunctionInfo] = {}

    def scan(fn_node, qual: str) -> FunctionInfo:
        info = FunctionInfo(qual=qual, module=mod.name, display=mod.display,
                            line=fn_node.lineno)
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            if _is_cloud_mutation(node):
                info.mutation_lines.append(node.lineno)
            elif _is_fence_call(node):
                info.fence_lines.append(node.lineno)
            f = node.func
            if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                    and f.value.id == "self"):
                info.calls.append((f"__self__.{f.attr}", node.lineno))
            elif isinstance(f, ast.Name):
                info.calls.append((f"{mod.name}:{f.id}", node.lineno))
        return info

    for top in mod.tree.body:
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
            q = f"{mod.name}:{top.name}"
            out[q] = scan(top, q)
        elif isinstance(top, ast.ClassDef):
            for item in top.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{mod.name}:{top.name}.{item.name}"
                    out[q] = scan(item, q)
    # resolve the __self__ placeholders now that the class's methods exist
    for qual, info in out.items():
        cls = qual.split(":", 1)[1].rsplit(".", 1)
        prefix = f"{mod.name}:{cls[0]}." if len(cls) == 2 else None
        resolved: list[tuple[str, int]] = []
        for callee, line in info.calls:
            if callee.startswith("__self__."):
                if prefix is None:
                    continue
                callee = prefix + callee[len("__self__."):]
            if callee in out or not callee.startswith("__self__"):
                resolved.append((callee, line))
        info.calls = [(c, ln) for c, ln in resolved if c in out or ":" in c]
    return out


def build_graph(package_root: Path,
                doc_path: Optional[Path] = None) -> ProgramGraph:
    package_root = Path(package_root)
    package = package_root.name
    modules: dict[str, ModuleInfo] = {}
    for f in sorted(package_root.rglob("*.py")):
        # Relative to the ROOT, so a fixture package under
        # tests/analysis_fixtures/ can itself be analyzed by the tests
        # while nested fixture trees inside a real package stay excluded.
        if FIXTURE_DIR in f.relative_to(package_root).parts:
            continue
        name, is_pkg = _module_name(package_root, f)
        source = f.read_text(encoding="utf-8")
        try:
            tree = ast.parse(source, filename=str(f))
        except SyntaxError:
            continue  # provlint PL000 already reports unparseable files
        modules[name] = ModuleInfo(
            name=name, path=f, display=_display(f), source=source,
            lines=source.splitlines(), tree=tree, is_package=is_pkg)

    # ---- import edges (with from-import alias refinement) ----------------
    edges: list[ImportEdge] = []
    for mod in modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] == package:
                        edges.append(ImportEdge(mod.name, a.name, node.lineno))
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_from(mod, node)
                if base is None or base.split(".")[0] != package:
                    continue
                edges.append(ImportEdge(mod.name, base, node.lineno))
                for a in node.names:
                    # `from ..providers import gcp` — the edge that matters
                    # is providers.gcp, not providers
                    refined = f"{base}.{a.name}"
                    if refined in modules:
                        edges.append(
                            ImportEdge(mod.name, refined, node.lineno))

    # ---- function table (module-local call graph) ------------------------
    functions: dict[str, FunctionInfo] = {}
    for mod in modules.values():
        functions.update(_collect_functions(mod))

    # ---- wake annotations + producers ------------------------------------
    consts: dict[str, str] = {}
    for mod in modules.values():
        for node in mod.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.startswith("SOURCE_")
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                consts[node.targets[0].id] = node.value.value

    annotations: list[WakeAnnotation] = []
    producers: set[str] = set()
    for mod in modules.values():
        for i, text in enumerate(mod.lines, start=1):
            m = _WAKES_SRC_RE.search(text)
            if not m:
                continue
            # Comment-only annotations anchor at the code line they
            # describe (same skip the waiver parser does), so a trailing
            # or comment-only provgraph waiver lands where the finding is.
            anchor = i
            if text.lstrip().startswith("#"):
                j = i + 1
                while j <= len(mod.lines) and (
                        not mod.lines[j - 1].strip()
                        or mod.lines[j - 1].lstrip().startswith("#")):
                    j += 1
                if j <= len(mod.lines):
                    anchor = j
            annotations.append(WakeAnnotation(
                mod.name, mod.display, anchor, m.group(1)))
        table = _ImportTable(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            exprs: list[ast.AST] = [
                kw.value for kw in node.keywords
                if kw.arg in ("source", "wake_source")]
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "wake" and len(node.args) >= 2:
                    exprs.append(node.args[1])
                elif node.func.attr == "wake_after" and len(node.args) >= 3:
                    exprs.append(node.args[2])
            for e in exprs:
                producers.update(_source_values(table, e, consts))

    # ---- metric literals + doc catalog -----------------------------------
    metric_literals: list[tuple[str, str, int]] = []
    for mod in modules.values():
        if mod.name.split(".")[1:2] == ["analysis"]:
            continue  # the analyzers talk ABOUT metric names
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _METRIC_RE.fullmatch(node.value)):
                metric_literals.append(
                    (node.value, mod.display, node.lineno))

    doc_metrics: dict[str, int] = {}
    doc_display = ""
    if doc_path is not None and Path(doc_path).is_file():
        doc_path = Path(doc_path)
        doc_display = _display(doc_path)
        for i, text in enumerate(
                doc_path.read_text(encoding="utf-8").splitlines(), start=1):
            for token in _DOC_METRIC_RE.findall(text):
                for name in _expand_doc_token(token):
                    doc_metrics.setdefault(name, i)
    else:
        doc_path = None

    return ProgramGraph(
        package=package, root=package_root, modules=modules,
        import_edges=edges, functions=functions,
        wake_annotations=annotations, wake_producers=producers,
        metric_literals=metric_literals, doc_path=doc_path,
        doc_display=doc_display, doc_metrics=doc_metrics)


# -------------------------------------------------------------------- rules

RawFinding = tuple[str, int, str]          # (display path, line, message)


@dataclasses.dataclass(frozen=True)
class GraphRule:
    id: str
    name: str
    doc: str
    fn: Callable[[ProgramGraph], list[RawFinding]]


# The paper's layer map (SURVEY §1): L5 operator → L4 controllers → L3
# cloudprovider → L2 instance provider → L1 cloud client/auth. Foundation
# modules (apis/errors/catalog/scheduling/transport/auth) sit below the
# whole stack; test/support trees (fake, envtest, chaos, analysis,
# observability, models/ops/parallel workload code) are outside it.
_LAYERS = {"runtime": 1, "providers": 2, "cloudprovider": 3,
           "controllers": 4, "operator": 5}
# Segments runtime/ must never import — everything layered above it, plus
# the support trees that themselves import the control plane.
_ABOVE_RUNTIME = {"providers", "cloudprovider", "controllers", "operator",
                  "chaos", "envtest", "fake", "observability", "analysis"}
# The ROADMAP item-4 provider seam: cloud-specific modules only the
# provider layer itself (and the operator composition root) may import.
_CLOUD_SPECIFIC = ("providers.gcp", "providers.rest")
# The multi-process shard seam (PG005): workers are shared-nothing OS
# processes, and these three modules are the ONLY legal cross-shard
# channel (lease handoff, informer relay, wake transport, cloud proxying).
# A module outside the seam importing into it is reaching for another
# shard's in-process state — exactly the coupling that would silently
# re-serialize the fleet onto one event loop.
_SHARD_SEAM = ("operator.supervisor", "operator.shardworker",
               "runtime.shardipc")
# Read-only consumers of the seam's WIRE data (cumulative snapshots), not
# its live state: the /metrics scrape folds worker ledgers at the parent.
_SHARD_SEAM_READERS = ("controllers.metrics",)


def check_layering(g: ProgramGraph) -> list[RawFinding]:
    cloud_specific = {f"{g.package}.{m}" for m in _CLOUD_SPECIFIC}
    out: list[RawFinding] = []
    for e in g.import_edges:
        src_seg, dst_seg = g.segment(e.src), g.segment(e.dst)
        mod = g.modules[e.src]
        if src_seg == "runtime" and dst_seg in _ABOVE_RUNTIME:
            out.append((mod.display, e.line, (
                f"runtime/ imports {e.dst}: the runtime layer sits below "
                f"the whole control plane (SURVEY §1 layer map) and must "
                f"import nothing above itself")))
        elif (src_seg in ("controllers", "cloudprovider", "runtime")
                and e.dst in cloud_specific):
            out.append((mod.display, e.line, (
                f"{src_seg}/ imports cloud-specific module {e.dst}: "
                f"everything above the instance-provider seam must stay "
                f"cloud-neutral (ROADMAP item 4 — the second-backend "
                f"refactor needs this seam clean)")))
        elif src_seg == "providers" and dst_seg in ("controllers",
                                                    "operator"):
            out.append((mod.display, e.line, (
                f"providers/ imports {e.dst}: the provider layer must not "
                f"depend on the control loops above it (dependencies point "
                f"down the SURVEY §1 layer map)")))
        elif dst_seg == "operator" and src_seg != "operator":
            out.append((mod.display, e.line, (
                f"{e.src} imports {e.dst}: operator/ is the composition "
                f"root (L5) — nothing imports the binary")))
    return out


def check_wake_graph(g: ProgramGraph) -> list[RawFinding]:
    out: list[RawFinding] = []
    for a in g.wake_annotations:
        if a.source not in g.wake_producers:
            out.append((a.display, a.line, (
                f"`# wakes: {a.source}` declares an event-driven wake "
                f"edge, but no call site in the package produces source "
                f"'{a.source}' (WakeHub.wake/wake_after, Controller."
                f"inject, a workqueue enqueue source=..., or a watch "
                f"wake_source=...) — a declared-but-unproduced edge means "
                f"this park only ever ends on its safety-net timer, the "
                f"bug class the wake graph exists to kill")))
    return out


def check_fence_flow(g: ProgramGraph) -> list[RawFinding]:
    # Fixpoint over the module-local call graph: a function "leaks" when a
    # mutation is reachable from its entry with no fence check first —
    # either a direct unfenced mutation or an unfenced call into a leaking
    # callee. PL003 already flags direct sites in their own function; this
    # rule flags the CALLERS of helpers that launder the mutation (helpers
    # whose own PL003 finding was waived with "caller holds the fence").
    provider_funcs = {q: f for q, f in g.functions.items()
                      if g.segment(f.module) == "providers"}

    def first_unfenced_site(f: FunctionInfo,
                            leaking: set[str]) -> Optional[int]:
        sites = list(f.mutation_lines)
        sites += [ln for callee, ln in f.calls if callee in leaking]
        if not sites:
            return None
        first = min(sites)
        if f.fence_lines and min(f.fence_lines) < first:
            return None
        return first

    leaking: set[str] = set()
    for _ in range(len(provider_funcs) + 1):
        nxt = {q for q, f in provider_funcs.items()
               if first_unfenced_site(f, leaking) is not None}
        if nxt == leaking:
            break
        leaking = nxt

    out: list[RawFinding] = []
    for q, f in provider_funcs.items():
        for callee, line in f.calls:
            if callee not in leaking:
                continue
            if f.fence_lines and min(f.fence_lines) < line:
                continue  # the caller's fence covers the laundered path
            helper = callee.split(":", 1)[1]
            out.append((f.display, line, (
                f"call into {helper}() reaches a cloud mutation with no "
                f"fence check on the path (neither inside the helper nor "
                f"before this call) — interprocedural PL003: a deposed "
                f"leader could mutate the cloud through this laundered "
                f"path")))
    return out


def check_shard_isolation(g: ProgramGraph) -> list[RawFinding]:
    seam = {f"{g.package}.{m}" for m in _SHARD_SEAM}
    readers = {f"{g.package}.{m}" for m in _SHARD_SEAM_READERS}
    out: list[RawFinding] = []
    for e in g.import_edges:
        if e.dst not in seam or e.src in seam or e.src in readers:
            continue
        if g.segment(e.src) == "operator":
            continue  # the composition root wires the seam together
        out.append((g.modules[e.src].display, e.line, (
            f"{e.src} imports shard-seam module {e.dst}: workers are "
            f"shared-nothing processes and only the supervisor/relay seam "
            f"(operator.supervisor, operator.shardworker, runtime.shardipc) "
            f"may touch another shard's in-process state — route through "
            f"the lease table, the relay, or the wake transport instead")))
    return out


def check_metrics_docs(g: ProgramGraph) -> list[RawFinding]:
    if g.doc_path is None:
        return []
    out: list[RawFinding] = []
    seen: set[str] = set()
    for name, display, line in g.metric_literals:
        if name in g.doc_metrics or name in seen:
            continue
        seen.add(name)
        out.append((display, line, (
            f"metric family {name} is registered in code but absent from "
            f"{g.doc_display} — the catalog is the triage entry point; an "
            f"undocumented family is invisible at 2am")))
    code_names = {name for name, _, _ in g.metric_literals}
    for name, line in sorted(g.doc_metrics.items()):
        if name not in code_names:
            out.append((g.doc_display, line, (
                f"{g.doc_display} documents metric {name} but nothing in "
                f"the package registers it — stale docs misdirect an "
                f"incident responder")))
    return out


RULES: list[GraphRule] = [
    GraphRule("PG001", "layering-violation",
              "import edge against the SURVEY §1 layer DAG (runtime "
              "imports nothing above itself; cloud-specific modules stay "
              "below the provider seam; providers never import "
              "controllers; nothing imports operator/)", check_layering),
    GraphRule("PG002", "unproduced-wake-edge",
              "a `# wakes: <source>` annotation with no producer call "
              "site for that source anywhere in the package",
              check_wake_graph),
    GraphRule("PG003", "unfenced-mutation-path",
              "a call into a helper that transitively issues a cloud "
              "mutation, with no fence check inside the helper or before "
              "the call (interprocedural PL003)", check_fence_flow),
    GraphRule("PG004", "metrics-docs-drift",
              "tpu_provisioner_* names in code and docs/OBSERVABILITY.md "
              "must match exactly, both directions", check_metrics_docs),
    GraphRule("PG005", "shard-isolation",
              "an import into the multi-process shard seam (operator."
              "supervisor / operator.shardworker / runtime.shardipc) from "
              "outside it — cross-shard state must travel the lease/relay/"
              "wake channels, never an in-process reference",
              check_shard_isolation),
]


# ------------------------------------------------------------------- runner

def _known_keys(rules: list[GraphRule]) -> set[str]:
    keys: set[str] = set()
    for r in rules:
        keys.add(r.id.lower())
        keys.add(r.name.lower())
    return keys


def analyze(package_root: Path, doc_path: Optional[Path] = None,
            rules: Optional[list[GraphRule]] = None) -> list[Finding]:
    """Build the graph, run the rules, apply per-file provgraph waivers.

    Doc-side findings (PG004's second direction) have no waiver channel —
    the fix is editing the doc, which is always available."""
    rules = RULES if rules is None else rules
    g = build_graph(Path(package_root), doc_path)
    raw: list[tuple[GraphRule, RawFinding]] = []
    seen: set[tuple[str, str, int]] = set()
    for rule in rules:
        for f in rule.fn(g):
            # One finding per (rule, file, line): a from-import records both
            # the base and the alias-refined edge, which are the same
            # violation at the same line.
            sig = (rule.id, f[0], f[1])
            if sig in seen:
                continue
            seen.add(sig)
            raw.append((rule, f))

    known = _known_keys(RULES)
    waivers = {mod.display: parse_waivers(
        mod.lines, known, _comment_lines(mod.source), tag=WAIVER_TAG)
        for mod in g.modules.values()}

    findings: list[Finding] = []
    for display, w in waivers.items():
        findings.extend(
            Finding("PG000", "malformed-waiver", display, line, msg)
            for line, msg in w.malformed)
    for rule, (display, line, msg) in raw:
        w = waivers.get(display)
        if w is not None and w.waived(rule, line):  # type: ignore[arg-type]
            continue
        findings.append(Finding(rule.id, rule.name, display, line, msg))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# ---------------------------------------------------------------------- CLI

def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="provgraph",
        description="Whole-program invariant analysis for the provisioner "
                    "control plane (docs/STATIC_ANALYSIS.md#provgraph).")
    ap.add_argument("root", nargs="?", default="gpu_provisioner_tpu",
                    help="package root to analyze")
    ap.add_argument("--docs", default=DEFAULT_DOC,
                    help="metrics catalog doc for PG004 (default: "
                         f"{DEFAULT_DOC}; pass an empty string to skip)")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE", help="run only these rules (id or name)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.id}  {r.name:<26} {r.doc}")
        return 0

    rules = RULES
    if args.select:
        keys = {s.lower() for s in args.select}
        rules = [r for r in RULES
                 if r.id.lower() in keys or r.name.lower() in keys]
        if not rules:
            print(f"provgraph: no rule matches {sorted(keys)}",
                  file=sys.stderr)
            return 2

    root = Path(args.root)
    if not root.is_dir():
        print(f"provgraph: no such package root: {root}", file=sys.stderr)
        return 2
    doc = Path(args.docs) if args.docs else None

    findings = analyze(root, doc, rules=rules)
    if args.as_json:
        print(json.dumps([dataclasses.asdict(f) for f in findings],
                         indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"provgraph: {len(findings)} finding(s), "
              f"{len(rules)} rule(s) active", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
