"""provlint engine: rule registry, waivers, file walking, CLI.

Design notes
------------
Each rule is a pure function over one parsed module (``RuleContext``) —
rules never do I/O, so the whole suite runs in one pass per file and the
fixture corpus (tests/analysis_fixtures/) can drive any rule against any
snippet regardless of where the snippet lives on disk.

Rules are *scoped by role*: a file under ``gpu_provisioner_tpu/controllers``
has roles ``{"package", "controllers"}``, test files have ``{"tests"}``, and
a rule only runs where its invariant applies (wall-clock discipline is a
controller rule; sleep-poll discipline a test rule). ``lint_file`` accepts a
``roles`` override so fixture tests can force a role.

Waivers are inline comments::

    do_the_thing()  # provlint: disable=naked-wall-clock — bench baseline

The separator is an em dash (``—``) or ``--``; the reason is MANDATORY — a
waiver without one (or naming an unknown rule) is itself a finding
(``PL000 malformed-waiver``). A trailing waiver suppresses the named rules
on its own line and the line directly below (multi-line statements); a
comment-only waiver suppresses exactly the next code line — never the one
after it. ``disable-file=`` waives for the whole file.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import io
import json
import re
import sys
import tokenize
from pathlib import Path
from typing import Callable, Iterable, Optional

# Roles a file can have; rules declare which they run under.
ROLE_PACKAGE = "package"          # anywhere under gpu_provisioner_tpu/
ROLE_CONTROLLERS = "controllers"
ROLE_PROVIDERS = "providers"
ROLE_RUNTIME = "runtime"
ROLE_CLOUDPROVIDER = "cloudprovider"
ROLE_CHAOS = "chaos"
ROLE_TESTS = "tests"

# Deliberate-violation corpus for the rule tests; never linted by default.
FIXTURE_DIR = "analysis_fixtures"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          # "PL004"
    name: str          # "naked-wall-clock"
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.name}] {self.message}"


class Imports:
    """Module import table for dotted-name resolution.

    ``import time as t`` maps ``t`` → ``time``; ``from datetime import
    datetime`` maps ``datetime`` → ``datetime.datetime`` — so
    ``dotted(node)`` on ``datetime.now`` resolves to
    ``datetime.datetime.now`` no matter how the module was imported.
    """

    def __init__(self, tree: ast.Module):
        self.aliases: dict[str, str] = {}
        self.from_names: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    self.from_names[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name)

    def resolve(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        if head in self.from_names:
            head = self.from_names[head]
        elif head in self.aliases:
            head = self.aliases[head]
        return f"{head}.{rest}" if rest else head


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class RuleContext:
    path: str                      # display path (repo-relative when possible)
    roles: frozenset
    source: str
    lines: list[str]
    tree: ast.Module
    imports: Imports

    def resolved(self, node: ast.AST) -> Optional[str]:
        d = dotted_name(node)
        return self.imports.resolve(d) if d is not None else None

    def functions(self) -> Iterable[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


def body_walk(node: ast.AST, *, into_nested_defs: bool = False):
    """Walk a function body without descending into nested function/class
    definitions (their bodies execute in a different context)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not into_nested_defs and isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                    ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    roles: frozenset
    doc: str
    fn: Callable[[RuleContext], list[tuple[int, str]]]

    def run(self, ctx: RuleContext) -> list[Finding]:
        if self.roles and not (self.roles & ctx.roles):
            return []
        return [Finding(self.id, self.name, ctx.path, line, msg)
                for line, msg in self.fn(ctx)]


# ------------------------------------------------------------------ waivers

# Waiver syntax is shared with provgraph (same grammar, different comment
# tag — "provgraph" instead of "provlint"), so the regexes are built per tag.
_WAIVER_RES: dict[str, tuple[re.Pattern, re.Pattern]] = {}


def _waiver_res(tag: str) -> tuple[re.Pattern, re.Pattern]:
    pair = _WAIVER_RES.get(tag)
    if pair is None:
        pair = (
            re.compile(
                rf"#\s*{tag}:\s*(disable|disable-file)\s*=\s*"
                r"([A-Za-z0-9_\-, ]+?)\s*(?:—|--)\s*(\S.*)$"),
            re.compile(rf"#\s*{tag}\s*:"))
        _WAIVER_RES[tag] = pair
    return pair


@dataclasses.dataclass
class Waivers:
    by_line: dict[int, set[str]]      # trailing waiver: its line (+ next)
    exact: dict[int, set[str]]        # comment-only waiver: ONE code line
    file_wide: set[str]
    malformed: list[tuple[int, str]]  # (line, message) → PL000 findings

    def waived(self, rule: Rule, line: int) -> bool:
        keys = {rule.id.lower(), rule.name.lower()}
        if keys & self.file_wide:
            return True
        if keys & self.exact.get(line, set()):
            return True
        # a trailing waiver covers its own line and the line directly
        # below it (multi-line statements); comment-only waivers are
        # EXACT — they must not bleed onto the line after their target
        for at in (line, line - 1):
            if keys & self.by_line.get(at, set()):
                return True
        return False


def _comment_lines(source: str) -> Optional[set[int]]:
    """Line numbers carrying a real COMMENT token — waiver syntax quoted in
    a docstring/string literal must neither waive nor count as malformed.
    None when the file fails to tokenize (caller falls back to line scan)."""
    try:
        return {tok.start[0]
                for tok in tokenize.generate_tokens(io.StringIO(source).readline)
                if tok.type == tokenize.COMMENT}
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return None


def parse_waivers(lines: list[str], known: set[str],
                  comment_lines: Optional[set[int]] = None,
                  tag: str = "provlint") -> Waivers:
    waiver_re, mark_re = _waiver_res(tag)
    by_line: dict[int, set[str]] = {}
    exact: dict[int, set[str]] = {}
    file_wide: set[str] = set()
    malformed: list[tuple[int, str]] = []
    for i, text in enumerate(lines, start=1):
        if comment_lines is not None and i not in comment_lines:
            continue
        if not mark_re.search(text):
            continue
        m = waiver_re.search(text)
        if m is None:
            malformed.append((i, (
                "malformed waiver: expected disable=<rule> — <reason> "
                f"after the {tag} marker (the reason is mandatory)")))
            continue
        kind, rules_raw, _reason = m.groups()
        keys = {r.strip().lower() for r in rules_raw.split(",") if r.strip()}
        unknown = keys - known
        if unknown:
            malformed.append((i, (
                f"waiver names unknown rule(s): {sorted(unknown)}")))
            keys -= unknown
        if kind == "disable-file":
            file_wide |= keys
            continue
        if text.lstrip().startswith("#"):
            # comment-only waiver: cover exactly the next CODE line,
            # skipping the rest of its own comment block (reasons often
            # wrap) — and nothing past it
            j = i + 1
            while j <= len(lines) and (
                    not lines[j - 1].strip()
                    or lines[j - 1].lstrip().startswith("#")):
                j += 1
            exact.setdefault(j, set()).update(keys)
        else:
            by_line.setdefault(i, set()).update(keys)
    return Waivers(by_line, exact, file_wide, malformed)


# ------------------------------------------------------------- role mapping

def infer_roles(path: Path) -> frozenset:
    parts = path.parts
    roles: set[str] = set()
    if "gpu_provisioner_tpu" in parts:
        roles.add(ROLE_PACKAGE)
        # LAST occurrence: a checkout directory named like the package
        # (~/gpu_provisioner_tpu/gpu_provisioner_tpu/controllers/...) must
        # not shadow the package dir and silently drop the sub-roles —
        # that would disable the control-plane rules with zero findings
        idx = len(parts) - 1 - parts[::-1].index("gpu_provisioner_tpu")
        sub = parts[idx + 1] if len(parts) > idx + 1 else ""
        if sub in (ROLE_CONTROLLERS, ROLE_PROVIDERS, ROLE_RUNTIME,
                   ROLE_CLOUDPROVIDER, ROLE_CHAOS):
            roles.add(sub)
    if "tests" in parts:
        roles.add(ROLE_TESTS)
    return frozenset(roles)


# ------------------------------------------------------------------- runner

def _known_keys(rules: list[Rule]) -> set[str]:
    keys: set[str] = set()
    for r in rules:
        keys.add(r.id.lower())
        keys.add(r.name.lower())
    return keys


def lint_file(path: Path, rules: Optional[list[Rule]] = None,
              roles: Optional[frozenset] = None,
              display_path: Optional[str] = None) -> list[Finding]:
    from .rules import RULES
    rules = RULES if rules is None else rules
    path = Path(path)
    display = display_path or _display(path)
    source = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [Finding("PL000", "malformed-waiver", display,
                        e.lineno or 0, f"file does not parse: {e.msg}")]
    lines = source.splitlines()
    ctx = RuleContext(
        path=display,
        roles=roles if roles is not None else infer_roles(path.resolve()),
        source=source, lines=lines, tree=tree, imports=Imports(tree))
    # waiver validity is judged against the FULL catalog, not any --select
    # subset — a waiver naming an unselected rule is not malformed
    from .rules import RULES as _ALL_RULES
    waivers = parse_waivers(lines, _known_keys(_ALL_RULES),
                            _comment_lines(source))
    findings = [Finding("PL000", "malformed-waiver", display, line, msg)
                for line, msg in waivers.malformed]
    for rule in rules:
        for f in rule.run(ctx):
            if not waivers.waived(rule, f.line):
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def _display(path: Path) -> str:
    try:
        return str(path.resolve().relative_to(Path.cwd()))
    except ValueError:
        return str(path)


def iter_py_files(paths: Iterable[Path]) -> Iterable[Path]:
    for p in paths:
        p = Path(p)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if FIXTURE_DIR in f.parts:
                    continue  # deliberate-violation corpus
                yield f


def changed_py_files(paths: Iterable[Path]) -> list[Path]:
    """The ``--changed`` file set: ``git diff --name-only HEAD`` (modified,
    tracked) plus untracked files, narrowed to existing ``.py`` files under
    ``paths`` — the fast pre-commit loop. Raises ``OSError`` /
    ``CalledProcessError`` when git is unavailable or the cwd is not a
    repository; fixture-corpus files are excluded exactly as in the
    full-tree walk."""
    import subprocess

    def git(*argv: str) -> str:
        return subprocess.run(["git", *argv], capture_output=True,
                              text=True, check=True).stdout

    root = Path(git("rev-parse", "--show-toplevel").strip())
    names = set(git("diff", "--name-only", "HEAD").splitlines())
    names |= set(git("ls-files", "--others",
                     "--exclude-standard").splitlines())
    scopes = [Path(p).resolve() for p in paths]
    out: list[Path] = []
    for name in sorted(names):
        f = root / name
        if f.suffix != ".py" or not f.is_file():
            continue  # deleted files still appear in the diff
        if FIXTURE_DIR in f.parts:
            continue
        rf = f.resolve()
        if scopes and not any(rf == s or s in rf.parents for s in scopes):
            continue
        out.append(f)
    return out


def lint_paths(paths: Iterable[Path],
               rules: Optional[list[Rule]] = None) -> list[Finding]:
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f, rules=rules))
    return findings


# ---------------------------------------------------------------------- CLI

def main(argv: Optional[list[str]] = None) -> int:
    from .rules import RULES
    ap = argparse.ArgumentParser(
        prog="provlint",
        description="Project-specific static analysis for the provisioner "
                    "control plane (docs/STATIC_ANALYSIS.md).")
    ap.add_argument("paths", nargs="*", default=["gpu_provisioner_tpu",
                                                 "tests"],
                    help="files or directories to lint")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE", help="run only these rules (id or name)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs HEAD (git diff "
                         "--name-only + untracked) under the given paths — "
                         "the fast pre-commit loop; rules and waiver "
                         "semantics are identical to the full walk")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            roles = ",".join(sorted(r.roles)) or "all"
            print(f"{r.id}  {r.name:<28} [{roles}]  {r.doc}")
        return 0

    rules = RULES
    if args.select:
        keys = {s.lower() for s in args.select}
        rules = [r for r in RULES
                 if r.id.lower() in keys or r.name.lower() in keys]
        if not rules:
            print(f"provlint: no rule matches {sorted(keys)}",
                  file=sys.stderr)
            return 2

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"provlint: no such path: {missing}", file=sys.stderr)
        return 2

    if args.changed:
        try:
            files = changed_py_files(Path(p) for p in args.paths)
        except Exception as e:  # noqa: BLE001 — git missing / not a repo
            print(f"provlint: --changed needs a git checkout: {e}",
                  file=sys.stderr)
            return 2
    else:
        files = list(iter_py_files(Path(p) for p in args.paths))
    findings: list[Finding] = []
    for f in files:
        findings.extend(lint_file(f, rules=rules))
    if args.as_json:
        print(json.dumps([dataclasses.asdict(f) for f in findings],
                         indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"provlint: {len(findings)} finding(s) across {len(files)} "
              f"file(s), {len(rules)} rule(s) active", file=sys.stderr)
    return 1 if findings else 0
