"""The provlint rule catalog.

Each rule encodes an invariant a previous PR paid for the hard way; the
rationale (and the PR that motivated each) is in docs/STATIC_ANALYSIS.md.
Rules are heuristics over one module's AST — deliberately simple enough to
read, with the inline-waiver syntax as the escape hatch for the places a
human can see further than the heuristic.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ..errors import KNOWN_REASONS
from .provlint import (
    ROLE_CHAOS, ROLE_CLOUDPROVIDER, ROLE_CONTROLLERS, ROLE_PACKAGE,
    ROLE_PROVIDERS, ROLE_RUNTIME, ROLE_TESTS,
    Rule, RuleContext, body_walk, dotted_name,
)

_ASYNC_ROLES = frozenset({ROLE_CONTROLLERS, ROLE_PROVIDERS, ROLE_RUNTIME})


# --------------------------------------------------- PL001 blocking-in-async

_BLOCKING_CALLS = {
    "time.sleep", "os.system", "os.popen", "socket.create_connection",
    "socket.getaddrinfo", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output", "subprocess.Popen",
}
_BLOCKING_PREFIXES = ("requests.", "urllib.request.", "urllib3.",
                      "http.client.")


def _async_functions(ctx: RuleContext):
    for fn in ctx.functions():
        if isinstance(fn, ast.AsyncFunctionDef):
            yield fn


def check_blocking_in_async(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for fn in _async_functions(ctx):
        for node in body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = ctx.resolved(node.func)
            if d is None:
                continue
            if (d in _BLOCKING_CALLS or d.startswith(_BLOCKING_PREFIXES)
                    or d == "open"):
                out.append((node.lineno, (
                    f"blocking call {d}() inside async def "
                    f"{fn.name!r} — this parks the single event loop "
                    f"every reconcile shares; use the async seam "
                    f"(asyncio.sleep / asyncio.to_thread / httpx)")))
    return out


# ----------------------------------------------- PL002 cancellation-swallow

_MUST_RERAISE_LAST = {"CancelledError", "SimulatedCrash", "BaseException",
                      "KeyboardInterrupt", "SystemExit"}


def _caught_names(handler: ast.ExceptHandler) -> list[str]:
    t = handler.type
    if t is None:
        return ["BaseException"]
    nodes = t.elts if isinstance(t, ast.Tuple) else [t]
    names = []
    for n in nodes:
        d = dotted_name(n)
        if d is not None:
            names.append(d.rsplit(".", 1)[-1])
    return names


def _is_task_reap_try(try_node: ast.Try) -> bool:
    """The standard teardown shape — ``t.cancel(); try: await t except
    CancelledError: pass`` — swallows the task's *own* cancellation, which
    is correct; only a handler that can eat the CURRENT task's cancellation
    is a hang risk. Recognized by the try body being nothing but awaits of
    plain names/attributes (no calls: the awaited thing already exists)."""
    for stmt in try_node.body:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Await)
                and isinstance(stmt.value.value, (ast.Name, ast.Attribute))):
            return False
    return bool(try_node.body)


def check_cancellation_swallow(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        reap = _is_task_reap_try(node)
        for handler in node.handlers:
            caught = set(_caught_names(handler)) & _MUST_RERAISE_LAST
            if not caught:
                continue
            if reap and caught <= {"CancelledError"}:
                continue
            if any(isinstance(n, ast.Raise) for n in body_walk(handler)):
                continue
            out.append((handler.lineno, (
                f"except catching {sorted(caught)} never re-raises — "
                f"swallowing CancelledError/SimulatedCrash turns shutdown "
                f"and crash injection into hangs (the PR 5 bpo-42130 bug "
                f"class); re-raise, or narrow the except")))
    return out


# --------------------------------------------- PL003 unfenced-cloud-mutation

_MUTATING_ATTRS = {"begin_create", "begin_delete"}
_QUEUED_MUTATING_ATTRS = {"create", "delete"}
_FENCE_CALLS = {"_fence_check", "check"}


def _is_cloud_mutation(call: ast.Call) -> str | None:
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr in _MUTATING_ATTRS:
        return attr
    if attr in _QUEUED_MUTATING_ATTRS:
        chain = dotted_name(call.func) or ""
        if "queued" in chain.lower():
            return chain
    return None


def _is_fence_call(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in _FENCE_CALLS:
        return False
    if call.func.attr == "_fence_check":
        return True
    chain = dotted_name(call.func) or ""
    return "fence" in chain.lower()


def check_unfenced_mutation(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    in_controllers = ROLE_CONTROLLERS in ctx.roles
    for fn in ctx.functions():
        fence_lines = []
        mutations = []
        for node in body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if _is_fence_call(node):
                fence_lines.append(node.lineno)
            what = _is_cloud_mutation(node)
            if what is not None:
                mutations.append((node.lineno, what))
        for line, what in mutations:
            if in_controllers:
                out.append((line, (
                    f"controller calls cloud mutation {what} directly — "
                    f"mutations must go through the provider seam, which "
                    f"owns the fence check (PR 3 single-writer discipline)")))
            elif not any(fl < line for fl in fence_lines):
                out.append((line, (
                    f"cloud mutation {what} with no preceding fence check "
                    f"in this function — a deposed leader's in-flight "
                    f"reconcile could race the new leader (PR 3); call "
                    f"self._fence_check() (or fence.check()) first")))
    return out


# -------------------------------------------------- PL004 naked-wall-clock

_WALL_CLOCKS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}


def check_naked_wall_clock(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for node in ast.walk(ctx.tree):
        # Attribute chains (time.monotonic) AND bare imported names
        # (`from time import monotonic`) — the import style must not be
        # the bypass. A Name inside an Attribute chain resolves to the
        # bare module ("time"), never a clock, so nothing double-counts.
        if not isinstance(node, (ast.Attribute, ast.Name)):
            continue
        if isinstance(node, ast.Name) and not isinstance(node.ctx, ast.Load):
            continue
        d = ctx.resolved(node)
        if d in _WALL_CLOCKS:
            out.append((node.lineno, (
                f"naked {d} in a controller — use the injected clock seams "
                f"(asyncio loop time / providers.operations.loop_now for "
                f"monotonic, apis.serde now()/wall_now() for wall time) so "
                f"envtest and unit tests control time")))
    return out


# ------------------------------------------- PL005 metrics-registered-late

_METRIC_CONSTRUCTORS = {"Counter", "Gauge", "Histogram", "Summary", "Info",
                        "Enum"}


def check_metrics_registration(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for fn in ctx.functions():
        for node in body_walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = ctx.resolved(node.func)
            if d is None:
                continue
            last = d.rsplit(".", 1)[-1]
            is_prom = (d.startswith("prometheus_client.")
                       and last in _METRIC_CONSTRUCTORS)
            if is_prom or last == "_get_or_create":
                out.append((node.lineno, (
                    f"metric registered inside function {fn.name!r} — "
                    f"prometheus collectors must be registered exactly once "
                    f"at module scope (re-registration raises or silently "
                    f"double-counts inside reconcile loops)")))
    return out


# ------------------------------------------- PL006 await-holding-sync-lock

def check_await_holding_sync_lock(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):   # async with is fine
            continue
        lockish = None
        for item in node.items:
            expr = item.context_expr
            target = expr.func if isinstance(expr, ast.Call) else expr
            d = dotted_name(target) or ""
            if "lock" in d.lower():
                lockish = d
                break
        if lockish is None:
            continue
        for inner in body_walk(node):
            if isinstance(inner, ast.Await):
                out.append((inner.lineno, (
                    f"await while holding sync lock {lockish!r} — the loop "
                    f"suspends with the lock held, and any other task "
                    f"taking it blocks the whole event loop (deadlock "
                    f"class); use asyncio.Lock with 'async with'")))
                break
    return out


# ------------------------------------------------------ PL007 untracked-task

_TASK_SPAWNS = {"asyncio.create_task", "asyncio.ensure_future"}


def _spawn_call(ctx: RuleContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = ctx.resolved(node.func)
    if d in _TASK_SPAWNS:
        return True
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "create_task"
            and "loop" in (dotted_name(node.func.value) or "").lower())


def check_untracked_task(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    msg = ("background task is fire-and-forget — retain the handle and "
           "track it for teardown (or add_done_callback), or it outlives "
           "its owner and keeps polling dead state (the PR 4/PR 5 "
           "tracker-poller bug class)")
    for fn in ctx.functions():
        assigned: list[tuple[str, ast.Assign]] = []
        for node in body_walk(fn):
            if isinstance(node, ast.Expr) and _spawn_call(ctx, node.value):
                out.append((node.lineno, msg))
            elif isinstance(node, ast.Assign) and _spawn_call(ctx, node.value):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    assigned.append((node.targets[0].id, node))
        for name, assign in assigned:
            # usage scan descends into nested defs: a handle retained via
            # a closure/callback is tracked, not fire-and-forget (the
            # Store-ctx assignment target is excluded by the Load check)
            used = any(
                isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Load)
                for n in body_walk(fn, into_nested_defs=True)
            )
            if not used:
                out.append((assign.lineno, msg))
    return out


# --------------------------------------------------- PL008 mutable-default

def check_mutable_default(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    mutable_ctors = {"list", "dict", "set"}
    for fn in ctx.functions():
        defaults = list(fn.args.defaults) + [
            d for d in fn.args.kw_defaults if d is not None]
        for d in defaults:
            bad = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and isinstance(d.func, ast.Name)
                and d.func.id in mutable_ctors)
            if bad:
                out.append((d.lineno, (
                    f"mutable default argument in {fn.name!r} — shared "
                    f"across every call; use None and materialize inside")))
    return out


# ------------------------------------------------ PL009 ungated-crash-point

def _has_crash_guard(fn: ast.AST) -> bool:
    for node in body_walk(fn):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        has_none = any(isinstance(s, ast.Constant) and s.value is None
                       for s in sides)
        names = " ".join(dotted_name(s) or "" for s in sides)
        if has_none and "crash" in names.lower():
            return True
    return False


def check_ungated_crash_point(ctx: RuleContext) -> list[tuple[int, str]]:
    if ROLE_CHAOS in ctx.roles:
        return []
    out = []
    layered = bool(ctx.roles & _ASYNC_ROLES | (ctx.roles & {ROLE_CLOUDPROVIDER}))
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ImportFrom) and layered:
            mod = node.module or ""
            names = {a.name for a in node.names}
            if ("chaos" in mod and names & {"SimulatedCrash", "CrashPoints"}):
                out.append((node.lineno, (
                    "controller/provider layer imports crash-injection "
                    "types directly — these layers stay chaos-unaware; "
                    "take a ``crashes`` object and gate on ``is not None`` "
                    "(the _crash helper idiom)")))
    for fn in ctx.functions():
        for node in body_walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "hit"):
                continue
            chain = dotted_name(node.func) or ""
            if "crash" not in chain.lower():
                continue
            if not _has_crash_guard(fn):
                out.append((node.lineno, (
                    f"crash point fired via {chain} without a "
                    f"'crashes is None' gate in this function — production "
                    f"passes no CrashPoints; guard the seam (the _crash "
                    f"helper idiom) so the hot path costs one None check")))
    return out


# ---------------------------------------------- PL010 unbounded-sleep-poll

_DEADLINEISH = re.compile(r"deadline|timeout|budget", re.IGNORECASE)


def _mentions_deadline(fn: ast.AST) -> bool:
    for node in body_walk(fn, into_nested_defs=True):
        if isinstance(node, ast.Name) and _DEADLINEISH.search(node.id):
            return True
        if isinstance(node, ast.Attribute) and _DEADLINEISH.search(node.attr):
            return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "time"):
            return True
    return False


def check_unbounded_sleep_poll(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for fn in _async_functions(ctx):
        if _mentions_deadline(fn):
            continue
        for node in body_walk(fn):
            if not isinstance(node, ast.While):
                continue
            sleeps = [
                n for n in body_walk(node)
                if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)
                and ctx.resolved(n.value.func) == "asyncio.sleep"]
            if sleeps:
                out.append((node.lineno, (
                    f"unbounded asyncio.sleep polling loop in {fn.name!r} "
                    f"— envtest tests must poll against an explicit "
                    f"deadline (the harness timeout turns this into a "
                    f"60s-late, context-free failure)")))
                break
    return out


# ------------------------------------------ PL011 unregistered-pytest-marker

_BUILTIN_MARKERS = {
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "anyio",
}
_MARKER_LINE = re.compile(r'^\s*"([A-Za-z_][A-Za-z0-9_]*)\s*:')
_marker_cache: dict[Path, frozenset] = {}


def _registered_markers(start: Path) -> frozenset:
    for parent in [start] + list(start.parents):
        pp = parent / "pyproject.toml"
        if not pp.is_file():
            continue
        if pp not in _marker_cache:
            names, in_markers = set(), False
            for line in pp.read_text(encoding="utf-8").splitlines():
                s = line.strip()
                if s.startswith("markers"):
                    in_markers = True
                    continue
                if in_markers:
                    if s.startswith("]"):
                        break
                    m = _MARKER_LINE.match(line)
                    if m:
                        names.add(m.group(1))
            _marker_cache[pp] = frozenset(names)
        return _marker_cache[pp]
    return frozenset()


def check_unregistered_marker(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    registered = _registered_markers(Path(ctx.path).resolve().parent)
    allowed = registered | _BUILTIN_MARKERS
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Attribute):
            continue
        d = dotted_name(node) or ""
        if d.startswith("pytest.mark.") and d.count(".") == 2:
            name = d.rsplit(".", 1)[-1]
            if name not in allowed:
                out.append((node.lineno, (
                    f"pytest marker {name!r} is not registered in "
                    f"pyproject.toml [tool.pytest.ini_options] markers — "
                    f"unregistered markers warn at collection and break "
                    f"-W error::DeprecationWarning runs")))
    return out


# ------------------------------------------------------ PL012 unclosed-span

def _is_span_call(node: ast.AST, attr: str) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == attr)


def _guarantees_span_end(fn: ast.AST) -> bool:
    """True when the function carries a ``try``/``finally`` whose finalbody
    calls ``span_end`` — the only manual shape that closes the span on every
    exit path (the canonical pair opens the span immediately BEFORE the
    try, so the check is function-scoped, not try-body-scoped)."""
    for node in body_walk(fn):
        if not isinstance(node, ast.Try):
            continue
        for stmt in node.finalbody:
            for sub in ast.walk(stmt):
                if _is_span_call(sub, "span_end"):
                    return True
    return False


def check_unclosed_span(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for fn in ctx.functions():
        if _guarantees_span_end(fn):
            continue
        for node in body_walk(fn):
            if _is_span_call(node, "span_begin"):
                out.append((node.lineno, (
                    f"span_begin in {fn.name!r} without a finally-guaranteed "
                    f"span_end — an exception between begin and end leaves "
                    f"the span open and its contextvar leaks trace ids into "
                    f"every later log line and Event on this task; use "
                    f"tracer.span() (context manager) or close the token in "
                    f"a try/finally")))
    return out


# ---------------------------------------------- PL013 literal-error-reason

def _reason_literals(expr: ast.AST) -> list[ast.Constant]:
    """String Constants carrying a known CreateError reason value, descending
    one level into literal tuples/sets/lists (``in ("A", "B")``)."""
    elts = expr.elts if isinstance(expr, (ast.Tuple, ast.Set, ast.List)) \
        else [expr]
    return [e for e in elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
            and e.value in KNOWN_REASONS]


def check_literal_error_reason(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            d = ctx.resolved(node.func) or dotted_name(node.func) or ""
            if d.rsplit(".", 1)[-1] != "CreateError":
                continue
            # the reason slot: 2nd positional or reason= keyword
            slots = node.args[1:2] + [kw.value for kw in node.keywords
                                      if kw.arg == "reason"]
            for s in slots:
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    out.append((s.lineno, (
                        f"CreateError reason spelled as string literal "
                        f"{s.value!r} — reasons come from the errors.py "
                        f"enum (REASON_*); a literal drifts from "
                        f"TERMINAL_REASONS and silently flips a terminal "
                        f"fault into an infinite retry (or vice versa)")))
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if not any(isinstance(s, ast.Attribute) and s.attr == "reason"
                       for s in sides):
                continue
            for s in sides:
                for lit in _reason_literals(s):
                    out.append((lit.lineno, (
                        f".reason compared against string literal "
                        f"{lit.value!r} — branch on the errors.py enum "
                        f"(REASON_* / reason_is_terminal()) so the "
                        f"terminal-vs-retryable classification has one "
                        f"home")))
    return out


# ---------------------------------------------- PL014 unsourced-requeue-wait

_WAKES_RE = re.compile(r"#\s*wakes:\s*\S")


def _is_requeue_result(call: ast.Call, ctx: RuleContext) -> bool:
    d = ctx.resolved(call.func) or dotted_name(call.func) or ""
    if d.rsplit(".", 1)[-1] != "Result":
        return False
    for kw in call.keywords:
        if kw.arg == "requeue_after":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


def _uses_wakehub(fn: ast.AST) -> bool:
    for n in body_walk(fn):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("wake", "wake_after")):
            return True
    return False


def check_unsourced_requeue_wait(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for fn in ctx.functions():
        armed = _uses_wakehub(fn)
        for node in body_walk(fn):
            if not (isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Call)
                    and _is_requeue_result(node.value, ctx)):
                continue
            if armed:
                continue  # the function itself arms a WakeHub wake
            # the return's own lines, plus the contiguous comment block
            # directly above it (annotations often share a longer comment)
            window = list(ctx.lines[node.lineno - 1:
                                    (node.end_lineno or node.lineno)])
            i = node.lineno - 2
            while i >= 0 and ctx.lines[i].lstrip().startswith("#"):
                window.append(ctx.lines[i])
                i -= 1
            if any(_WAKES_RE.search(line) for line in window):
                continue
            out.append((node.lineno, (
                "Result(requeue_after=...) without a declared wake source — "
                "annotate the return with `# wakes: <source>` (node / lro / "
                "timer / stockout / ...) or arm a WakeHub wake in the same "
                "function; an undeclared wait is exactly the requeue-idle-"
                "gap the event-driven control plane exists to kill (the "
                "timer must be the named safety net, not an accident)")))
    return out


# ---------------------------------------------- PL015 unclassified-watch-gap

# Watch/list pump loops, by the names this codebase (and client-go) uses.
_PL015_NAME_RE = re.compile(r"(^|_)(run|watch|pump|relist|resync)")

# The verbs a pump loop issues against a watch/list surface. A function
# that never touches one of these is not a pump, whatever its name
# (providers/operations.py `_run` ticks reconcile state, not a watch).
_PL015_TOUCH = frozenset({
    "watch", "__anext__", "try_next", "list", "list_pages", "_stream",
    "_list_into_queue", "relist", "_relist", "resync", "_resync",
})

# Broad handlers that would swallow a 410 into the generic retry path.
_PL015_BROAD = frozenset({
    "Exception", "BaseException", "ClientError", "APIError",
})

# Names/attributes whose presence proves the function classifies expired-
# resourceVersion distinctly: the typed error, or a typed `.expired` /
# `.gone` predicate on a caught error.
_PL015_CLASSIFIERS = frozenset({"ResourceExpiredError", "expired", "gone"})


def _pl015_handler_names(h: ast.ExceptHandler) -> list[str]:
    types = (h.type.elts if isinstance(h.type, ast.Tuple)
             else [h.type] if h.type is not None else [])
    return [(dotted_name(t) or "").rsplit(".", 1)[-1] for t in types]


def check_unclassified_watch_gap(ctx: RuleContext) -> list[tuple[int, str]]:
    out = []
    for fn in ctx.functions():
        if not _PL015_NAME_RE.search(fn.name):
            continue
        nodes = list(body_walk(fn))
        if not any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr in _PL015_TOUCH for n in nodes):
            continue
        classified = any(
            (isinstance(n, ast.Name) and n.id in _PL015_CLASSIFIERS)
            or (isinstance(n, ast.Attribute)
                and n.attr in _PL015_CLASSIFIERS)
            # getattr(e, "expired", False) — the duck-typed predicate probe
            or (isinstance(n, ast.Constant)
                and n.value in ("expired", "gone"))
            for n in nodes)
        if classified:
            continue
        for h in nodes:
            if (isinstance(h, ast.ExceptHandler)
                    and any(name in _PL015_BROAD
                            for name in _pl015_handler_names(h))):
                out.append((h.lineno, (
                    "watch/list pump catches broad errors without "
                    "classifying expired-resourceVersion — a 410 Gone "
                    "swallowed into the generic retry path reconnects "
                    "forever against compacted history and the informer "
                    "cache silently diverges; branch on "
                    "ResourceExpiredError (or the provider errors' "
                    ".expired/.gone predicate) and relist (PR 16 "
                    "watch-gap resync)")))
                break  # one finding per pump function
    return out


# ----------------------------------------------------------------- catalog

RULES: list[Rule] = [
    Rule("PL001", "blocking-in-async", _ASYNC_ROLES,
         "no time.sleep / sync HTTP / sync file I/O inside async defs in "
         "the control plane (BENCH r04/r05: one blocked loop stalls every "
         "reconcile)", check_blocking_in_async),
    Rule("PL002", "cancellation-swallow",
         frozenset({ROLE_PACKAGE, ROLE_TESTS}),
         "except clauses that can catch CancelledError/SimulatedCrash must "
         "re-raise (PR 5 bpo-42130 teardown hang; PR 3 crash realism)",
         check_cancellation_swallow),
    Rule("PL003", "unfenced-cloud-mutation",
         frozenset({ROLE_PROVIDERS, ROLE_CONTROLLERS}),
         "cloud mutations (begin_create/begin_delete/queued writes) need a "
         "preceding fence check on the same path; controllers never call "
         "them directly (PR 3 single-writer discipline)",
         check_unfenced_mutation),
    Rule("PL004", "naked-wall-clock", frozenset({ROLE_CONTROLLERS}),
         "controllers use the injected clock seams, never "
         "time.time/monotonic/datetime.now (PR 5 observed-staleness "
         "anchoring; deterministic envtest time)", check_naked_wall_clock),
    Rule("PL005", "metrics-registered-late", frozenset({ROLE_PACKAGE}),
         "prometheus collectors are registered exactly once at module "
         "scope, never inside functions/reconcile loops (PR 1 metrics "
         "surface)", check_metrics_registration),
    Rule("PL006", "await-holding-sync-lock", frozenset({ROLE_PACKAGE}),
         "no await while holding a non-async lock (lock held across a "
         "suspension point blocks the whole loop)",
         check_await_holding_sync_lock),
    Rule("PL007", "untracked-task", frozenset({ROLE_PACKAGE}),
         "every asyncio.create_task/ensure_future result is retained and "
         "tracked for teardown (PR 4 tracker-poller leak class)",
         check_untracked_task),
    Rule("PL008", "mutable-default", _ASYNC_ROLES | {ROLE_CLOUDPROVIDER},
         "no mutable default arguments in control-plane signatures",
         check_mutable_default),
    Rule("PL009", "ungated-crash-point",
         frozenset({ROLE_PACKAGE}),
         "crash points fire only through a None-gated seam; control-plane "
         "layers never import crash types (PR 3 chaos layering)",
         check_ungated_crash_point),
    Rule("PL010", "unbounded-sleep-poll", frozenset({ROLE_TESTS}),
         "test polling loops carry an explicit deadline, not bare "
         "asyncio.sleep spins", check_unbounded_sleep_poll),
    Rule("PL011", "unregistered-pytest-marker", frozenset({ROLE_TESTS}),
         "pytest markers used in tests are registered in pyproject.toml",
         check_unregistered_marker),
    Rule("PL012", "unclosed-span", frozenset({ROLE_PACKAGE}),
         "claimtrace span_begin is closed via tracer.span() or a "
         "try/finally span_end — an open span leaks trace ids into every "
         "later log line on the task (PR 9 claimtrace)", check_unclosed_span),
    Rule("PL013", "literal-error-reason", frozenset({ROLE_PACKAGE}),
         "CreateError reasons and terminal-vs-retryable branching come from "
         "the errors.py reason enum, never string literals at call sites "
         "(PR 10 capacity placement: a drifted literal flips a terminal "
         "fault into an infinite retry)", check_literal_error_reason),
    Rule("PL014", "unsourced-requeue-wait", frozenset({ROLE_CONTROLLERS}),
         "every controller Result(requeue_after=...) names its wake source "
         "— a `# wakes: <source>` annotation or an in-function WakeHub wake "
         "(PR 11 event-driven control plane: the timer is the safety net, "
         "never the undeclared primary)", check_unsourced_requeue_wait),
    Rule("PL015", "unclassified-watch-gap",
         frozenset({ROLE_RUNTIME, ROLE_PROVIDERS}),
         "watch/list pump loops with broad error handlers must branch on "
         "expired-resourceVersion (ResourceExpiredError / .expired / "
         ".gone) — a 410 swallowed into generic retry reconnects forever "
         "and silently diverges the cache (PR 16 watch-gap resync)",
         check_unclassified_watch_gap),
]
