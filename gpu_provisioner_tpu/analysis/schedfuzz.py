"""schedfuzz: seeded deterministic interleaving exploration.

provlint checks what the code *says*; provgraph checks how the modules
*relate*; neither can see the bug class PR 11 actually shipped and
reverted twice in review — orderings. "Cache-apply before handler
delivery", "meta patch before status patch", "fence check before cloud
mutate", "hub stopped means no more wakes" are all happens-before
contracts: every individual statement is correct, and the defect only
exists in the *schedule* — which asyncio callback ran first. The default
event loop is FIFO, so the buggy schedule may essentially never occur on a
developer laptop and then occur at fleet scale under load.

schedfuzz makes the schedule an input:

- :class:`SchedFuzzLoop` is a drop-in ``SelectorEventLoop`` whose
  ``call_soon`` perturbs the ready queue with a seeded RNG — sometimes the
  newly scheduled callback jumps the queue, sometimes a victim already in
  the queue is pushed to the back (a forced yield). Same seed + same
  scenario → same decision stream.
- The probe seam (:mod:`..runtime.probes`) records the ordering-relevant
  events while a scenario runs: ``cache-apply`` / ``handler-delivery``
  (informer relay), ``wq-enqueue`` / ``wq-timer-due`` / ``wq-stale-drop``
  (workqueue epoch guard), ``fence-check`` / ``cloud-mutate`` (leader
  fence), ``meta-patch`` / ``status-patch`` (status writer), ``hub-wake``
  / ``hub-stop`` (wake hub). Probes are module-global and disarmed by
  default — production pays one ``is None`` check per site.
- The happens-before checkers (:data:`CHECKERS`) replay the recorded event
  stream and assert each contract. A violated contract is reported with
  the event index and a human diagnosis.
- :func:`explore` sweeps a seed range; any failing seed is written as a
  **replay file** (JSON: scenario name, seed, perturbation probability,
  decision trace, violations) and :func:`replay` re-runs it. The RNG
  stream is fully determined by the seed, so re-running the scenario with
  the replay file's seed re-derives the same decision sequence whenever
  the scenario itself is deterministic; the envtest scenarios use
  wall-clock timers, so the guarantee in practice is "the same seed finds
  the same violation", which the mutation tests in
  tests/test_schedfuzz.py pin down.

Run it: ``make fuzz`` (seed budget via ``FUZZ_SEEDS``), or directly::

    python -m gpu_provisioner_tpu.analysis.schedfuzz --seeds 25
    python -m gpu_provisioner_tpu.analysis.schedfuzz --replay \\
        .schedfuzz/replay-wave-seed7.json

See docs/STATIC_ANALYSIS.md#schedfuzz for the catalog of contracts and
how to write a scenario.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import random
import sys
from collections import Counter, deque
from pathlib import Path
from typing import Callable, Iterable, Optional

from ..runtime import probes

DEFAULT_SEEDS = 20
DEFAULT_PERTURB = 0.25
DEFAULT_TIMEOUT = 60.0
DEFAULT_REPLAY_DIR = ".schedfuzz"
REPLAY_FORMAT = "schedfuzz-replay/1"


# --------------------------------------------------------------- loop shim

class SchedFuzzLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop with a seeded ready-queue perturber.

    Every ``call_soon`` may (with probability ``perturb_prob``) reorder the
    loop's ready queue: promote the new handle to the front, or rotate an
    already-queued handle to the back. Both are schedules plain asyncio is
    allowed to produce across versions/platforms/load — the shim only
    *chooses* among legal interleavings, it never drops or duplicates a
    callback, so a violation found here is a real program bug, not an
    artifact. Timer ordering (``call_at``) is untouched: timers enter the
    ready queue through ``_run_once`` and their relative deadline order is
    part of the loop contract; what the shim varies is who runs first once
    several callbacks are runnable, which is exactly the freedom production
    load exercises.

    Decisions are recorded as ``(call_index, op, arg)`` triples (op 1 =
    new-handle-to-front, op 2 = victim ``arg`` rotated to back) — the
    replay file carries them for diagnosis.
    """

    def __init__(self, seed: int, perturb_prob: float = DEFAULT_PERTURB):
        super().__init__()
        self.seed = seed
        self.perturb_prob = perturb_prob
        self._rng = random.Random(seed)
        self.call_soon_total = 0
        self.perturbed_total = 0
        self.decisions: list[tuple[int, int, int]] = []
        # _ready is a CPython BaseEventLoop internal; if it ever changes
        # shape, degrade to a plain (un-perturbed) loop rather than crash.
        self._fuzz_armed = isinstance(getattr(self, "_ready", None), deque)

    def call_soon(self, callback, *args, context=None):
        handle = super().call_soon(callback, *args, context=context)
        if self._fuzz_armed:
            self._perturb()
        return handle

    def _perturb(self) -> None:
        self.call_soon_total += 1
        rng = self._rng
        # rng.random() is consumed unconditionally so the decision stream
        # depends only on the call_soon sequence, not on queue depth.
        roll = rng.random()
        ready = self._ready
        if roll >= self.perturb_prob or len(ready) < 2:
            return
        if rng.randrange(2) == 0:
            # the newcomer (tail) jumps the whole queue
            ready.appendleft(ready.pop())
            self.decisions.append((self.call_soon_total, 1, 0))
        else:
            # a victim already queued is pushed behind the newcomer — the
            # forced-yield schedule
            victim = rng.randrange(len(ready) - 1)
            h = ready[victim]
            del ready[victim]
            ready.append(h)
            self.decisions.append((self.call_soon_total, 2, victim))
        self.perturbed_total += 1


# ---------------------------------------------------------------- recorder

@dataclasses.dataclass
class FuzzEvent:
    seq: int
    name: str
    key: object
    task: Optional[str]          # "Task-7#7f3a..." — fence scoping
    info: dict


class TraceRecorder:
    """The probe sink: records every emitted event with its sequence
    number and the asyncio task it fired on (probes fire synchronously, so
    this IS program order on the loop)."""

    def __init__(self) -> None:
        self.events: list[FuzzEvent] = []

    def __call__(self, event: str, key, **info) -> None:
        try:
            t = asyncio.current_task()
        except RuntimeError:
            t = None
        task = None if t is None else f"{t.get_name()}#{id(t):x}"
        self.events.append(
            FuzzEvent(len(self.events), event, key, task, info))


# ---------------------------------------------------- happens-before rules

@dataclasses.dataclass
class Violation:
    checker: str
    seq: int
    message: str


def check_cache_before_deliver(events: list[FuzzEvent]) -> list[Violation]:
    """A controller handler must never be handed a watch event its informer
    cache cannot serve yet (RelayWatch's post-cache-apply ordering,
    controller-runtime parity). Counted per object key: at any handler
    delivery, the cache must have applied at least as many updates for that
    key as this controller has been handed. Kinds that never produce a
    ``cache-apply`` are uncached (raw watches) and exempt."""
    cached_kinds = {e.key[0] for e in events if e.name == "cache-apply"}
    applies: Counter = Counter()
    delivered: Counter = Counter()
    out: list[Violation] = []
    for e in events:
        if e.name == "cache-apply":
            applies[e.key] += 1
        elif e.name == "handler-delivery" and e.key[0] in cached_kinds:
            slot = (e.info.get("controller"), e.key)
            delivered[slot] += 1
            if delivered[slot] > applies[e.key]:
                out.append(Violation(
                    "cache-before-deliver", e.seq,
                    f"controller {slot[0]!r} handed delivery "
                    f"#{delivered[slot]} for {e.key} but its cache has "
                    f"applied only {applies[e.key]} update(s) — the handler "
                    f"can read stale cache for the object it was woken "
                    f"for (post-cache-apply relay ordering broken)"))
    return out


def check_stale_timer_requeue(events: list[FuzzEvent]) -> list[Violation]:
    """A safety-net timer that fires stale (the item's wake epoch moved on
    while it was parked) must be DROPPED, never enqueued: the wake that
    bumped the epoch already ran the reconcile, and re-firing the old
    timer is the spurious double-reconcile the epoch guard exists to
    kill."""
    pending: dict = {}
    out: list[Violation] = []
    for e in events:
        if e.name == "wq-timer-due" and e.info.get("stale"):
            pending[e.key] = e.seq
        elif e.name == "wq-stale-drop":
            pending.pop(e.key, None)
        elif e.name == "wq-enqueue":
            if e.key in pending and e.info.get("source") == "timer":
                out.append(Violation(
                    "stale-timer-requeue", e.seq,
                    f"workqueue item {e.key!r} came due STALE (armed at an "
                    f"older wake epoch) but was enqueued as a timer wake "
                    f"instead of dropped — the epoch guard is not holding "
                    f"and every event wake costs a spurious extra "
                    f"reconcile"))
            pending.pop(e.key, None)
    return out


def check_fence_before_mutate(events: list[FuzzEvent]) -> list[Violation]:
    """Every cloud mutation must be preceded, on the same asyncio task, by
    a leadership fence check — the interleaving form of provlint PL003 /
    provgraph PG003: the static rules prove a check exists in the code
    path, this proves one actually RAN before the call left the
    process."""
    fenced: set = set()
    out: list[Violation] = []
    for e in events:
        if e.name == "fence-check" and e.task is not None:
            fenced.add(e.task)
        elif e.name == "cloud-mutate":
            if e.task is None or e.task not in fenced:
                out.append(Violation(
                    "fence-before-mutate", e.seq,
                    f"cloud mutation {e.key} issued on task {e.task} with "
                    f"no fence check earlier on that task — a deposed "
                    f"leader could still mutate the cloud"))
    return out


def check_meta_before_status(events: list[FuzzEvent]) -> list[Violation]:
    """Per claim: the status patch never outruns the meta patch (Ready must
    not be observable while launch-merged labels are unwritten — the
    ``write_claim_patches`` invariant, here checked across every writer
    and interleaving rather than inside one call)."""
    meta: Counter = Counter()
    status: Counter = Counter()
    out: list[Violation] = []
    for e in events:
        if e.name == "meta-patch":
            meta[e.key] += 1
        elif e.name == "status-patch":
            status[e.key] += 1
            if status[e.key] > meta[e.key]:
                out.append(Violation(
                    "meta-before-status", e.seq,
                    f"claim {e.key!r} status patch #{status[e.key]} landed "
                    f"with only {meta[e.key]} meta patch(es) written — a "
                    f"watcher can observe conditions (incl. Ready) before "
                    f"the launch-merged labels exist"))
    return out


def check_stop_before_late_wake(events: list[FuzzEvent]) -> list[Violation]:
    """After ``WakeHub.stop()`` no wake may deliver from that hub — a late
    wake would enqueue into a workqueue that is shutting down (the PL007
    teardown-leak bug class, caught as an ordering instead of a leak)."""
    stopped: set = set()
    out: list[Violation] = []
    for e in events:
        if e.name == "hub-stop":
            stopped.add(e.key)
        elif e.name == "hub-wake" and e.key in stopped:
            out.append(Violation(
                "stop-before-late-wake", e.seq,
                f"WakeHub {e.key} delivered wake {e.info.get('name')!r} "
                f"(source={e.info.get('source')!r}) after stop() — "
                f"teardown does not quiesce the wake graph"))
    return out


def check_partition_fenced_mutate(events: list[FuzzEvent]) -> list[Violation]:
    """No cloud mutation may land while the APIHealthGovernor holds the
    incarnation in PARTITIONED — the apiserver is unreachable, so the
    mutation's outcome could not be recorded and a healed restart would
    re-create the pool (the duplicate-pool-factory class, PR 16). The
    governor emits ``api-mode`` on every transition; this replays the
    mode timeline and flags any ``cloud-mutate`` inside a PARTITIONED
    window. The runtime guard is the provider's PartitionFencedError
    raise in ``_fence_check``; this proves it held across interleavings."""
    mode = "HEALTHY"
    out: list[Violation] = []
    for e in events:
        if e.name == "api-mode":
            mode = str(e.key)
        elif e.name == "cloud-mutate" and mode == "PARTITIONED":
            out.append(Violation(
                "partition-fenced-mutate", e.seq,
                f"cloud mutation {e.key} issued while the governor was "
                f"PARTITIONED — the kube apiserver is unreachable, the "
                f"outcome cannot be recorded, and a healed incarnation "
                f"would re-create the pool (duplicate-pool factory)"))
    return out


CHECKERS: dict[str, Callable[[list[FuzzEvent]], list[Violation]]] = {
    "cache-before-deliver": check_cache_before_deliver,
    "stale-timer-requeue": check_stale_timer_requeue,
    "fence-before-mutate": check_fence_before_mutate,
    "meta-before-status": check_meta_before_status,
    "stop-before-late-wake": check_stop_before_late_wake,
    "partition-fenced-mutate": check_partition_fenced_mutate,
}


# ------------------------------------------------------------------ runner

@dataclasses.dataclass
class FuzzResult:
    scenario: str
    seed: int
    perturb_prob: float
    events: list[FuzzEvent]
    violations: list[Violation]
    decisions: list[tuple[int, int, int]]
    call_soon_total: int
    perturbed_total: int
    error: Optional[str] = None
    replay_path: Optional[Path] = None

    @property
    def ok(self) -> bool:
        return not self.violations and self.error is None


def run_scenario(scenario: Callable[[], object], seed: int, *,
                 name: Optional[str] = None,
                 checkers: Optional[dict] = None,
                 perturb_prob: float = DEFAULT_PERTURB,
                 timeout: float = DEFAULT_TIMEOUT) -> FuzzResult:
    """Run one scenario coroutine under a perturbed loop with the probe
    seam armed; replay the recorded events through the checkers.

    The scenario runs on a private :class:`SchedFuzzLoop` (installed as the
    thread's loop for the duration, restored after); a scenario exception
    is captured into ``result.error`` — an interleaving-induced crash is a
    finding, not a harness failure.
    """
    checkers = CHECKERS if checkers is None else checkers
    loop = SchedFuzzLoop(seed, perturb_prob)
    rec = TraceRecorder()
    prev = probes.arm(rec)
    error: Optional[str] = None
    asyncio.set_event_loop(loop)
    try:
        try:
            loop.run_until_complete(
                asyncio.wait_for(scenario(), timeout=timeout))
        except Exception as exc:  # noqa: BLE001 — captured as a finding
            error = f"{type(exc).__name__}: {exc}"
    finally:
        probes.disarm(prev)
        try:
            _drain(loop)
        finally:
            asyncio.set_event_loop(None)
            loop.close()
    violations: list[Violation] = []
    for fn in checkers.values():
        violations.extend(fn(rec.events))
    violations.sort(key=lambda v: v.seq)
    return FuzzResult(
        scenario=name or getattr(scenario, "__name__", "scenario"),
        seed=seed, perturb_prob=perturb_prob, events=rec.events,
        violations=violations, decisions=loop.decisions,
        call_soon_total=loop.call_soon_total,
        perturbed_total=loop.perturbed_total, error=error)


def _drain(loop: asyncio.AbstractEventLoop) -> None:
    pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
    for t in pending:
        t.cancel()
    if pending:
        loop.run_until_complete(
            asyncio.gather(*pending, return_exceptions=True))
    loop.run_until_complete(loop.shutdown_asyncgens())


def explore(scenario: Callable[[], object], *, name: Optional[str] = None,
            seeds: Iterable[int] = range(DEFAULT_SEEDS),
            perturb_prob: float = DEFAULT_PERTURB,
            checkers: Optional[dict] = None,
            replay_dir: Optional[object] = None,
            stop_on_first: bool = False,
            timeout: float = DEFAULT_TIMEOUT) -> list[FuzzResult]:
    """Seed sweep: run the scenario once per seed; failing seeds get a
    replay file in ``replay_dir`` (when given). ``stop_on_first`` returns
    as soon as one seed fails — the mutation tests use it so the seed
    budget is an upper bound, not a fixed cost."""
    results: list[FuzzResult] = []
    for seed in seeds:
        res = run_scenario(scenario, seed, name=name, checkers=checkers,
                           perturb_prob=perturb_prob, timeout=timeout)
        results.append(res)
        if not res.ok:
            if replay_dir is not None:
                res.replay_path = write_replay(res, replay_dir)
            if stop_on_first:
                break
    return results


# -------------------------------------------------------------- replay I/O

def write_replay(result: FuzzResult, out_dir) -> Path:
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / f"replay-{result.scenario}-seed{result.seed}.json"
    payload = {
        "format": REPLAY_FORMAT,
        "scenario": result.scenario,
        "seed": result.seed,
        "perturb_prob": result.perturb_prob,
        "call_soon_total": result.call_soon_total,
        "perturbed_total": result.perturbed_total,
        "decisions": [list(d) for d in result.decisions],
        "violations": [dataclasses.asdict(v) for v in result.violations],
        "error": result.error,
        "repro": ("python -m gpu_provisioner_tpu.analysis.schedfuzz "
                  f"--replay {path}"),
    }
    path.write_text(json.dumps(payload, indent=2, default=str) + "\n",
                    encoding="utf-8")
    return path


def replay(path, *, scenarios: Optional[dict] = None,
           checkers: Optional[dict] = None,
           timeout: float = DEFAULT_TIMEOUT) -> FuzzResult:
    """Re-run the scenario+seed a replay file records. The decision trace
    in the file is diagnostic — the rerun re-derives it from the seed."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if data.get("format") != REPLAY_FORMAT:
        raise ValueError(f"{path}: not a {REPLAY_FORMAT} file")
    scenarios = SCENARIOS if scenarios is None else scenarios
    fn = scenarios.get(data["scenario"])
    if fn is None:
        raise ValueError(f"{path}: unknown scenario {data['scenario']!r}")
    return run_scenario(fn, data["seed"], name=data["scenario"],
                        checkers=checkers,
                        perturb_prob=data.get("perturb_prob",
                                              DEFAULT_PERTURB),
                        timeout=timeout)


# ------------------------------------------------------ built-in scenarios

def fuzz_options(**overrides):
    """Envtest options tuned for interleaving density, not realism: tiny
    latencies so many callbacks are runnable at once (more schedules to
    choose among per seed), detectors' stall budget off (the perturber
    deliberately delays callbacks; that is the point, not a stall)."""
    from ..envtest import EnvtestOptions
    base = dict(
        use_informer=True,
        create_latency=0.01, delete_latency=0.01, qr_step_latency=0.0,
        node_join_delay=0.0, node_ready_delay=0.0,
        node_wait_interval=0.01,
        instance_cache_ttl=0.05, instance_cache_negative_ttl=0.02,
        gc_interval=0.5, leak_grace=0.5,
        stall_budget=0.0,
    )
    base.update(overrides)
    return EnvtestOptions(**base)


async def scenario_wave() -> None:
    """Small provisioning wave through the informer-cached wiring — the
    densest ordering surface: relay fanout, LRO wakes, status batching,
    fence-checked creates, teardown quiesce."""
    from ..envtest import Env
    from ..fake import make_nodeclaim
    async with Env(fuzz_options()) as env:
        names = [f"fz{i}" for i in range(3)]
        for n in names:
            await env.client.create(make_nodeclaim(n))
        for n in names:
            await env.wait_ready(n)


async def scenario_churn() -> None:
    """Provision, deprovision mid-flight, provision again: exercises the
    delete path's fences, stale safety-net timers (the woken claim's
    parked requeues), and late-wake pressure at teardown."""
    from ..apis.karpenter import NodeClaim
    from ..envtest import Env
    from ..fake import make_nodeclaim
    async with Env(fuzz_options()) as env:
        await env.client.create(make_nodeclaim("fz-keep"))
        await env.client.create(make_nodeclaim("fz-churn"))
        await env.wait_ready("fz-keep")
        await env.wait_ready("fz-churn")
        await env.client.delete(NodeClaim, "fz-churn")
        await env.wait_gone("fz-churn")
        await env.client.create(make_nodeclaim("fz-late"))
        await env.wait_ready("fz-late")


SCENARIOS: dict[str, Callable[[], object]] = {
    "wave": scenario_wave,
    "churn": scenario_churn,
}


# --------------------------------------------------------------------- CLI

def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="schedfuzz",
        description="Seeded interleaving explorer for the provisioner's "
                    "happens-before contracts "
                    "(docs/STATIC_ANALYSIS.md#schedfuzz).")
    ap.add_argument("--scenario", action="append", default=None,
                    choices=sorted(SCENARIOS), metavar="NAME",
                    help="scenario(s) to sweep (default: all): "
                         + ", ".join(sorted(SCENARIOS)))
    ap.add_argument("--seeds", type=int, default=DEFAULT_SEEDS,
                    help=f"seed budget per scenario (default "
                         f"{DEFAULT_SEEDS})")
    ap.add_argument("--seed-base", type=int, default=0,
                    help="first seed of the sweep (default 0)")
    ap.add_argument("--perturb", type=float, default=DEFAULT_PERTURB,
                    help=f"per-call_soon perturbation probability "
                         f"(default {DEFAULT_PERTURB})")
    ap.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT,
                    help="per-run scenario timeout in seconds")
    ap.add_argument("--replay-dir", default=DEFAULT_REPLAY_DIR,
                    help="where failing seeds' replay files go "
                         f"(default {DEFAULT_REPLAY_DIR}/)")
    ap.add_argument("--replay", metavar="FILE",
                    help="re-run one replay file instead of sweeping")
    args = ap.parse_args(argv)

    if args.replay:
        res = replay(args.replay, timeout=args.timeout)
        _print_failures(res)
        state = "reproduced" if not res.ok else "did NOT reproduce"
        print(f"schedfuzz replay {args.replay}: scenario={res.scenario} "
              f"seed={res.seed} — failure {state}")
        return 0 if not res.ok else 1

    names = args.scenario or sorted(SCENARIOS)
    seeds = range(args.seed_base, args.seed_base + args.seeds)
    rc = 0
    for nm in names:
        results = explore(SCENARIOS[nm], name=nm, seeds=seeds,
                          perturb_prob=args.perturb,
                          replay_dir=args.replay_dir,
                          timeout=args.timeout)
        bad = [r for r in results if not r.ok]
        print(f"schedfuzz {nm}: {len(results)} seed(s), "
              f"{sum(len(r.events) for r in results)} events, "
              f"{sum(r.perturbed_total for r in results)} perturbations, "
              f"{len(bad)} failing seed(s)")
        for r in bad:
            rc = 1
            _print_failures(r)
            if r.replay_path is not None:
                print(f"  replay file: {r.replay_path}")
    return rc


def _print_failures(res: FuzzResult) -> None:
    for v in res.violations:
        print(f"  seed {res.seed} event {v.seq}: [{v.checker}] "
              f"{v.message}")
    if res.error:
        print(f"  seed {res.seed}: scenario error: {res.error}")


if __name__ == "__main__":
    sys.exit(main())
