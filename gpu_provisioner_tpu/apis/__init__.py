"""Kubernetes-style API types (hand-built; no k8s client library exists here).

The reference vendors ``k8s.io/apimachinery`` + the karpenter.sh/v1 NodeClaim
CRD (see SURVEY.md §2b V10). This package re-creates the load-bearing subset as
plain dataclasses with camelCase JSON round-tripping, so objects serialize
exactly like their Kubernetes counterparts (YAML examples, REST payloads, CRD
storage) while staying idiomatic Python in-process.
"""
