"""core/v1 subset: Node, Pod, VolumeAttachment, Event.

Only the fields the controllers actually read/write exist (the reference gets
the full types from k8s.io/api; the load-bearing subset is what registration
(registration.go:120-147), initialization (initialization.go:54-77), drain
(terminator/terminator.go:96-117) and volume-detach wait touch).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime
from typing import ClassVar, Optional

from .meta import Condition, Object, ObjectMeta, register_kind

# Node condition types / taint effects
NODE_READY = "Ready"
EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE

    def matches(self, other: "Taint") -> bool:
        return self.key == other.key and self.effect == other.effect


@dataclass
class NodeSystemInfo:
    architecture: str = "amd64"
    operating_system: str = "linux"
    kubelet_version: str = ""


@dataclass
class NodeSpec:
    provider_id: str = field(default="", metadata={"json": "providerID"})
    taints: list[Taint] = field(default_factory=list)
    unschedulable: bool = False


@dataclass
class NodeStatus:
    capacity: dict[str, str] = field(default_factory=dict)
    allocatable: dict[str, str] = field(default_factory=dict)
    conditions: list[Condition] = field(default_factory=list)
    node_info: NodeSystemInfo = field(default_factory=NodeSystemInfo)


@register_kind
@dataclass
class Node(Object):
    API_VERSION: ClassVar[str] = "v1"
    KIND: ClassVar[str] = "Node"
    NAMESPACED: ClassVar[bool] = False

    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    def ready_condition(self) -> Optional[Condition]:
        for c in self.status.conditions:
            if c.type == NODE_READY:
                return c
        return None

    def is_ready(self) -> bool:
        c = self.ready_condition()
        return c is not None and c.status == "True"


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"
    value: str = ""
    effect: str = ""

    def tolerates(self, taint: Taint) -> bool:
        if self.key and self.key != taint.key:
            return False
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class PodSpec:
    node_name: str = ""
    priority: int = 0
    tolerations: list[Toleration] = field(default_factory=list)
    termination_grace_period_seconds: Optional[int] = None


@dataclass
class PodStatus:
    phase: str = "Pending"


@register_kind
@dataclass
class Pod(Object):
    API_VERSION: ClassVar[str] = "v1"
    KIND: ClassVar[str] = "Pod"
    NAMESPACED: ClassVar[bool] = True

    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def is_terminal(self) -> bool:
        return self.status.phase in ("Succeeded", "Failed")

    def is_owned_by_daemonset(self) -> bool:
        return any(o.kind == "DaemonSet" for o in self.metadata.owner_references)


@dataclass
class LabelSelector:
    match_labels: dict[str, str] = field(default_factory=dict)

    def matches(self, labels: dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.match_labels.items())


@dataclass
class PodDisruptionBudgetSpec:
    selector: LabelSelector = field(default_factory=LabelSelector)
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None


@register_kind
@dataclass
class PodDisruptionBudget(Object):
    """policy/v1 PDB subset — the eviction subresource honors these
    server-side; the in-memory client and the e2e fake apiserver evaluate
    them so the eviction queue's 429 path (terminator/eviction.go:199-209)
    is testable without a real cluster."""

    API_VERSION: ClassVar[str] = "policy/v1"
    KIND: ClassVar[str] = "PodDisruptionBudget"
    NAMESPACED: ClassVar[bool] = True

    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)

    def disruptions_allowed(self, pods: list["Pod"]) -> int:
        """Allowed evictions among ``pods`` (same namespace). Healthy means
        non-terminal — the fake evaluates budgets live rather than via the
        disruption controller's cached status."""
        selected = [p for p in pods
                    if self.spec.selector.matches(p.metadata.labels)]
        healthy = sum(1 for p in selected if not p.is_terminal())
        if self.spec.max_unavailable is not None:
            unavailable = len(selected) - healthy
            return max(0, self.spec.max_unavailable - unavailable)
        if self.spec.min_available is not None:
            return max(0, healthy - self.spec.min_available)
        return healthy


@dataclass
class VolumeAttachmentSpec:
    node_name: str = ""
    attacher: str = ""


@register_kind
@dataclass
class VolumeAttachment(Object):
    API_VERSION: ClassVar[str] = "storage.k8s.io/v1"
    KIND: ClassVar[str] = "VolumeAttachment"
    NAMESPACED: ClassVar[bool] = False

    spec: VolumeAttachmentSpec = field(default_factory=VolumeAttachmentSpec)


@dataclass
class ObjectReference:
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@register_kind
@dataclass
class Event(Object):
    """Cluster events published by the recorder (reference: lifecycle/events.go,
    terminator/events/, health/events.go)."""

    API_VERSION: ClassVar[str] = "v1"
    KIND: ClassVar[str] = "Event"
    NAMESPACED: ClassVar[bool] = True

    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"
    count: int = 1
    last_timestamp: Optional[datetime] = None


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: Optional[datetime] = None
    renew_time: Optional[datetime] = None
    lease_transitions: int = 0


@register_kind
@dataclass
class Lease(Object):
    """coordination.k8s.io Lease — leader election (the reference's manager
    elects via Lease, vendor/.../operator/operator.go:157-164; disabled by
    default per options.go:117 but implemented for multi-replica deploys)."""

    API_VERSION: ClassVar[str] = "coordination.k8s.io/v1"
    KIND: ClassVar[str] = "Lease"
    NAMESPACED: ClassVar[bool] = True

    spec: LeaseSpec = field(default_factory=LeaseSpec)
