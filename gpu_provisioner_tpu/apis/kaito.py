"""kaito.sh/v1alpha1 KaitoNodeClass.

The reference ships a deliberately empty cluster-scoped NodeClass shell so
Karpenter's GetSupportedNodeClasses/IsManaged machinery has a GVK to point at
(pkg/apis/v1alpha1/kaitonodeclass.go:28-50, kaitonodeclass_status.go:23-33 —
no-op status conditions). The TPU build keeps the shell but gives spec two
optional, backwards-compatible knobs that are genuinely per-class on GCP.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar

from .meta import Condition, Object, register_kind

GROUP = "kaito.sh"


@dataclass
class KaitoNodeClassSpec:
    # Optional GCP placement hints; empty means "use controller config".
    zones: list[str] = field(default_factory=list)
    reservation: str = ""
    spot: bool = False


@dataclass
class KaitoNodeClassStatus:
    conditions: list[Condition] = field(default_factory=list)


@register_kind
@dataclass
class KaitoNodeClass(Object):
    API_VERSION: ClassVar[str] = "kaito.sh/v1alpha1"
    KIND: ClassVar[str] = "KaitoNodeClass"
    NAMESPACED: ClassVar[bool] = False
    CONDITION_DEPENDENTS: ClassVar[list[str]] = []

    spec: KaitoNodeClassSpec = field(default_factory=KaitoNodeClassSpec)
    status: KaitoNodeClassStatus = field(default_factory=KaitoNodeClassStatus)
