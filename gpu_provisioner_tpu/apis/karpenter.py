"""karpenter.sh/v1 NodeClaim — the provisioning unit of the system.

Hand-built equivalent of the vendored CRD types the reference runs on
(vendor/sigs.k8s.io/karpenter/pkg/apis/v1/nodeclaim.go and
nodeclaim_status.go:26-35): spec carries scheduling requirements with
minValues, resource requests, a nodeClassRef and taints; status carries
providerID/imageID/capacity plus the lifecycle condition ladder
Launched → Registered → Initialized (and Drained / VolumesDetached /
InstanceTerminating during teardown).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar, Optional

from .core import Taint
from .meta import Condition, Object, register_kind

# Status condition types (reference: apis/v1/nodeclaim_status.go:26-35).
LAUNCHED = "Launched"
REGISTERED = "Registered"
INITIALIZED = "Initialized"
DRAINED = "Drained"
VOLUMES_DETACHED = "VolumesDetached"
INSTANCE_TERMINATING = "InstanceTerminating"
CONSOLIDATABLE = "Consolidatable"

# Requirement operators (corev1.NodeSelectorOperator).
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass
class NodeSelectorRequirement:
    """corev1.NodeSelectorRequirement + karpenter's minValues extension
    (reference: apis/v1/nodeclaim.go NodeSelectorRequirementWithMinValues)."""

    key: str = ""
    operator: str = IN
    values: list[str] = field(default_factory=list)
    min_values: Optional[int] = None


@dataclass
class NodeClassRef:
    group: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class ResourceRequirements:
    requests: dict[str, str] = field(default_factory=dict)


@dataclass
class NodeClaimSpec:
    requirements: list[NodeSelectorRequirement] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    node_class_ref: Optional[NodeClassRef] = None
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    termination_grace_period: Optional[str] = None  # metav1.Duration, e.g. "30s"
    expire_after: Optional[str] = None


@dataclass
class NodeClaimStatus:
    provider_id: str = field(default="", metadata={"json": "providerID"})
    image_id: str = field(default="", metadata={"json": "imageID"})
    node_name: str = ""
    capacity: dict[str, str] = field(default_factory=dict)
    allocatable: dict[str, str] = field(default_factory=dict)
    conditions: list[Condition] = field(default_factory=list)


@register_kind
@dataclass
class NodeClaim(Object):
    API_VERSION: ClassVar[str] = "karpenter.sh/v1"
    KIND: ClassVar[str] = "NodeClaim"
    NAMESPACED: ClassVar[bool] = False
    # Ready = Launched ∧ Registered ∧ Initialized (reference: operatorpkg root
    # condition over the lifecycle dependents).
    CONDITION_DEPENDENTS: ClassVar[list[str]] = [LAUNCHED, REGISTERED, INITIALIZED]

    spec: NodeClaimSpec = field(default_factory=NodeClaimSpec)
    status: NodeClaimStatus = field(default_factory=NodeClaimStatus)
