"""Well-known labels, annotations, taints and finalizers.

Karpenter/kaito keys mirror the reference's contract
(vendor/sigs.k8s.io/karpenter/pkg/apis/v1/labels.go:42-61 and
pkg/providers/instance/instance.go:39-50); the ``tpu.kaito.sh/*`` group is the
new slice-topology schema this build adds (SURVEY.md §7 step 1) alongside the
labels GKE itself stamps on TPU nodes, so JAX pods can target and bootstrap a
slice (SURVEY.md §2c).
"""

# --- karpenter.sh core contract -------------------------------------------
GROUP = "karpenter.sh"
NODEPOOL_LABEL = "karpenter.sh/nodepool"
CAPACITY_TYPE_LABEL = "karpenter.sh/capacity-type"
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_RESERVED = "reserved"

TERMINATION_FINALIZER = "karpenter.sh/termination"
UNREGISTERED_TAINT = "karpenter.sh/unregistered"
DISRUPTED_TAINT = "karpenter.sh/disrupted"
DO_NOT_DISRUPT_ANNOTATION = "karpenter.sh/do-not-disrupt"
TERMINATION_TIMESTAMP_ANNOTATION = "karpenter.sh/nodeclaim-termination-timestamp"

# --- kubernetes core -------------------------------------------------------
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
ARCH_LABEL = "kubernetes.io/arch"
OS_LABEL = "kubernetes.io/os"
HOSTNAME_LABEL = "kubernetes.io/hostname"
ZONE_LABEL = "topology.kubernetes.io/zone"
REGION_LABEL = "topology.kubernetes.io/region"

# --- kaito.sh ownership contract (reference: instance.go:39-50,330-342) ----
KAITO_NODEPOOL_NAME = "kaito"  # NodePool label value marking kaito-owned capacity
KAITO_WORKSPACE_LABEL = "kaito.sh/workspace"
KAITO_RAGENGINE_LABEL = "kaito.sh/ragengine"
KAITO_MACHINE_TYPE_LABEL = "kaito.sh/machine-type"  # "tpu" | "cpu" (ref: gpu|cpu)
KAITO_CREATION_TIMESTAMP_LABEL = "kaito.sh/creation-timestamp"
KAITO_NODE_IMAGE_FAMILY_ANNOTATION = "kaito.sh/node-image-family"

# --- GKE-native TPU node labels (stamped by GKE on TPU node pools) ---------
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"
GKE_SPOT_LABEL = "cloud.google.com/gke-spot"
TPU_RESOURCE_NAME = "google.com/tpu"  # extended resource registered by device plugin

# --- tpu.kaito.sh: the new slice-topology propagation schema ---------------
# These ride NodeClaim requirements → Instance labels → Node labels so that
# (a) the catalog can resolve a slice shape and (b) JAX pods can compute their
# mesh/coordinator (parallel/topology.py consumes them).
TPU_ACCELERATOR_LABEL = "tpu.kaito.sh/accelerator"     # e.g. "v5e", "v5p"
TPU_TOPOLOGY_LABEL = "tpu.kaito.sh/topology"           # e.g. "2x4", "2x2x4"
TPU_CHIPS_LABEL = "tpu.kaito.sh/chips"                 # total chips in slice
TPU_HOSTS_LABEL = "tpu.kaito.sh/hosts"                 # VM count in slice
TPU_SLICE_ID_LABEL = "tpu.kaito.sh/slice-id"           # node-pool name
TPU_WORKER_INDEX_LABEL = "tpu.kaito.sh/worker-index"   # 0..hosts-1, per node
TPU_SLICE_GROUP_LABEL = "tpu.kaito.sh/slice-group"     # multi-slice DCN group
# Multi-slice identity, stamped by the instance provider at create so every
# member of a slice-group can bootstrap jax.distributed with NO manual env
# (the analog of the reference stamping labels at create, instance.go:321-369,
# synced to nodes by registration.go:120-147):
TPU_SLICE_INDEX_LABEL = "tpu.kaito.sh/slice-index"     # 0..num_slices-1
TPU_NUM_SLICES_LABEL = "tpu.kaito.sh/num-slices"       # group size
TPU_COORDINATOR_LABEL = "tpu.kaito.sh/coordinator"     # worker 0 of slice 0
# Capacity tier the slice was actually placed on (reserved|on-demand|spot):
# rides NodeClaim requirements → pool config labels → Node labels so the
# placement engine can filter candidates and workloads can see what tier
# they landed on. Values reuse the karpenter CAPACITY_TYPE_* constants.
TPU_CAPACITY_TIER_LABEL = "tpu.kaito.sh/capacity-tier"

# Taint applied by GKE to TPU nodes; tolerated by TPU workloads.
TPU_TAINT = "google.com/tpu"

# e2e test-discovery label (reference: vendor/.../pkg/test/metadata.go:33).
# Builders stamp DISCOVERY_VALUE; real-cluster e2e teardown sweeps by it —
# the two MUST stay one constant or cleanup silently matches nothing.
DISCOVERY_LABEL = "testing/cluster"
DISCOVERY_VALUE = "tpu-provisioner-e2e"

# Domains whose labels are controller-managed and synced NodeClaim → Node
# (reference: registration.go:120-147 syncs all nodeclaim labels).
MANAGED_LABEL_DOMAINS = ("karpenter.sh", "kaito.sh", "tpu.kaito.sh")
