"""Object metadata, conditions, and the API-object base machinery.

Re-creates the subset of ``k8s.io/apimachinery`` + ``awslabs/operatorpkg/status``
the reference actually uses (SURVEY.md §2b V10/V15): ObjectMeta with finalizers
and deletionTimestamp, owner references, and status conditions with transition
times and a root ``Ready`` condition computed from declared dependents
(reference: operatorpkg status conditions, vendored at
vendor/github.com/awslabs/operatorpkg/status).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from datetime import datetime
from typing import ClassVar, Optional

from .serde import from_dict, now, to_dict

# Condition polarity values (metav1.ConditionStatus).
TRUE = "True"
FALSE = "False"
UNKNOWN = "Unknown"

# Root condition type every object exposes (operatorpkg ConditionReady).
CONDITION_READY = "Ready"


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None
    block_owner_deletion: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    creation_timestamp: Optional[datetime] = None
    deletion_timestamp: Optional[datetime] = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    finalizers: list[str] = field(default_factory=list)
    owner_references: list[OwnerReference] = field(default_factory=list)


@dataclass
class Condition:
    type: str = ""
    status: str = UNKNOWN
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[datetime] = None
    # NodeCondition's kubelet liveness signal (core/v1): refreshed on every
    # kubelet status report even when the status value is unchanged. A
    # heartbeat that stops while ``status`` stays a stale ``True`` is the
    # silent-kubelet-death signature node repair keys off.
    last_heartbeat_time: Optional[datetime] = None
    observed_generation: int = 0


class ConditionSet:
    """Mutator over an object's ``status.conditions`` list.

    Mirrors operatorpkg's condition semantics: setting a condition bumps
    ``lastTransitionTime`` only when the status value actually flips, and the
    root ``Ready`` condition is recomputed from the object's declared
    ``CONDITION_DEPENDENTS`` after every write.
    """

    def __init__(self, obj: "Object"):
        self.obj = obj
        self.deps: list[str] = list(getattr(obj, "CONDITION_DEPENDENTS", []))

    def _conds(self) -> list[Condition]:
        return self.obj.status.conditions

    def get(self, ctype: str) -> Optional[Condition]:
        for c in self._conds():
            if c.type == ctype:
                return c
        return None

    def is_true(self, ctype: str) -> bool:
        c = self.get(ctype)
        return c is not None and c.status == TRUE

    def _set(self, ctype: str, status: str, reason: str, message: str) -> bool:
        c = self.get(ctype)
        changed = c is None or c.status != status
        if c is None:
            c = Condition(type=ctype)
            self._conds().append(c)
        if changed:
            c.last_transition_time = now()
        c.status = status
        c.reason = reason or ctype
        c.message = message
        c.observed_generation = self.obj.metadata.generation
        if ctype != CONDITION_READY:
            self._recompute_ready()
        return changed

    def set_true(self, ctype: str, reason: str = "", message: str = "") -> bool:
        return self._set(ctype, TRUE, reason, message)

    def set_false(self, ctype: str, reason: str, message: str = "") -> bool:
        return self._set(ctype, FALSE, reason, message)

    def set_unknown(self, ctype: str, reason: str = "AwaitingReconciliation",
                    message: str = "") -> bool:
        return self._set(ctype, UNKNOWN, reason, message)

    def clear(self, ctype: str) -> None:
        self.obj.status.conditions = [c for c in self._conds() if c.type != ctype]
        self._recompute_ready()

    def _recompute_ready(self) -> None:
        if not self.deps:
            return
        worst: Optional[Condition] = None
        for d in self.deps:
            c = self.get(d)
            if c is None or c.status == UNKNOWN:
                worst = c or Condition(type=d, status=UNKNOWN, reason="AwaitingReconciliation")
                break
            if c.status == FALSE:
                worst = c
                break
        if worst is None:
            self._set(CONDITION_READY, TRUE, "Ready", "")
        elif worst.status == FALSE:
            self._set(CONDITION_READY, FALSE, worst.reason, worst.message)
        else:
            self._set(CONDITION_READY, UNKNOWN, worst.reason, worst.message)

    def initialize(self) -> None:
        """Seed Unknown conditions for all dependents not yet present."""
        for d in self.deps:
            if self.get(d) is None:
                self._set(d, UNKNOWN, "AwaitingReconciliation", "object is awaiting reconciliation")


@dataclass
class Object:
    """Base for all API objects. Subclasses declare API_VERSION/KIND and may
    declare CONDITION_DEPENDENTS for the Ready-root condition machinery."""

    API_VERSION: ClassVar[str] = ""
    KIND: ClassVar[str] = ""
    NAMESPACED: ClassVar[bool] = False
    CONDITION_DEPENDENTS: ClassVar[list[str]] = []

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    @property
    def status_conditions(self) -> ConditionSet:
        return ConditionSet(self)

    def deepcopy(self):
        return _fast_clone(self)

    def to_dict(self) -> dict:
        d = to_dict(self)
        d["apiVersion"] = self.API_VERSION
        d["kind"] = self.KIND
        return d

    @classmethod
    def from_dict(cls, data: dict):
        data = {k: v for k, v in data.items() if k not in ("apiVersion", "kind")}
        return from_dict(cls, data)


_ATOMIC = (str, int, float, bool, bytes, type(None), datetime)
_ATOMIC_SET = frozenset(_ATOMIC)


def _fast_clone(x, _atomic=_ATOMIC_SET):
    """Structural clone of the API-object dataclass trees ~10× faster than
    copy.deepcopy (no memo machinery / reduce protocol) — the store deepcopies
    on every read, write, and watch fan-out, which made generic deepcopy the
    top CPU cost of a provisioning wave at 100+ concurrent claims.

    The atomic-leaf check is INLINED at every recursion site (a profile of
    the 1024-claim wave showed ~67 _fast_clone calls per object copy,
    ~2/3 of them returning an atomic leaf — the CPython call overhead for
    those dominated the whole wave's clone cost)."""
    t = type(x)
    if t in _atomic or isinstance(x, _ATOMIC):
        return x
    if t is dict:
        return {k: (v if type(v) in _atomic else _fast_clone(v))
                for k, v in x.items()}
    if t is list:
        return [v if type(v) in _atomic else _fast_clone(v) for v in x]
    if t is tuple:
        return tuple(v if type(v) in _atomic else _fast_clone(v)
                     for v in x)
    if t is set:
        return {v if type(v) in _atomic else _fast_clone(v) for v in x}
    d = getattr(x, "__dict__", None)
    if d is not None:
        new = t.__new__(t)
        nd = new.__dict__
        for k, v in d.items():
            nd[k] = v if type(v) in _atomic else _fast_clone(v)
        return new
    import copy
    return copy.deepcopy(x)


# kind registry so the store / envtest loader can round-trip YAML.
_KINDS: dict[str, type] = {}


def register_kind(cls: type) -> type:
    _KINDS[cls.KIND] = cls
    return cls


def kind_for(name: str) -> type:
    try:
        return _KINDS[name]
    except KeyError:
        raise KeyError(
            f"unknown kind {name!r}; registered kinds: {sorted(_KINDS)}") from None


def object_from_manifest(data: dict) -> Object:
    cls = kind_for(data["kind"])
    return cls.from_dict(data)
