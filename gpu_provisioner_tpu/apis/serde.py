"""Dataclass ⇄ camelCase-JSON round-tripping for API objects.

Kubernetes API objects serialize with camelCase keys and RFC3339 timestamps.
Rather than hand-writing ``to_dict``/``from_dict`` on every type (the Go
reference gets this from generated deepcopy/json tags), a single generic walker
handles nested dataclasses, lists, dicts, datetimes and ``Quantity`` strings.

Field-name overrides that don't follow snake→camel (``provider_id`` →
``providerID``) are declared per-field via ``field(metadata={"json": ...})``.
Fields that are ``None`` or empty containers are omitted from output, matching
``omitempty`` semantics in the reference's Go structs.
"""

from __future__ import annotations

import dataclasses
import functools
from datetime import datetime, timezone
from typing import Any, Union, get_args, get_origin, get_type_hints

RFC3339 = "%Y-%m-%dT%H:%M:%SZ"


def now() -> datetime:
    """UTC now, truncated to seconds (Kubernetes metav1.Time resolution)."""
    return datetime.now(timezone.utc).replace(microsecond=0)


def wall_now() -> datetime:
    """UTC now at full precision — the wall-clock seam for age computations
    against sub-second data timestamps (e.g. heartbeat staleness), where
    ``now()``'s metav1 truncation would under-report ages by up to a
    second. Controllers use this (or ``now()``) rather than naked
    ``datetime.now`` so the clock stays a seam (provlint PL004)."""
    return datetime.now(timezone.utc)


def fmt_time(t: datetime) -> str:
    return t.astimezone(timezone.utc).strftime(RFC3339)


def parse_time(s: str) -> datetime:
    """Parse any RFC3339 timestamp (Z or numeric offset, optional fractional
    seconds) to a UTC datetime truncated to seconds."""
    dt = datetime.fromisoformat(s.replace("Z", "+00:00"))
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.astimezone(timezone.utc).replace(microsecond=0)


def snake_to_camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _json_key(f: dataclasses.Field) -> str:
    return f.metadata.get("json", snake_to_camel(f.name))


def to_dict(obj: Any) -> Any:
    """Serialize a dataclass (or container of them) to JSON-ready primitives."""
    if obj is None:
        return None
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if v is None:
                continue
            if isinstance(v, (list, dict)) and not v:
                continue
            out[_json_key(f)] = to_dict(v)
        return out
    if isinstance(obj, datetime):
        return fmt_time(obj)
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def _unwrap_optional(tp: Any) -> Any:
    if get_origin(tp) is Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_dict(cls: type, data: Any) -> Any:
    """Deserialize JSON primitives into dataclass ``cls`` (inverse of to_dict)."""
    if data is None:
        return None
    tp = _unwrap_optional(cls)
    origin = get_origin(tp)
    if origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        return [from_dict(elem, v) for v in data]
    if origin is dict:
        args = get_args(tp)
        val_t = args[1] if len(args) == 2 else Any
        return {k: from_dict(val_t, v) for k, v in data.items()}
    if dataclasses.is_dataclass(tp):
        hints, by_json = _class_info(tp)
        kwargs = {}
        for jk, v in (data or {}).items():
            f = by_json.get(jk)
            if f is None:
                continue
            kwargs[f.name] = from_dict(hints[f.name], v)
        return tp(**kwargs)
    if tp is datetime:
        return parse_time(data) if isinstance(data, str) else data
    return data


@functools.lru_cache(maxsize=None)
def _class_info(tp: type) -> tuple[dict, dict]:
    """Cached (type hints, json-key → field) maps — from_dict is on the hot
    path of every store operation and watch notification."""
    hints = get_type_hints(tp)
    by_json = {_json_key(f): f for f in dataclasses.fields(tp)}
    return hints, by_json
