"""GCP auth: env config + workload-identity credentials (L1, pkg/auth analog)."""

from .config import Config, build_config, ConfigError  # noqa: F401
from .credentials import (  # noqa: F401
    Credentials, FederatedTokenCredential, MetadataServerCredential,
    StaticTokenCredential, new_credential,
)
