"""Env-var configuration (pkg/auth/config.go analog).

The reference reads LOCATION / ARM_RESOURCE_GROUP / AZURE_TENANT_ID /
AZURE_CLIENT_ID / AZURE_CLUSTER_NAME / ARM_SUBSCRIPTION_ID / DEPLOYMENT_MODE
from env (config.go:75-83) and validates at startup (config.go:128-137),
panicking early with an actionable message if workload identity is
misconfigured (pkg/operator/operator.go:46). Same two-layer pattern here with
the GCP equivalents, wired by the Helm chart.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


class ConfigError(Exception):
    pass


@dataclass
class Config:
    project_id: str = ""
    location: str = ""            # zone for zonal clusters, e.g. us-central2-b
    cluster_name: str = ""
    deployment_mode: str = "managed"   # "managed" → ADC/metadata; else federated
    federated_token_file: str = ""     # workload-identity projected token
    service_account_email: str = ""
    e2e_test_mode: bool = False        # reroutes endpoints (azure_client.go:95-100)
    # e2e reroute targets + credential (cred.go:137-153's KeyVault-cert analog
    # is a pre-issued static token here). Empty → production endpoints.
    gke_api_endpoint: str = ""
    tpu_api_endpoint: str = ""
    e2e_static_token: str = ""

    BASE_VARS: tuple[str, ...] = field(default=(
        "PROJECT_ID", "LOCATION", "CLUSTER_NAME"), repr=False)

    def validate(self) -> None:
        missing = [v for v in ("project_id", "location", "cluster_name")
                   if not getattr(self, v)]
        if missing:
            raise ConfigError(
                f"missing required configuration: {', '.join(missing)} — set the "
                "PROJECT_ID / LOCATION / CLUSTER_NAME environment variables "
                "(the Helm chart wires these from values.yaml)")
        if self.deployment_mode not in ("managed", "self-hosted"):
            raise ConfigError(
                f"DEPLOYMENT_MODE must be 'managed' or 'self-hosted', got "
                f"{self.deployment_mode!r}")
        if self.deployment_mode == "self-hosted" and not self.federated_token_file:
            raise ConfigError(
                "DEPLOYMENT_MODE=self-hosted requires GOOGLE_FEDERATED_TOKEN_FILE "
                "(workload-identity projected token path); for GKE workload "
                "identity use DEPLOYMENT_MODE=managed")


def build_config(env: dict[str, str] | None = None) -> Config:
    e = env if env is not None else os.environ
    cfg = Config(
        project_id=e.get("PROJECT_ID", "").strip(),
        location=e.get("LOCATION", "").strip(),
        cluster_name=e.get("CLUSTER_NAME", "").strip(),
        deployment_mode=e.get("DEPLOYMENT_MODE", "managed").strip() or "managed",
        federated_token_file=e.get("GOOGLE_FEDERATED_TOKEN_FILE", "").strip(),
        service_account_email=e.get("GOOGLE_SERVICE_ACCOUNT", "").strip(),
        e2e_test_mode=e.get("E2E_TEST_MODE", "").strip().lower() == "true",
        gke_api_endpoint=e.get("GKE_API_ENDPOINT", "").strip(),
        tpu_api_endpoint=e.get("TPU_API_ENDPOINT", "").strip(),
        e2e_static_token=e.get("E2E_STATIC_TOKEN", "").strip(),
    )
    cfg.validate()
    return cfg
