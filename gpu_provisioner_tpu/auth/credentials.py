"""Workload-identity credentials (pkg/auth/cred.go analog).

The reference's credential ladder: managed mode → DefaultAzureCredential,
self-hosted → ClientAssertionCredential reading the projected AAD JWT from
disk with a 5-minute re-read cache (cred.go:49-135, azure_client.go:78-89).
GCP ladder here: managed → GCE metadata-server token (what GKE workload
identity serves), self-hosted → federated token file exchanged via STS.
Tokens are cached and re-read/refreshed on the same 5-minute cadence
(cred.go:126).
"""

from __future__ import annotations

import json
import time
from typing import Optional, Protocol

import httpx

TOKEN_REREAD_INTERVAL = 300.0  # cred.go:126 (5 min)
METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                      "instance/service-accounts/default/token")
STS_URL = "https://sts.googleapis.com/v1/token"
CLOUD_PLATFORM_SCOPE = "https://www.googleapis.com/auth/cloud-platform"


class Credentials(Protocol):
    async def token(self) -> str: ...


class StaticTokenCredential:
    """Fixed token — tests and the e2e harness (cred.go:137-153's KeyVault
    cert path analog: the harness injects a pre-fetched credential)."""

    def __init__(self, token: str):
        self._token = token

    async def token(self) -> str:
        return self._token


class _CachingCredential:
    def __init__(self):
        self._cached: Optional[str] = None
        self._expires = 0.0

    async def token(self) -> str:
        if self._cached is None or time.monotonic() >= self._expires:
            self._cached = await self._fetch()
            self._expires = time.monotonic() + TOKEN_REREAD_INTERVAL
        return self._cached

    async def _fetch(self) -> str:
        raise NotImplementedError


class MetadataServerCredential(_CachingCredential):
    """GKE workload identity: the metadata server mints access tokens for the
    bound GCP service account (managed-mode analog of DefaultAzureCredential)."""

    def __init__(self, http: Optional[httpx.AsyncClient] = None):
        super().__init__()
        self.http = http or httpx.AsyncClient(timeout=10.0)

    async def _fetch(self) -> str:
        r = await self.http.get(METADATA_TOKEN_URL,
                                headers={"Metadata-Flavor": "Google"})
        r.raise_for_status()
        return r.json()["access_token"]


class FederatedTokenCredential(_CachingCredential):
    """Self-hosted: exchange a projected OIDC token for a GCP access token via
    STS (the AAD ClientAssertionCredential analog, cred.go:49-135). The
    projected token file is re-read on every refresh — kubelet rotates it."""

    def __init__(self, token_file: str, audience: str,
                 http: Optional[httpx.AsyncClient] = None):
        super().__init__()
        self.token_file = token_file
        self.audience = audience
        self.http = http or httpx.AsyncClient(timeout=10.0)

    async def _fetch(self) -> str:
        with open(self.token_file) as f:
            subject_token = f.read().strip()
        r = await self.http.post(STS_URL, data={
            "grant_type": "urn:ietf:params:oauth:grant-type:token-exchange",
            "audience": self.audience,
            "scope": CLOUD_PLATFORM_SCOPE,
            "subject_token_type": "urn:ietf:params:oauth:token-type:jwt",
            "requested_token_type": "urn:ietf:params:oauth:token-type:access_token",
            "subject_token": subject_token,
        })
        r.raise_for_status()
        return r.json()["access_token"]


class ImpersonatedCredential(_CachingCredential):
    """Exchange a base (federated) token for a service-account access token
    via iamcredentials generateAccessToken — the step that makes
    GOOGLE_SERVICE_ACCOUNT effective in self-hosted mode (IAM bindings live
    on the service account, not the workload-identity-pool principal)."""

    def __init__(self, base: Credentials, service_account_email: str,
                 http: Optional[httpx.AsyncClient] = None):
        super().__init__()
        self.base = base
        self.email = service_account_email
        self.http = http or httpx.AsyncClient(timeout=10.0)

    async def _fetch(self) -> str:
        base_token = await self.base.token()
        url = (f"https://iamcredentials.googleapis.com/v1/projects/-/"
               f"serviceAccounts/{self.email}:generateAccessToken")
        r = await self.http.post(url, json={"scope": [CLOUD_PLATFORM_SCOPE]},
                                 headers={"Authorization": f"Bearer {base_token}"})
        r.raise_for_status()
        return r.json()["accessToken"]


def new_credential(cfg) -> Credentials:
    """Credential selection by deployment mode (azure_client.go:78-89);
    e2e mode short-circuits to a pre-issued token (cred.go:137-153)."""
    if getattr(cfg, "e2e_test_mode", False):
        return StaticTokenCredential(cfg.e2e_static_token or "e2e-token")
    if cfg.deployment_mode == "managed":
        return MetadataServerCredential()
    audience = (f"//iam.googleapis.com/projects/{cfg.project_id}/"
                f"locations/global/workloadIdentityPools/kaito/providers/kaito")
    federated = FederatedTokenCredential(cfg.federated_token_file, audience)
    if cfg.service_account_email:
        return ImpersonatedCredential(federated, cfg.service_account_email)
    return federated
