"""TPU accelerator catalog: NodeClaim requirements → slice shape.

This is the component the reference *lacks* (SURVEY.md §7 step 2): Azure's
build passes the VM size string straight through and gates gpu-ness on a
``Standard_N`` prefix (pkg/providers/instance/instance.go:90-95,335-339).
A TPU NodeClaim instead resolves to a **slice shape** — accelerator
generation + ICI topology + host count — because one NodeClaim may
materialize a multi-host node pool (SURVEY.md §2c).

Naming follows Cloud TPU conventions: v4/v5p slice names count TensorCores
(2 per chip — ``v5p-32`` = 16 chips = 4 hosts), v5e/v6e count chips
(``v5e-8`` = 8 chips = 1 host). Aliases (``v5litepod-8``, ``tpu-v5e-8``,
bare topology strings) all resolve. The tables are data, not code — wrong
machine-type strings are a one-line fix.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from .apis import labels as wk
from .scheduling import Requirements


class UnknownShapeError(Exception):
    """Requirements did not resolve to any catalog entry."""


@dataclass(frozen=True)
class SliceShape:
    """One provisionable TPU slice shape."""

    name: str              # canonical instance-type value, e.g. "tpu-v5e-8"
    generation: str        # "v4" | "v5e" | "v5p" | "v6e"
    slice_name: str        # cloud accelerator-type, e.g. "v5e-8" / "v5p-32"
    topology: str          # ICI topology, e.g. "2x4" or "2x2x4"
    chips: int             # total chips in the slice
    hosts: int             # VMs in the node pool (reference Count=1 → this)
    chips_per_host: int
    machine_type: str      # GKE machine type, e.g. "ct5lp-hightpu-8t"
    gke_accelerator: str   # value for cloud.google.com/gke-tpu-accelerator
    cores_per_chip: int = 2
    aliases: tuple[str, ...] = ()

    @property
    def multi_host(self) -> bool:
        return self.hosts > 1

    @property
    def ici_dims(self) -> tuple[int, ...]:
        return tuple(int(d) for d in self.topology.split("x"))

    def node_labels(self, slice_id: str = "", zone: str = "",
                    capacity_tier: str = "") -> dict[str, str]:
        """Labels every node of this slice carries (GKE-native + tpu.kaito.sh).

        ``zone``/``capacity_tier`` record the placement verdict: the zone the
        slice actually landed in (``topology.kubernetes.io/zone`` — before
        this, only ``provider_id`` carried it) and the capacity tier it was
        placed on."""
        out = {
            wk.INSTANCE_TYPE_LABEL: self.name,
            wk.GKE_TPU_ACCELERATOR_LABEL: self.gke_accelerator,
            wk.GKE_TPU_TOPOLOGY_LABEL: self.topology,
            wk.TPU_ACCELERATOR_LABEL: self.generation,
            wk.TPU_TOPOLOGY_LABEL: self.topology,
            wk.TPU_CHIPS_LABEL: str(self.chips),
            wk.TPU_HOSTS_LABEL: str(self.hosts),
            wk.KAITO_MACHINE_TYPE_LABEL: "tpu",
        }
        if slice_id:
            out[wk.TPU_SLICE_ID_LABEL] = slice_id
        if zone:
            out[wk.ZONE_LABEL] = zone
        if capacity_tier:
            out[wk.TPU_CAPACITY_TIER_LABEL] = capacity_tier
        return out

    def per_host_capacity(self) -> dict[str, str]:
        """Extended-resource capacity one host registers (device plugin view)."""
        cpu, mem = _HOST_RESOURCES.get(self.machine_type, (96, 448))
        return {
            wk.TPU_RESOURCE_NAME: str(self.chips_per_host),
            "cpu": str(cpu),
            "memory": f"{mem}Gi",
        }


# (vCPU, memory GiB) per GKE TPU machine type — plausible published values.
_HOST_RESOURCES = {
    "ct5lp-hightpu-1t": (24, 48),
    "ct5lp-hightpu-4t": (112, 192),
    "ct5lp-hightpu-8t": (224, 400),
    "ct5p-hightpu-4t": (208, 448),
    "ct4p-hightpu-4t": (240, 407),
    "ct6e-standard-1t": (44, 176),
    "ct6e-standard-4t": (180, 720),
    "ct6e-standard-8t": (180, 1440),
}


def _v5e_like(gen: str, gke_acc: str, machine_prefix: str,
              cores_per_chip: int) -> list[SliceShape]:
    """v5e/v6e family: 2D ICI; 1/4/8-chip hosts; ≥16 chips → 8-chip hosts."""
    shapes = []
    single = [("1x1", 1, 1), ("2x2", 4, 4), ("2x4", 8, 8)]
    multi = [("4x4", 16), ("4x8", 32), ("8x8", 64), ("8x16", 128), ("16x16", 256)]
    for topo, chips, cph in single:
        shapes.append(SliceShape(
            name=f"tpu-{gen}-{chips}", generation=gen, slice_name=f"{gen}-{chips}",
            topology=topo, chips=chips, hosts=1, chips_per_host=cph,
            machine_type=f"{machine_prefix}-{cph}t", gke_accelerator=gke_acc,
            cores_per_chip=cores_per_chip,
            aliases=(f"v5litepod-{chips}",) if gen == "v5e" else (),
        ))
    for topo, chips in multi:
        shapes.append(SliceShape(
            name=f"tpu-{gen}-{chips}", generation=gen, slice_name=f"{gen}-{chips}",
            topology=topo, chips=chips, hosts=chips // 8, chips_per_host=8,
            machine_type=f"{machine_prefix}-8t", gke_accelerator=gke_acc,
            cores_per_chip=cores_per_chip,
            aliases=(f"v5litepod-{chips}",) if gen == "v5e" else (),
        ))
    return shapes


def _v4_like(gen: str, gke_acc: str, machine_type: str) -> list[SliceShape]:
    """v4/v5p family: 3D ICI torus; 4-chip hosts; names count TensorCores."""
    topos = ["2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8",
             "4x8x8", "8x8x8", "8x8x16"]
    shapes = []
    for topo in topos:
        chips = math.prod(int(d) for d in topo.split("x"))
        cores = chips * 2
        shapes.append(SliceShape(
            name=f"tpu-{gen}-{cores}", generation=gen, slice_name=f"{gen}-{cores}",
            topology=topo, chips=chips, hosts=max(1, chips // 4), chips_per_host=min(4, chips),
            machine_type=machine_type, gke_accelerator=gke_acc,
        ))
    return shapes


CATALOG: list[SliceShape] = (
    _v5e_like("v5e", "tpu-v5-lite-podslice", "ct5lp-hightpu", 1)
    + _v5e_like("v6e", "tpu-v6e-slice", "ct6e-standard", 1)
    + _v4_like("v5p", "tpu-v5p-slice", "ct5p-hightpu-4t")
    + _v4_like("v4", "tpu-v4-podslice", "ct4p-hightpu-4t")
)

_BY_NAME: dict[str, SliceShape] = {}
for _s in CATALOG:
    for key in (_s.name, _s.slice_name, *_s.aliases):
        _BY_NAME.setdefault(key.lower(), _s)
    # topology-qualified alias, e.g. "v5p/2x2x4"
    _BY_NAME.setdefault(f"{_s.generation}/{_s.topology}".lower(), _s)


def lookup(name: str) -> Optional[SliceShape]:
    return _BY_NAME.get(name.strip().lower())


def smallest_fitting(generation: Optional[str], min_chips: int) -> Optional[SliceShape]:
    candidates = [s for s in CATALOG
                  if (generation is None or s.generation == generation)
                  and s.chips >= min_chips]
    return min(candidates, key=lambda s: (s.chips, s.hosts), default=None)


def resolve(reqs: Requirements, resources: Optional[dict[str, str]] = None) -> SliceShape:
    """Resolve NodeClaim requirements (+ resource requests) to a slice shape.

    Resolution order (first hit wins), mirroring-then-extending the
    reference's "first value of the instance-type requirement" rule
    (instance.go:90-95):

    1. ``node.kubernetes.io/instance-type`` values, in order.
    2. ``tpu.kaito.sh/accelerator`` (+ optional ``tpu.kaito.sh/topology``).
    3. ``google.com/tpu`` resource request → smallest fitting shape.
    """
    itype_vals = reqs.get(wk.INSTANCE_TYPE_LABEL).values()
    for v in itype_vals:
        s = lookup(v)
        if s is not None:
            return s
    if itype_vals:
        raise UnknownShapeError(
            f"instance-type values {itype_vals} match no TPU shape "
            f"(known shapes look like 'tpu-v5e-8', 'v5p-32', 'v5litepod-8')")

    gen_req = reqs.get(wk.TPU_ACCELERATOR_LABEL)
    gens = [g.lower() for g in gen_req.values()]
    topo_vals = reqs.get(wk.TPU_TOPOLOGY_LABEL).values()
    if gens and topo_vals:
        for g in gens:
            for t in topo_vals:
                s = lookup(f"{g}/{t}")
                if s is not None:
                    return s
        raise UnknownShapeError(f"no shape for accelerator {gens} topology {topo_vals}")
    chips_req = reqs.get(wk.TPU_CHIPS_LABEL).values()
    if gens and chips_req:
        s = smallest_fitting(gens[0], int(chips_req[0]))
        if s is not None:
            return s
        raise UnknownShapeError(f"no {gens[0]} shape with >= {chips_req[0]} chips")

    want = int(float((resources or {}).get(wk.TPU_RESOURCE_NAME, 0)))
    if want > 0:
        s = smallest_fitting(gens[0] if gens else None, want)
        if s is not None:
            return s
        raise UnknownShapeError(f"no shape with >= {want} chips")

    if gens:
        s = smallest_fitting(gens[0], 1)
        if s is not None:
            return s

    raise UnknownShapeError(
        "requirements carry no resolvable instance-type, accelerator/topology, "
        f"or google.com/tpu request (keys: {reqs.keys()})")


def resolve_all(reqs: Requirements,
                resources: Optional[dict[str, str]] = None) -> list[SliceShape]:
    """Preference-ordered shape candidates for the placement fallback walk.

    The first element is always exactly what :func:`resolve` returns (so the
    happy path is unchanged); later elements are progressively-less-preferred
    shapes that still satisfy the requirements — the order the placement
    engine tries when a zone/generation is stocked out. Raises
    :class:`UnknownShapeError` exactly when :func:`resolve` would.
    """
    out: list[SliceShape] = []
    seen: set[str] = set()

    def _add(s: Optional[SliceShape]) -> None:
        if s is not None and s.name not in seen:
            seen.add(s.name)
            out.append(s)

    itype_vals = reqs.get(wk.INSTANCE_TYPE_LABEL).values()
    if itype_vals:
        for v in itype_vals:
            _add(lookup(v))
        if not out:
            raise UnknownShapeError(
                f"instance-type values {itype_vals} match no TPU shape "
                f"(known shapes look like 'tpu-v5e-8', 'v5p-32', 'v5litepod-8')")
        return out

    gen_req = reqs.get(wk.TPU_ACCELERATOR_LABEL)
    gens = [g.lower() for g in gen_req.values()]
    topo_vals = reqs.get(wk.TPU_TOPOLOGY_LABEL).values()
    if gens and topo_vals:
        for g in gens:
            for t in topo_vals:
                _add(lookup(f"{g}/{t}"))
        if not out:
            raise UnknownShapeError(
                f"no shape for accelerator {gens} topology {topo_vals}")
        return out
    chips_req = reqs.get(wk.TPU_CHIPS_LABEL).values()
    if gens and chips_req:
        for g in gens:
            _add(smallest_fitting(g, int(chips_req[0])))
        if not out:
            raise UnknownShapeError(f"no {gens[0]} shape with >= {chips_req[0]} chips")
        return out

    want = int(float((resources or {}).get(wk.TPU_RESOURCE_NAME, 0)))
    if want > 0:
        if gens:
            for g in gens:
                _add(smallest_fitting(g, want))
        else:
            _add(smallest_fitting(None, want))
        if not out:
            raise UnknownShapeError(f"no shape with >= {want} chips")
        return out

    if gens:
        for g in gens:
            _add(smallest_fitting(g, 1))
        if out:
            return out

    raise UnknownShapeError(
        "requirements carry no resolvable instance-type, accelerator/topology, "
        f"or google.com/tpu request (keys: {reqs.keys()})")
