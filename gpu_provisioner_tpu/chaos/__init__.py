"""Chaos-injection subsystem: seeded fault policies for the simulated cloud
and kube client, the named profiles the soak suite runs under, and the
crash-point schedule the crash-restart recovery suite drives."""

from .client import ChaosClient, ChaosClientError, transient_kube
from .crash import CRASH_POINTS, CrashPoints, SimulatedCrash
from .policy import (
    ChaosPolicy, FaultRule, PROFILES, profile, stockout, transient,
)

__all__ = [
    "CRASH_POINTS", "ChaosClient", "ChaosClientError", "ChaosPolicy",
    "CrashPoints", "FaultRule", "PROFILES", "SimulatedCrash", "profile",
    "stockout", "transient", "transient_kube",
]
