"""Chaos-injection subsystem: seeded fault policies for the simulated cloud
and kube client, the named profiles the soak suite runs under, the
crash-point schedule the crash-restart recovery suite drives, and the
node-fault injector that makes Nodes themselves sick (flapping Ready,
degraded accelerators, silent kubelet death, maintenance waves)."""

from .apifaults import (
    API_PROFILES, ApiFaultClient, ApiFaultInjector, api_fault_profile,
)
from .client import ChaosClient, ChaosClientError, transient_kube
from .crash import CRASH_POINTS, CrashPoints, SimulatedCrash
from .nodefaults import (
    ACCELERATOR_HEALTHY, FAULT_KINDS, MAINTENANCE_SCHEDULED,
    NODE_FAULT_PROFILES, NodeFault, NodeFaultInjector, SPOT_PREEMPTED,
    node_fault_profile,
)
from .policy import (
    ChaosPolicy, FaultRule, PROFILES, ZoneWindow, profile, stockout,
    transient,
)

__all__ = [
    "ACCELERATOR_HEALTHY", "API_PROFILES", "ApiFaultClient",
    "ApiFaultInjector", "CRASH_POINTS", "ChaosClient", "ChaosClientError",
    "ChaosPolicy", "CrashPoints", "FAULT_KINDS", "FaultRule",
    "MAINTENANCE_SCHEDULED", "NODE_FAULT_PROFILES", "NodeFault",
    "NodeFaultInjector", "PROFILES", "SPOT_PREEMPTED", "SimulatedCrash",
    "ZoneWindow", "api_fault_profile", "node_fault_profile", "profile",
    "stockout", "transient", "transient_kube",
]
