"""Chaos-injection subsystem: seeded fault policies for the simulated cloud
and kube client, plus the named profiles the soak suite runs under."""

from .client import ChaosClient, ChaosClientError, transient_kube
from .policy import (
    ChaosPolicy, FaultRule, PROFILES, profile, stockout, transient,
)

__all__ = [
    "ChaosClient", "ChaosClientError", "ChaosPolicy", "FaultRule",
    "PROFILES", "profile", "stockout", "transient", "transient_kube",
]
