"""Seeded kube-APISERVER fault injection: brownouts, partitions, watch gaps.

Every chaos profile before PR 16 attacked the cloud side (policy.py) or the
nodes (nodefaults.py); the apiserver — the one dependency every reconcile
rides — had no fault model. This module closes that: a seeded
:class:`ApiFaultInjector` describes fault windows on the kube client's
verbs and watch streams, and :class:`ApiFaultClient` wires them into the
envtest client chain (below the informer, so relists and watches feel the
faults exactly like a real reflector would).

Fault vocabulary:

- **brownout** — latency inflation plus seeded 429-with-Retry-After and
  503 bursts on every verb during a window.
- **partition** — a total kube-API outage window: every verb raises, the
  watch stream goes silent (events land in the store but never reach the
  consumer — exactly what a dead HTTP stream does).
- **watch gap** — watch events silently dropped during a window, then a
  410 Gone / expired-resourceVersion answer at the window's end: the
  classic compacted-history hole only a relist-and-diff can heal.
- **catchup storm** — a partition whose heal expires EVERY watch (410
  regardless of drops) into a full-fleet relist, with throttling pressure
  during the catch-up.

Determinism matches policy.py: draws hash (seed, decision-key), windows
anchor at the injector's FIRST consult (the ZoneWindow idiom), so a given
(profile, seed) replays bit-identically regardless of wall clock.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Callable, Optional

from ..runtime.client import (
    ClientError, ResourceExpiredError, TooManyRequestsError,
)


class ApiFaultInjector:
    """Seeded schedule of apiserver fault windows.

    All times are seconds relative to the injector's first consult (loop
    clock). ``brownout_duration=None`` with nonzero rates means the
    brownout never ends; ``partition_start=None`` means no partition.
    Observability mirrors ChaosPolicy: ``calls``/``injected`` per-site
    counters plus ``dropped`` per-kind watch-event counts.
    """

    def __init__(self, seed: int = 0, *,
                 latency: float = 0.0,
                 throttle_rate: float = 0.0,
                 error_rate: float = 0.0,
                 retry_after: float = 0.05,
                 brownout_start: float = 0.0,
                 brownout_duration: Optional[float] = None,
                 partition_start: Optional[float] = None,
                 partition_duration: float = 0.0,
                 gap_start: Optional[float] = None,
                 gap_duration: float = 0.0,
                 heal_410: bool = False):
        self.seed = seed
        self.latency = latency
        self.throttle_rate = throttle_rate
        self.error_rate = error_rate
        self.retry_after = retry_after
        self.brownout_start = brownout_start
        self.brownout_duration = brownout_duration
        self.partition_start = partition_start
        self.partition_duration = partition_duration
        self.gap_start = gap_start
        self.gap_duration = gap_duration
        self.heal_410 = heal_410
        self._anchor: Optional[float] = None
        self.calls: dict[str, int] = {}
        self.injected: dict[str, int] = {}
        self.dropped: dict[str, int] = {}

    # -- clock / determinism ----------------------------------------------

    def _elapsed(self) -> float:
        now = asyncio.get_event_loop().time()
        if self._anchor is None:
            self._anchor = now
        return now - self._anchor

    def _draw(self, *key) -> float:
        h = hashlib.sha256(repr((self.seed,) + key).encode()).digest()
        return int.from_bytes(h[:8], "big") / 2 ** 64

    @staticmethod
    def _in(start: Optional[float], duration: Optional[float],
            el: float) -> bool:
        if start is None:
            return False
        if duration is None:
            return el >= start
        return start <= el < start + duration

    # -- window queries ----------------------------------------------------

    def partition_active(self) -> bool:
        return self._in(self.partition_start, self.partition_duration,
                        self._elapsed())

    def brownout_active(self) -> bool:
        if not (self.latency or self.throttle_rate or self.error_rate):
            return False
        return self._in(self.brownout_start, self.brownout_duration,
                        self._elapsed())

    def gap_active(self) -> bool:
        """True while the watch stream is losing events: an explicit gap
        window, or a partition (a dead stream drops everything)."""
        el = self._elapsed()
        return (self._in(self.gap_start, self.gap_duration, el)
                or self._in(self.partition_start, self.partition_duration,
                            el))

    def affects_watch(self) -> bool:
        return self.gap_start is not None or self.partition_start is not None

    def _count(self, table: dict[str, int], key: str) -> None:
        table[key] = table.get(key, 0) + 1

    # -- verb path ---------------------------------------------------------

    async def before_verb(self, verb: str) -> None:
        """Consulted by :class:`ApiFaultClient` before delegating a verb.
        Raises the injected fault, or returns after any injected latency."""
        self._count(self.calls, verb)
        n = self.calls[verb]
        if self.partition_active():
            self._count(self.injected, f"partition:{verb}")
            raise ClientError(
                f"{verb}: apiserver unreachable (injected partition)")
        if not self.brownout_active():
            return
        if self.latency:
            await asyncio.sleep(
                self.latency * (0.5 + self._draw("latency", verb, n)))
        if (self.throttle_rate
                and self._draw("throttle", verb, n) < self.throttle_rate):
            self._count(self.injected, f"throttle:{verb}")
            raise TooManyRequestsError(
                f"{verb}: HTTP 429 (injected brownout throttle)",
                retry_after=self.retry_after)
        if (self.error_rate
                and self._draw("error", verb, n) < self.error_rate):
            self._count(self.injected, f"error:{verb}")
            raise ClientError(f"{verb}: HTTP 503 (injected brownout)")


class _FaultWatch:
    """Watch wrapper that silently drops events during a gap/partition
    window, then answers 410 Gone once the window closes — the compacted
    watch-history hole the informer's gap resync exists to heal. The 410
    fires even on a quiet stream (bounded poll while windows are armed), so
    the heal never waits for a fresh event that may not come."""

    _POLL = 0.02

    def __init__(self, inner, faults: ApiFaultInjector, kind: str):
        self._inner = inner
        self._f = faults
        self._kind = kind
        self._saw_gap = False
        self._dropped = 0

    def __aiter__(self):
        return self

    def _heal_check(self) -> None:
        """Raise ResourceExpiredError exactly once per closed gap window
        that lost events (always, under heal_410 — the catchup storm)."""
        if self._f.gap_active():
            self._saw_gap = True
            return
        if not self._saw_gap:
            return
        self._saw_gap = False
        dropped, self._dropped = self._dropped, 0
        if dropped or self._f.heal_410:
            raise ResourceExpiredError(
                f"{self._kind} watch: HTTP 410 Gone — resourceVersion "
                f"expired ({dropped} events compacted during injected gap)")

    def _drop(self, ev) -> None:
        del ev
        self._saw_gap = True
        self._dropped += 1
        self._f._count(self._f.dropped, self._kind)

    async def __anext__(self):
        if not self._f.affects_watch():
            return await self._inner.__anext__()
        while True:
            self._heal_check()
            gapped = self._f.gap_active()
            try:
                ev = await asyncio.wait_for(self._inner.__anext__(),
                                            self._POLL)
            except asyncio.TimeoutError:
                continue
            # an event that raced the window edge is judged by the LATER of
            # the two looks — losing one extra event to the gap is exactly
            # the ambiguity a real stream teardown has
            if gapped or self._f.gap_active():
                self._drop(ev)
                continue
            return ev

    def try_next(self):
        if not self._f.affects_watch():
            return self._inner.try_next()
        self._heal_check()
        while True:
            ev = self._inner.try_next()
            if ev is None:
                return None
            if self._f.gap_active():
                self._drop(ev)
                continue
            return ev

    def close(self) -> None:
        self._inner.close()


class ApiFaultClient:
    """Delegating kube-client wrapper driven by an :class:`ApiFaultInjector`.

    Sits below the informer in the envtest chain (raw → ChaosClient →
    **ApiFaultClient** → GovernedClient → CachedListClient) so informer
    relists, controller reads and status writes all feel the same weather —
    and watch streams degrade exactly like real reflector connections."""

    def __init__(self, inner, faults: ApiFaultInjector):
        self.inner = inner
        self.faults = faults

    @property
    def store(self):
        return self.inner.store

    async def get(self, cls, name, namespace=""):
        await self.faults.before_verb("get")
        return await self.inner.get(cls, name, namespace)

    async def list(self, cls, labels=None, namespace=None, index=None):
        await self.faults.before_verb("list")
        return await self.inner.list(cls, labels, namespace, index)

    async def create(self, obj):
        await self.faults.before_verb("create")
        return await self.inner.create(obj)

    async def update(self, obj):
        await self.faults.before_verb("update")
        return await self.inner.update(obj)

    async def update_status(self, obj):
        await self.faults.before_verb("update_status")
        return await self.inner.update_status(obj)

    async def delete(self, cls, name, namespace=""):
        await self.faults.before_verb("delete")
        return await self.inner.delete(cls, name, namespace)

    async def evict(self, name, namespace="", uid=""):
        await self.faults.before_verb("evict")
        return await self.inner.evict(name, namespace, uid=uid)

    def watch(self, cls):
        return _FaultWatch(self.inner.watch(cls), self.faults,
                           getattr(cls, "KIND", cls.__name__))

    def add_index(self, cls, name, key_fn):
        if hasattr(self.inner, "add_index"):
            self.inner.add_index(cls, name, key_fn)


# ---------------------------------------------------------------------------
# Named profiles (the policy.py PROFILES idiom): soaks select by name +
# seed; keyword overrides let a soak stretch a window (the 30s partition)
# without forking the profile.

API_PROFILES: dict[str, Callable[..., ApiFaultInjector]] = {}


def _register(name: str):
    def deco(fn):
        API_PROFILES[name] = fn
        return fn
    return deco


def api_fault_profile(name: str, seed: int = 0, **overrides) -> ApiFaultInjector:
    """Build a named apiserver-fault profile with ``seed``. Unknown names
    raise with the known-profile list (mirrors chaos.profile)."""
    try:
        build = API_PROFILES[name]
    except KeyError:
        raise ValueError(
            f"unknown API fault profile {name!r}; known: "
            f"{sorted(API_PROFILES)}") from None
    return build(seed, **overrides)


@_register("apiserver_brownout")
def _apiserver_brownout(seed: int, **kw) -> ApiFaultInjector:
    """Latency inflation + 429/503 bursts with Retry-After: the apiserver
    is up but drowning. Drives HEALTHY→BROWNOUT and the AIMD backoff."""
    kw.setdefault("latency", 0.005)
    kw.setdefault("throttle_rate", 0.2)
    kw.setdefault("error_rate", 0.1)
    kw.setdefault("retry_after", 0.05)
    kw.setdefault("brownout_start", 0.1)
    kw.setdefault("brownout_duration", 2.0)
    return ApiFaultInjector(seed, **kw)


@_register("apiserver_partition")
def _apiserver_partition(seed: int, **kw) -> ApiFaultInjector:
    """Total kube-API outage window: every verb fails, the watch stream
    drops everything, and the heal answers 410 — partition-fencing plus
    gap resync must carry the fleet through."""
    kw.setdefault("partition_start", 0.3)
    kw.setdefault("partition_duration", 1.0)
    return ApiFaultInjector(seed, **kw)


@_register("watch_gap")
def _watch_gap(seed: int, **kw) -> ApiFaultInjector:
    """Silently dropped watch events, then a 410 Gone answer: verbs stay
    healthy, only the stream lies. The informer's diff-based resync must
    synthesize the missed events."""
    kw.setdefault("gap_start", 0.1)
    kw.setdefault("gap_duration", 0.5)
    return ApiFaultInjector(seed, **kw)


@_register("catchup_storm")
def _catchup_storm(seed: int, **kw) -> ApiFaultInjector:
    """Partition heal into a full-fleet relist: every watch expires at the
    heal (410 regardless of drops) while the recovering apiserver still
    throttles — the storm the CATCHUP mode and status-shedding absorb."""
    kw.setdefault("partition_start", 0.3)
    kw.setdefault("partition_duration", 0.8)
    kw.setdefault("heal_410", True)
    kw.setdefault("throttle_rate", 0.15)
    kw.setdefault("retry_after", 0.05)
    kw.setdefault("brownout_start", 1.1)
    kw.setdefault("brownout_duration", 1.5)
    return ApiFaultInjector(seed, **kw)
