"""Chaos wrapper over the kube ``Client`` seam.

Injects the policy's ``kube.*`` rules in front of any Client implementation
(latency on reads, transient ``ClientError`` on writes, …) so controllers can
be soaked against a flaky apiserver, not just a flaky cloud. Watches pass
through untouched: the in-memory watch path has no real failure mode to
simulate and dropping events would test the store, not the controllers.
"""

from __future__ import annotations

from ..runtime.client import Client, ClientError


class ChaosClientError(ClientError):
    """Injected apiserver failure (reconcilers treat it like any transient
    client error: the workqueue's backoff owns the retry)."""


def transient_kube(message: str = "chaos: apiserver unavailable"):
    """Error factory for ``FaultRule(error=...)`` on ``kube.*`` sites."""
    return lambda: ChaosClientError(message)


class ChaosClient:
    """Delegating Client that runs ``policy.before_call("kube", <method>)``
    ahead of every API method."""

    def __init__(self, inner: Client, policy):
        self.inner = inner
        self.policy = policy
        # controllers reach for .store (index registration) on the raw client
        self.store = getattr(inner, "store", None)

    async def get(self, cls, name, namespace=""):
        await self.policy.before_call("kube", "get")
        return await self.inner.get(cls, name, namespace)

    async def list(self, cls, labels=None, namespace=None, index=None):
        await self.policy.before_call("kube", "list")
        return await self.inner.list(cls, labels=labels, namespace=namespace,
                                     index=index)

    async def create(self, obj):
        await self.policy.before_call("kube", "create")
        return await self.inner.create(obj)

    async def update(self, obj):
        await self.policy.before_call("kube", "update")
        return await self.inner.update(obj)

    async def update_status(self, obj):
        await self.policy.before_call("kube", "update_status")
        return await self.inner.update_status(obj)

    async def delete(self, cls, name, namespace=""):
        await self.policy.before_call("kube", "delete")
        return await self.inner.delete(cls, name, namespace)

    async def evict(self, name, namespace="", uid=""):
        await self.policy.before_call("kube", "evict")
        return await self.inner.evict(name, namespace, uid)

    def watch(self, cls):
        return self.inner.watch(cls)
