"""Crash-point injection: deterministic process-death simulation.

The reference provisioner survives process death by construction — cloud
creates are idempotent and a restarted replica re-drives every NodeClaim
from the API server (SURVEY §1 L4/L5) — but nothing in its test suite ever
*kills* it mid-operation. This module names the cut lines where an operator
death strands the most interesting state and gives the envtest restart
harness (``envtest.RestartableEnv``) a deterministic way to die there.

``SimulatedCrash`` derives from ``BaseException`` (like KeyboardInterrupt)
on purpose: every resilience layer in the operator catches ``Exception`` —
workqueue error backoff, GC's keep-ticking guard, the lifecycle
sub-reconciler aggregation — and a simulated process death must not be
absorbed as one more retryable error. It rips through to the task boundary;
the harness observes ``CrashPoints.crashed`` and tears the incarnation down
the way the kernel would: tasks cancelled, in-memory state gone, cloud and
kube state persisting.

Determinism follows the ``ChaosPolicy`` convention: probabilistic arming
draws are a pure hash of ``(seed, point, key, nth hit)``, so a crash
schedule reproduces for a fixed seed regardless of reconcile interleaving.
"""

from __future__ import annotations

import asyncio
import hashlib
from collections import defaultdict
from typing import Optional, Union

# The named cut lines, each chosen for the state it strands (see
# docs/FAILURE_MODES.md "Crash & restart taxonomy"):
CRASH_POINTS = (
    # queued resource created in the cloud, nothing recorded on the claim
    "after_qr_create",
    # create LRO issued, never polled — pool stranded PROVISIONING
    "after_pool_begin_create",
    # create LRO completed server-side, result never observed/recorded
    "before_lro_done",
    # delete LRO issued (queued resource already cleaned up), never polled
    "mid_delete_after_pool_delete",
    # node tainted, evictions queued in-memory, drain unfinished
    "mid_drain",
    # repair committed: node cordoned, budget token consumed (in-memory),
    # evictions queued — the NodeClaim force-delete not yet issued
    "mid_repair",
)


class SimulatedCrash(BaseException):
    """Injected process death. BaseException so no retry/backoff layer can
    absorb it — it must reach the task boundary like a real crash."""


class CrashPoints:
    """An armable crash schedule shared across operator incarnations.

    ``at`` arms one point (fire on the next eligible hit) or a mapping of
    ``{point: times}``; ``after`` skips the first N hits of each armed point
    so a test can crash on the Nth create rather than the first. ``rate``
    below 1.0 makes each eligible hit a seeded keyed-hash draw (the
    ``ChaosPolicy`` trick: independent of scheduling order).

    Budgets persist across incarnations: hand the same object to the
    restarted operator and an exhausted point stays quiet, which is exactly
    the crash-once-then-recover shape the soak matrix drives.
    """

    def __init__(self, at: Union[str, dict, None] = None, times: int = 1,
                 after: int = 0, rate: float = 1.0, seed: int = 0):
        self._budget: dict[str, int] = {}
        self._after: dict[str, int] = {}
        self.rate = rate
        self.seed = seed
        # observability for harness/soak assertions
        self.hits: dict[str, int] = defaultdict(int)
        self.fired: dict[str, int] = defaultdict(int)
        self.last: Optional[tuple[str, str]] = None
        self.crashed = asyncio.Event()
        if at is not None:
            if isinstance(at, str):
                self.arm(at, times=times, after=after)
            else:
                for point, n in dict(at).items():
                    self.arm(point, times=n, after=after)

    def arm(self, point: str, times: int = 1, after: int = 0) -> "CrashPoints":
        """(Re-)arm ``point`` to fire ``times`` more times, skipping its next
        ``after`` hits. Chainable; callable mid-test between incarnations."""
        if point not in CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {point!r}; known: {CRASH_POINTS}")
        self._budget[point] = self._budget.get(point, 0) + times
        self._after[point] = self.hits[point] + after
        return self

    def _draw(self, *key) -> float:
        h = hashlib.sha256(repr((self.seed,) + key).encode()).digest()
        return int.from_bytes(h[:8], "big") / 2 ** 64

    def hit(self, point: str, key: str = "") -> None:
        """Instrumented code marks a cut line; raises ``SimulatedCrash`` when
        the point is armed. A no-op for unarmed points (production passes no
        ``CrashPoints`` at all, so the seam costs one None check)."""
        if point not in CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {point!r}; known: {CRASH_POINTS}")
        n = self.hits[point]
        self.hits[point] = n + 1
        if self._budget.get(point, 0) <= 0 or n < self._after.get(point, 0):
            return
        if self.rate < 1.0 and self._draw(point, key, n) >= self.rate:
            return
        self._budget[point] -= 1
        self.fired[point] += 1
        self.last = (point, key)
        self.crashed.set()
        raise SimulatedCrash(f"simulated crash at {point} ({key})")

    def disarm(self, point: Optional[str] = None) -> "CrashPoints":
        """Zero the budget of ``point`` (or all points): the next incarnation
        runs clean. Hit/fired counters are preserved for assertions."""
        if point is None:
            self._budget.clear()
        else:
            self._budget.pop(point, None)
        return self

    def fired_total(self) -> int:
        return sum(self.fired.values())
