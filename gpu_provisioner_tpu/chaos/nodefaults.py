"""Node-fault injection: the third chaos leg — sick *nodes*, not sick APIs.

PR 1 faults the cloud API (``ChaosPolicy``) and PR 3 kills the operator
(``CrashPoints``); nothing could produce an unhealthy Node, which for
multi-host TPU slices is the dominant real failure (one bad host breaks the
ICI ring and strands the whole slice). ``NodeFaultInjector`` plays the
kubelet fleet: a seeded background task that drives Node *state* over
envtest time — ``Ready`` flapping, accelerator degradation, silent kubelet
death (heartbeats stop while ``Ready`` stays stale-True), and scheduled
maintenance notices — through the ``fake.builders`` condition helpers, so
every fault writes conditions exactly the way a kubelet would.

Determinism follows the ``ChaosPolicy`` convention: whether a node is a
fault's victim is a pure hash of ``(seed, kind, node name)``, independent of
scheduling order; fault *timing* is anchored per node NAME at the moment the
injector first observes it (monotonic), and the clock survives repair
replacements under the same name — a finite-duration fault's window closes
in wall time no matter how many replacements appear inside it (a
replacement created inside the window is re-faulted, one created after it
stays clean), which is what lets the repair soaks converge.

The injector doubles as the heartbeat source: real clusters have a
node-lifecycle-controller marking silent nodes ``Unknown``; envtest doesn't,
so repair's stale-heartbeat policy (controllers/health.py) needs live nodes
to actually *have* fresh heartbeats. Every tick stamps
``Ready.lastHeartbeatTime`` on managed nodes except silent-death victims.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from collections import defaultdict
from dataclasses import dataclass, field, replace
from fnmatch import fnmatch
from typing import Callable, Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..fake.builders import heartbeat_node, set_node_condition, set_node_ready

log = logging.getLogger("chaos.nodefaults")

# Condition types the repair policies key off (cloudprovider/tpu.py).
ACCELERATOR_HEALTHY = "AcceleratorHealthy"
MAINTENANCE_SCHEDULED = "MaintenanceScheduled"
# Stamped by the fake cloud's spot-reclaim sweep (not this injector — the
# preemption notice comes from the cloud, not a sick kubelet); repair treats
# it as a short-toleration replace-now fault and the placement engine counts
# it into the spot-zone demotion hysteresis.
SPOT_PREEMPTED = "SpotPreempted"

FAULT_KINDS = ("flap", "degrade", "silent", "maintenance")


@dataclass
class NodeFault:
    """One node-state fault, matched by ``fnmatch`` against node names.

    ``rate`` is the seeded per-node probability that a matched node is a
    victim (1.0 = every match). The fault is active from ``start`` to
    ``start + duration`` seconds after the injector FIRST OBSERVES the node's
    name (the clock is shared by same-named repair replacements, so a finite
    window closes in wall time); outside the window the injector heals what
    it broke.

    Kinds:

    - ``flap``         Ready oscillates True/False every ``period`` seconds,
                       resetting lastTransitionTime on each flip — each
                       individual False interval is shorter than any sane
                       toleration, which is exactly the repair-defeating
                       shape the hysteresis window exists for.
    - ``degrade``      ``AcceleratorHealthy=False`` (device-plugin-reported
                       accelerator fault), stable for the window.
    - ``silent``       heartbeats stop; ``Ready`` stays a stale ``True`` —
                       no watch event will ever announce this death.
    - ``maintenance``  ``MaintenanceScheduled=True`` notice for the window.
    """

    kind: str
    match: str = "*"
    rate: float = 1.0
    start: float = 0.0
    period: float = 0.5
    duration: float = float("inf")

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown node fault kind {self.kind!r}; known: {FAULT_KINDS}")


class NodeFaultInjector:
    """Seeded kubelet-fleet simulator driving Node conditions over time.

    ``start(client)`` binds a kube client (the RAW envtest client — faults
    are the world's doing and must not themselves be subject to kube chaos)
    and launches the tick loop; idempotent, so a ``RestartableEnv`` can
    re-enter it across operator incarnations without resetting per-node
    fault clocks. ``injected`` counts what actually fired, keyed
    ``kind:node``, for soak assertions ("the profile injected nothing" is a
    test bug, not a pass).
    """

    def __init__(self, seed: int = 0, faults: Optional[list[NodeFault]] = None,
                 tick: float = 0.05, heartbeat: bool = True):
        self.seed = seed
        self.faults = list(faults or [])
        self.tick = tick
        self.heartbeat = heartbeat
        self.client = None
        self.injected: dict[str, int] = defaultdict(int)
        # node name -> monotonic time first observed (the per-node fault clock)
        self._first_seen: dict[str, float] = {}
        # (fault idx, node) -> last state applied, for edge-triggered writes
        self._applied: dict[tuple[int, str], object] = {}
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------- seeding
    def _draw(self, *key) -> float:
        h = hashlib.sha256(repr((self.seed,) + key).encode()).digest()
        return int.from_bytes(h[:8], "big") / 2 ** 64

    def _victim(self, fault: NodeFault, i: int, name: str) -> bool:
        if not fnmatch(name, fault.match):
            return False
        return fault.rate >= 1.0 or self._draw(fault.kind, i, name) < fault.rate

    # ------------------------------------------------------------ lifecycle
    def start(self, client) -> None:
        self.client = client
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._run(),
                                             name="node-fault-injector")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the world keeps turning
                log.warning("node-fault tick failed: %s", e)
            await asyncio.sleep(self.tick)

    # ------------------------------------------------------------- the tick
    async def step(self) -> None:
        """One injection pass over the managed fleet (public so tests can
        drive injection synchronously without the background task)."""
        nodes = await self.client.list(
            Node, labels={wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME})
        mono = asyncio.get_event_loop().time()
        for node in nodes:
            name = node.metadata.name
            first = self._first_seen.setdefault(name, mono)
            elapsed = mono - first
            changed = False
            silent = False
            for i, fault in enumerate(self.faults):
                if not self._victim(fault, i, name):
                    continue
                active = fault.start <= elapsed < fault.start + fault.duration
                changed |= self._apply(fault, i, node, active, elapsed)
                if fault.kind == "silent" and active:
                    silent = True
            if self.heartbeat and not silent:
                changed |= heartbeat_node(node)
            if changed:
                try:
                    await self.client.update_status(node)
                except Exception:  # noqa: BLE001 — conflict/NotFound: next
                    pass           # tick re-reads and re-applies

    def _apply(self, fault: NodeFault, i: int, node: Node, active: bool,
               elapsed: float) -> bool:
        key = (i, node.metadata.name)
        if fault.kind == "flap":
            # half-period square wave while active; heal to Ready outside
            want_ready = True
            if active:
                want_ready = int((elapsed - fault.start) / fault.period) % 2 == 0
            if self._applied.get(key) == want_ready:
                return False
            self._applied[key] = want_ready
            flipped = set_node_ready(
                node, want_ready,
                reason="KubeletReady" if want_ready else "ChaosFlap")
            if flipped and not want_ready:
                self.injected[f"flap:{node.metadata.name}"] += 1
            return flipped
        if fault.kind == "degrade":
            if active:
                if set_node_condition(node, ACCELERATOR_HEALTHY, "False",
                                      reason="ChaosDegraded"):
                    self.injected[f"degrade:{node.metadata.name}"] += 1
                    return True
                return False
            # heal only what we broke — a fresh replacement node without the
            # condition stays untouched
            cond = next((c for c in node.status.conditions
                         if c.type == ACCELERATOR_HEALTHY), None)
            if cond is not None and cond.status == "False":
                return set_node_condition(node, ACCELERATOR_HEALTHY, "True",
                                          reason="ChaosHealed")
            return False
        if fault.kind == "maintenance":
            if active:
                if set_node_condition(node, MAINTENANCE_SCHEDULED, "True",
                                      reason="ScheduledMaintenance"):
                    self.injected[f"maintenance:{node.metadata.name}"] += 1
                    return True
                return False
            cond = next((c for c in node.status.conditions
                         if c.type == MAINTENANCE_SCHEDULED), None)
            if cond is not None and cond.status == "True":
                return set_node_condition(node, MAINTENANCE_SCHEDULED, "False",
                                          reason="MaintenanceDone")
            return False
        if fault.kind == "silent":
            # the whole point is writing NOTHING: the heartbeat skip happens
            # in step(); count the window entry once for observability
            if active and self._applied.get(key) is not True:
                self._applied[key] = True
                self.injected[f"silent:{node.metadata.name}"] += 1
            elif not active:
                self._applied[key] = False
            return False
        return False

    def injected_total(self, prefix: str = "") -> int:
        return sum(v for k, v in self.injected.items() if k.startswith(prefix))


# ------------------------------------------------------------------ profiles
# Named node-fault profiles: the vocabulary tests/test_health.py, `make
# repair` and docs/FAILURE_MODES.md share (same registry pattern as
# policy.PROFILES). Defaults are envtest-timescale; keyword overrides pass
# through to the underlying NodeFault fields.

NODE_FAULT_PROFILES: dict[str, Callable[..., NodeFaultInjector]] = {}


def node_fault_profile(name: str, seed: int = 0, **overrides) -> NodeFaultInjector:
    try:
        factory = NODE_FAULT_PROFILES[name]
    except KeyError:
        raise ValueError(f"unknown node-fault profile {name!r}; "
                         f"known: {sorted(NODE_FAULT_PROFILES)}") from None
    return factory(seed, **overrides)


def _register_profile(name: str):
    def deco(fn):
        NODE_FAULT_PROFILES[name] = fn
        return fn
    return deco


def _faults(base: NodeFault, **overrides) -> list[NodeFault]:
    return [replace(base, **overrides)]


@_register_profile("flapping_node")
def _flapping_node(seed: int, **kw) -> NodeFaultInjector:
    """Worker 0 of every pool flaps Ready faster than any toleration: each
    False interval is short, each flip resets lastTransitionTime. Repair
    must accrue the flaps (hysteresis) instead of restarting its clock."""
    return NodeFaultInjector(seed, _faults(NodeFault(
        kind="flap", match="*-w0", start=0.3, period=0.25, duration=2.0), **kw))


@_register_profile("degraded_slice")
def _degraded_slice(seed: int, **kw) -> NodeFaultInjector:
    """One host's accelerator degrades (AcceleratorHealthy=False) — for a
    multi-host slice the ICI ring is broken and the whole slice must be
    replaced, not just the sick host."""
    return NodeFaultInjector(seed, _faults(NodeFault(
        kind="degrade", match="*-w0", start=0.2, duration=60.0), **kw))


@_register_profile("silent_death")
def _silent_death(seed: int, **kw) -> NodeFaultInjector:
    """Worker 0's kubelet dies silently: heartbeats stop, Ready stays a
    stale True, and no watch event will ever announce it. Repair's
    stale-heartbeat policy is the only thing that can see this."""
    return NodeFaultInjector(seed, _faults(NodeFault(
        kind="silent", match="*-w0", start=0.3, duration=60.0), **kw))


@_register_profile("maintenance_wave")
def _maintenance_wave(seed: int, **kw) -> NodeFaultInjector:
    """EVERY managed node gets a scheduled-maintenance notice at once — the
    correlated-wave signature. The fraction breaker must hold repair back
    (zero force-deletes while tripped) instead of mass-deleting the fleet."""
    return NodeFaultInjector(seed, _faults(NodeFault(
        kind="maintenance", match="*", start=0.2, duration=2.5), **kw))
