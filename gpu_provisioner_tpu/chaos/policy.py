"""Deterministic, seeded chaos policies for the fake cloud + kube client.

The reference repo survives real clouds because every layer assumes the cloud
misbehaves; its fakes can only script one fault at a time
(``_FaultInjector.fail(method, times=1)``). This module generalizes that into
a *policy*: probabilistic errors, latency/hang injection, error schedules
(bursts), and partial-failure modes (pool created but nodes never join,
queued resource stuck mid-ladder, operation ``result()`` raising after
``done()``) — so any envtest scenario can run under a named chaos profile and
still be reproducible.

Determinism without a shared RNG stream: every decision is a pure hash of
``(seed, decision key)``. Concurrent reconciles interleave differently from
run to run, which would desynchronize a sequential PRNG; keyed draws make
each decision independent of scheduling order — ``should("no_join", pool)``
answers the same for a given seed no matter when it is asked.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from collections import defaultdict
from dataclasses import dataclass
from fnmatch import fnmatch
from typing import Callable, Optional

from ..providers.gcp import APIError


@dataclass
class FaultRule:
    """One injection rule, matched against ``scope.method`` call sites
    (e.g. ``nodepools.begin_create``, ``queuedresources.*``, ``kube.list``).

    ``rate`` is the per-call probability that ``error()`` is raised.
    ``after``/``until`` window the rule to a call-count range of the matched
    site, which is how bursts/outage schedules are expressed (calls 0..until
    fail, then recovery). ``latency`` sleeps before the error check on every
    matched call; ``hang``/``hang_rate`` sleep long enough to trip a
    reconcile deadline (the wedged-API simulation).
    """

    match: str
    rate: float = 0.0
    error: Optional[Callable[[], Exception]] = None
    latency: float = 0.0
    hang: float = 0.0
    hang_rate: float = 0.0
    after: int = 0
    until: Optional[int] = None


@dataclass
class ZoneWindow:
    """A scripted per-zone dry window, consumed by the fake cloud's capacity
    model: while a zone matching ``match`` (fnmatch) is inside
    ``[start, start + duration)`` on its own clock, every ``begin_create``
    into it verdicts RESOURCE_EXHAUSTED regardless of inventory.

    The clock is anchored at the zone's FIRST CONSULT (the ``nodefaults.py``
    first-observation idiom): the window is deterministic relative to when
    traffic first reaches the zone, not wall-clock test startup, so a soak's
    probe counts are reproducible whatever the harness warm-up costs."""

    match: str
    start: float = 0.0
    duration: float = 1.0


def transient(code: int = 503, message: str = "chaos: transient") -> Callable[[], Exception]:
    return lambda: APIError(message, code=code)


def stockout(message: str = "chaos: out of TPU capacity") -> Callable[[], Exception]:
    return lambda: APIError(message, code=429)


class ChaosPolicy:
    """A seeded bundle of fault rules + partial-failure mode rates.

    Partial modes (consumed by ``FakeCloud``):

    - ``no_join``    node pool creates fine, kubelets never join (keyed per
                     pool name: a doomed pool stays doomed across retries —
                     that is the scenario's point).
    - ``qr_stuck``   queued resource never advances past CREATING (keyed per
                     resource name).
    - ``op_error``   LRO ``done()`` returns True but ``result()`` raises and
                     the pool lands in ERROR (keyed per pool name *and*
                     attempt, so retries can eventually succeed).
    """

    def __init__(self, seed: int = 0, rules: Optional[list[FaultRule]] = None,
                 partial: Optional[dict[str, float]] = None,
                 zone_windows: Optional[list[ZoneWindow]] = None,
                 spot: Optional[dict[str, float]] = None):
        self.seed = seed
        self.rules = list(rules or [])
        self.partial = dict(partial or {})
        # capacity-fault layer (consumed by the fake cloud's capacity model):
        # scripted per-zone dry windows, and the spot-preemption spec
        # {"rate", "after", "window"} — rate is the stable per-pool victim
        # probability, after the minimum pool age before the notice, window
        # bounds the reclaim wave (anchored at first consult) so replacement
        # pools created once it closes survive and soaks converge.
        self.zone_windows = list(zone_windows or [])
        self.spot = dict(spot or {})
        self._zone_first_seen: dict[str, float] = {}
        self._spot_anchor: Optional[float] = None
        self._site_calls: dict[str, int] = defaultdict(int)
        self._key_calls: dict[tuple, int] = defaultdict(int)
        # observability for soak assertions: what actually fired
        self.injected: dict[str, int] = defaultdict(int)
        self.calls: dict[str, int] = defaultdict(int)

    # ------------------------------------------------------------- draws
    def _draw(self, *key) -> float:
        """Pure hash draw in [0, 1): independent of call ordering."""
        h = hashlib.sha256(repr((self.seed,) + key).encode()).digest()
        return int.from_bytes(h[:8], "big") / 2 ** 64

    # ---------------------------------------------------------- call path
    async def before_call(self, scope: str, method: str) -> None:
        """Instrumentation hook fakes call before executing an API method.
        May sleep (latency/hang) and/or raise the rule's error."""
        site = f"{scope}.{method}"
        n = self._site_calls[site]
        self._site_calls[site] = n + 1
        self.calls[site] += 1
        for i, rule in enumerate(self.rules):
            if not fnmatch(site, rule.match):
                continue
            if n < rule.after or (rule.until is not None and n >= rule.until):
                continue
            if rule.latency > 0:
                await asyncio.sleep(rule.latency)
            if rule.hang > 0 and (rule.hang_rate >= 1.0 or
                                  self._draw("hang", i, site, n) < rule.hang_rate):
                self.injected[f"hang:{site}"] += 1
                await asyncio.sleep(rule.hang)
            if rule.error is not None and (
                    rule.rate >= 1.0 or self._draw("err", i, site, n) < rule.rate):
                self.injected[f"error:{site}"] += 1
                raise rule.error()

    # ------------------------------------------------------ partial modes
    def should(self, mode: str, key: str, per_attempt: bool = False) -> bool:
        """Deterministic partial-failure decision for ``key`` (a pool or
        queued-resource name). ``per_attempt`` folds a per-key call counter
        into the draw so repeated attempts re-roll (op_error); without it the
        decision is stable for the key's lifetime (no_join, qr_stuck)."""
        rate = self.partial.get(mode, 0.0)
        if rate <= 0:
            return False
        draw_key: tuple = (mode, key)
        if per_attempt:
            n = self._key_calls[(mode, key)]
            self._key_calls[(mode, key)] = n + 1
            draw_key = (mode, key, n)
        hit = rate >= 1.0 or self._draw(*draw_key) < rate
        if hit:
            self.injected[f"{mode}:{key}"] += 1
        return hit

    # --------------------------------------------------- capacity faults
    def zone_dry(self, zone: str) -> bool:
        """True while ``zone`` sits inside a scripted dry window on its own
        first-consult-anchored clock. Counted under ``stockout:<zone>`` so
        soaks can assert how often the dry verdict actually fired."""
        now = time.monotonic()
        first = self._zone_first_seen.setdefault(zone, now)
        elapsed = now - first
        for w in self.zone_windows:
            if not fnmatch(zone, w.match):
                continue
            if w.start <= elapsed < w.start + w.duration:
                self.injected[f"stockout:{zone}"] += 1
                return True
        return False

    def spot_preempt(self, pool: str, age: float) -> bool:
        """Deterministic spot-preemption verdict for a RUNNING spot pool of
        ``age`` seconds. The draw is stable per pool name (a spared pool
        stays spared); the wave window is anchored at the first consult so
        replacements created after it closes are never preempted."""
        rate = self.spot.get("rate", 0.0)
        if rate <= 0:
            return False
        now = time.monotonic()
        if self._spot_anchor is None:
            self._spot_anchor = now
        window = self.spot.get("window")
        if window is not None and now - self._spot_anchor >= window:
            return False
        if age < self.spot.get("after", 0.0):
            return False
        if rate >= 1.0 or self._draw("spot", pool) < rate:
            self.injected[f"spot_preempt:{pool}"] += 1
            return True
        return False

    def injected_total(self, prefix: str = "") -> int:
        return sum(v for k, v in self.injected.items() if k.startswith(prefix))


# ------------------------------------------------------------------ profiles

PROFILES: dict[str, Callable[[int], ChaosPolicy]] = {}


def profile(name: str, seed: int = 0) -> ChaosPolicy:
    """Build a named chaos profile. Profiles are the vocabulary the soak
    suite (tests/test_chaos.py), ``make chaos``, and docs/FAILURE_MODES.md
    share."""
    try:
        factory = PROFILES[name]
    except KeyError:
        raise ValueError(
            f"unknown chaos profile {name!r}; "
            f"known: {sorted(PROFILES)}") from None
    return factory(seed)


def _register(name: str):
    def deco(fn):
        PROFILES[name] = fn
        return fn
    return deco


@_register("flaky-cloud")
def _flaky_cloud(seed: int) -> ChaosPolicy:
    """20% transient 5xx on every cloud API call — the everyday GKE/TPU
    weather. Everything must still converge via retry + backoff."""
    return ChaosPolicy(seed, rules=[
        FaultRule(match="nodepools.*", rate=0.2, error=transient(503)),
        FaultRule(match="queuedresources.*", rate=0.2, error=transient(500)),
    ])


@_register("stockout")
def _stockout(seed: int) -> ChaosPolicy:
    """Deterministic full stockout: EVERY zone is dry for its first second
    (capacity-model dry window, not a probabilistic call-count burst — that
    shape survives as ``stockout-flaky``). Claims whose placement walk runs
    inside the window terminate (deleted, KAITO would re-shape); creates
    after it go through. Composes with the fake cloud's zone inventories:
    the window dries a zone regardless of chips remaining."""
    return ChaosPolicy(seed, zone_windows=[ZoneWindow(match="*", duration=1.0)])


@_register("stockout-flaky")
def _stockout_flaky(seed: int) -> ChaosPolicy:
    """RESOURCE_EXHAUSTED bursts (the pre-capacity-model ``stockout``
    shape): the first creates hit a stockout (terminal for those claims —
    deleted, KAITO would re-shape), later creates go through, with 10%
    transient noise on top. Mixed terminal/success convergence."""
    return ChaosPolicy(seed, rules=[
        FaultRule(match="nodepools.begin_create", rate=1.0, until=2,
                  error=stockout()),
        FaultRule(match="nodepools.*", rate=0.1, error=transient(503)),
    ])


@_register("zonal_stockout")
def _zonal_stockout(seed: int) -> ChaosPolicy:
    """One zone of the fleet dries up and stays dry (``*-b`` — in the
    canonical three-zone envtest layout that is exactly one of three) while
    its siblings keep capacity: the placement walk must route every claim
    around the dry zone, and the stockout memo must hold probes of it to
    one per TTL window. No noise rules — probe counts are the assertion."""
    return ChaosPolicy(seed, zone_windows=[
        ZoneWindow(match="*-b", start=0.0, duration=600.0)])


@_register("spot_reclaim")
def _spot_reclaim(seed: int) -> ChaosPolicy:
    """The cloud preempts every spot pool older than 0.2s during a 1.5s
    reclaim wave: nodes get the SpotPreempted notice, then the pool is
    reclaim-deleted after the grace. Repair must replace the slices within
    budget; replacements created after the wave closes survive."""
    return ChaosPolicy(seed, spot={"rate": 1.0, "after": 0.2, "window": 1.5})


@_register("partial-provision")
def _partial_provision(seed: int) -> ChaosPolicy:
    """Pools create and report RUNNING, but for ~half of them the kubelets
    never join (half-created capacity — the dominant leak shape). Liveness
    must reap the claims, GC must reap the pools."""
    return ChaosPolicy(seed, partial={"no_join": 0.5})


@_register("stuck-queue")
def _stuck_queue(seed: int) -> ChaosPolicy:
    """Queued resources wedge mid-ladder (stuck CREATING forever) — the
    Cloud TPU stockout-queue pathology. Claims on the queued path must hit
    the launch liveness deadline, not spin."""
    return ChaosPolicy(seed, partial={"qr_stuck": 1.0})


@_register("op-error")
def _op_error(seed: int) -> ChaosPolicy:
    """LROs complete (``done()`` True) but ``result()`` raises and the pool
    lands in ERROR ~half the time per attempt — create retries must replace
    the carcass, never duplicate it."""
    return ChaosPolicy(seed, partial={"op_error": 0.5})


@_register("outage")
def _outage(seed: int) -> ChaosPolicy:
    """Sustained full outage of the node-pool API: every call fails 503.
    Nothing converges — the assertion is about *cost*: backoff/breaker keep
    the call rate O(probe interval), not O(retry storm)."""
    return ChaosPolicy(seed, rules=[
        FaultRule(match="nodepools.*", rate=1.0, error=transient(503)),
    ])


@_register("slow-cloud")
def _slow_cloud(seed: int) -> ChaosPolicy:
    """Every cloud call is slow and some hang long enough to trip reconcile
    deadlines — exercises per-reconcile cancellation."""
    return ChaosPolicy(seed, rules=[
        FaultRule(match="nodepools.*", latency=0.02, hang=5.0, hang_rate=0.1),
        FaultRule(match="queuedresources.*", latency=0.02),
    ])
