"""CloudProvider contract, error taxonomy, metrics decorator, TPU impl.

Mirrors the layering of the reference: the Karpenter CloudProvider interface
(vendor/sigs.k8s.io/karpenter/pkg/cloudprovider/types.go:72-100) is implemented
by a thin shim (pkg/cloudprovider/cloudprovider.go) that delegates to the
instance provider, and every call is wrapped in a Prometheus metrics decorator
(vendor/.../cloudprovider/metrics/cloudprovider.go:95-190).
"""

from .errors import (  # noqa: F401
    CloudProviderError, CreateError, InsufficientCapacityError,
    NodeClaimNotFoundError, NodeClassNotReadyError, ignore_nodeclaim_not_found,
    is_nodeclaim_not_found,
)
from .metrics import MetricsDecorator  # noqa: F401
from .types import CloudProvider, RepairPolicy  # noqa: F401
from .tpu import TPUCloudProvider  # noqa: F401
