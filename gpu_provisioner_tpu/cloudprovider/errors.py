"""Re-export of the cloud error taxonomy (lives top-level in
``gpu_provisioner_tpu.errors`` to keep providers ↔ cloudprovider import-cycle
free)."""

from ..errors import (  # noqa: F401
    CloudProviderError, CreateError, InsufficientCapacityError,
    NodeClaimNotFoundError, NodeClassNotReadyError, ignore_nodeclaim_not_found,
    is_nodeclaim_not_found,
)
