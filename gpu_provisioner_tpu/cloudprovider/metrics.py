"""Prometheus metrics decorator around any CloudProvider.

The analog of vendor/sigs.k8s.io/karpenter/pkg/cloudprovider/metrics/
cloudprovider.go:49-80,95-190 — method duration histogram + error counter
labeled by (controller, method, provider, error type), applied at operator
assembly time (cmd/controller/main.go:41 `metrics.Decorate`). Metric names
kept identical so existing karpenter dashboards work unchanged.
"""

from __future__ import annotations

import time
from contextvars import ContextVar

from prometheus_client import REGISTRY, Counter, Histogram

# The reference stamps the calling controller into the context
# (injection.WithControllerName); a ContextVar is the asyncio equivalent.
current_controller: ContextVar[str] = ContextVar("controller", default="unknown")


def _get_or_create(cls, name, doc, labelnames, **kw):
    try:
        return cls(name, doc, labelnames, **kw)
    except ValueError:  # already registered (test re-imports)
        return REGISTRY._names_to_collectors[name]


METHOD_DURATION = _get_or_create(
    Histogram, "karpenter_cloudprovider_duration_seconds",
    "Duration of cloud provider method calls.",
    ["controller", "method", "provider"])

METHOD_ERRORS = _get_or_create(
    Counter, "karpenter_cloudprovider_errors_total",
    "Total number of cloud provider method errors.",
    ["controller", "method", "provider", "error"])

_DECORATED = ("create", "get", "list", "delete", "get_instance_types", "is_drifted")


class MetricsDecorator:
    """Wraps a CloudProvider; passthrough for non-IO methods."""

    def __init__(self, inner):
        self.inner = inner

    def name(self) -> str:
        return self.inner.name()

    def repair_policies(self):
        return self.inner.repair_policies()

    def get_supported_node_classes(self):
        return self.inner.get_supported_node_classes()

    def __getattr__(self, method: str):
        fn = getattr(self.inner, method)
        if method not in _DECORATED:
            return fn

        async def wrapped(*args, **kwargs):
            controller = current_controller.get()
            provider = self.inner.name()
            start = time.monotonic()
            try:
                return await fn(*args, **kwargs)
            except Exception as e:
                METHOD_ERRORS.labels(controller, method, provider,
                                     type(e).__name__).inc()
                raise
            finally:
                METHOD_DURATION.labels(controller, method, provider).observe(
                    time.monotonic() - start)

        return wrapped
