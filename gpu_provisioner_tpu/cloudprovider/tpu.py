"""The TPU CloudProvider: thin shim over the instance provider (L3).

Mirrors pkg/cloudprovider/cloudprovider.go — every method delegates to the
instance provider (:54,65,79,91) and ``instance_to_nodeclaim`` (:127-173)
translates the cloud view back into a NodeClaim: labels, capacity type,
providerID, imageID, creation timestamp recovered from the pool label, and a
Deleting state surfaced as a deletionTimestamp. Improvements over the
reference are deliberate and noted inline: a real instance-type catalog
(reference returns `[]`, :99-101) and TPU-aware repair policies.
"""

from __future__ import annotations

from typing import Optional

from ..apis import labels as wk
from ..apis.kaito import KaitoNodeClass
from ..apis.karpenter import NodeClaim, NodeClaimStatus
from ..apis.meta import ObjectMeta
from ..apis.serde import now
from ..catalog import CATALOG
from ..providers.instance import (
    Instance, InstanceProvider, STATE_DELETING, parse_ts_label,
)
from .errors import NodeClaimNotFoundError
from .types import InstanceTypeInfo, RepairPolicy

PROVIDER_NAME = "gcp"  # reference names itself "azure" (cloudprovider.go:49)

# Node-repair toleration: NodeReady False/Unknown for 10 min → replace
# (reference: cloudprovider.go:103-116).
REPAIR_TOLERATION_SECONDS = 10 * 60

# Spot preemption is a done deal the moment the cloud stamps the notice —
# tolerating it buys nothing (the capacity is being reclaimed regardless),
# so the policy uses a much shorter fuse than hardware-fault repair.
SPOT_REPAIR_TOLERATION_SECONDS = 30.0


class TPUCloudProvider:
    def __init__(self, instances: InstanceProvider,
                 repair_toleration: float = REPAIR_TOLERATION_SECONDS):
        self.instances = instances
        self.repair_toleration = repair_toleration

    def name(self) -> str:
        return PROVIDER_NAME

    async def create(self, nodeclaim: NodeClaim) -> NodeClaim:
        instance = await self.instances.create(nodeclaim)
        return instance_to_nodeclaim(instance)

    async def get(self, provider_id: str) -> NodeClaim:
        if not provider_id:
            raise NodeClaimNotFoundError("empty providerID")
        return instance_to_nodeclaim(await self.instances.get(provider_id))

    async def list(self) -> list[NodeClaim]:
        return [instance_to_nodeclaim(i) for i in await self.instances.list()]

    async def delete(self, nodeclaim: NodeClaim) -> None:
        await self.instances.delete(nodeclaim.metadata.name)

    async def get_instance_types(self) -> list[InstanceTypeInfo]:
        # The reference returns an empty catalog (cloudprovider.go:99-101);
        # exposing the real one costs nothing and lets tooling introspect.
        return [InstanceTypeInfo(
            name=s.name, generation=s.generation, topology=s.topology,
            chips=s.chips, hosts=s.hosts, capacity=s.per_host_capacity(),
        ) for s in CATALOG]

    async def is_drifted(self, nodeclaim: NodeClaim) -> str:
        return ""  # reference: always empty (cloudprovider.go:94-97)

    def repair_policies(self) -> list[RepairPolicy]:
        return [
            RepairPolicy("Ready", "False", self.repair_toleration),
            RepairPolicy("Ready", "Unknown", self.repair_toleration),
            # TPU extension: device-plugin-reported accelerator health.
            RepairPolicy("AcceleratorHealthy", "False", self.repair_toleration),
            # TPU extension: host scheduled for maintenance — drain-first
            # repair replaces the slice ahead of the disruption. A
            # maintenance WAVE (many nodes at once) is held back by the
            # health controller's unhealthy-fraction breaker + RepairBudget.
            RepairPolicy("MaintenanceScheduled", "True", self.repair_toleration),
            # TPU extension: spot capacity reclaimed by the cloud. The grace
            # window is short by design — the node WILL disappear; repair
            # exists to re-place the slice (the placement engine's fallback
            # walk picks the zone), not to wait the fault out.
            RepairPolicy("SpotPreempted", "True",
                         min(self.repair_toleration,
                             SPOT_REPAIR_TOLERATION_SECONDS)),
        ]

    def get_supported_node_classes(self) -> list[type]:
        return [KaitoNodeClass]


def instance_to_nodeclaim(instance: Instance) -> NodeClaim:
    """Cloud instance → NodeClaim view (cloudprovider.go:127-173)."""
    labels = dict(instance.labels)
    labels[wk.CAPACITY_TYPE_LABEL] = instance.capacity_type
    if instance.type:
        labels[wk.INSTANCE_TYPE_LABEL] = instance.type

    created = None
    ts = labels.get(wk.KAITO_CREATION_TIMESTAMP_LABEL, "")
    if ts:
        created = parse_ts_label(ts)

    meta = ObjectMeta(name=instance.name, labels=labels,
                      creation_timestamp=created or now())
    if instance.state == STATE_DELETING:
        meta.deletion_timestamp = now()

    status = NodeClaimStatus(
        provider_id=instance.id,
        image_id=instance.image_id,
        capacity={
            wk.TPU_RESOURCE_NAME: str(instance.chips),
        } if instance.chips else {},
    )
    return NodeClaim(metadata=meta, status=status)
