"""The CloudProvider interface the controllers program against.

Method set mirrors vendor/sigs.k8s.io/karpenter/pkg/cloudprovider/types.go:72-100
(Create/Delete/Get/List/GetInstanceTypes/IsDrifted/RepairPolicies/Name/
GetSupportedNodeClasses). RepairPolicy drives the node auto-repair controller
(reference: pkg/cloudprovider/cloudprovider.go:103-116 tolerates NodeReady
False/Unknown for 10 minutes before force-replacing the node).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

from ..apis.karpenter import NodeClaim


@dataclass(frozen=True)
class RepairPolicy:
    condition_type: str          # Node condition to watch, e.g. "Ready"
    condition_status: str        # unhealthy value, e.g. "False"/"Unknown"
    toleration_duration: float   # seconds before force-repair


@dataclass(frozen=True)
class InstanceTypeInfo:
    """Catalog row surfaced through GetInstanceTypes. The reference returns an
    empty list (cloudprovider.go:99-101, 'no catalog!'); the TPU build exposes
    its real catalog so schedulers/tools can introspect shapes."""

    name: str
    generation: str
    topology: str
    chips: int
    hosts: int
    capacity: dict[str, str]


class CloudProvider(Protocol):
    def name(self) -> str: ...

    async def create(self, nodeclaim: NodeClaim) -> NodeClaim: ...

    async def get(self, provider_id: str) -> NodeClaim: ...

    async def list(self) -> list[NodeClaim]: ...

    async def delete(self, nodeclaim: NodeClaim) -> None: ...

    async def get_instance_types(self) -> list[InstanceTypeInfo]: ...

    async def is_drifted(self, nodeclaim: NodeClaim) -> str: ...

    def repair_policies(self) -> list[RepairPolicy]: ...

    def get_supported_node_classes(self) -> list[type]: ...
