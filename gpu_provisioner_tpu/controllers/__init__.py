"""Reconcile controllers (L4 of the layer map, SURVEY.md §1).

The active controller set replicates what the reference actually runs — its
vendored Karpenter fork comments out provisioner/disruption/consolidation and
keeps only: nodeclaim lifecycle, node termination, nodeclaim GC, node health
(vendor/.../controllers/controllers.go:39-122, SURVEY.md §2b V1) — plus the
first-party instance GC loop. KAITO owns NodeClaim creation; this controller
only materializes and reaps them (SURVEY.md §7 hard part 5).
"""

from .gc import InstanceGCController, NodeClaimGCController  # noqa: F401
from .health import NodeHealthController  # noqa: F401
from .lifecycle import LifecycleOptions, NodeClaimLifecycleController  # noqa: F401
from .termination import EvictionQueue, NodeTerminationController  # noqa: F401
