"""Bidirectional garbage collection — the leak-proofing loops (§3.4).

Two independent singletons diff the cloud and the cluster in opposite
directions:

- ``InstanceGCController`` (first-party analog,
  pkg/controllers/instance/garbagecollection/controller.go): every 2 minutes,
  delete cloud slices whose NodeClaim no longer exists and that are older
  than a 30s grace window (:74-87), with 20 parallel delete workers (:91);
  also delete orphaned Node objects (:99-120). This catches the documented
  leak: NodeClaim deleted while its pool is still Creating.
- ``NodeClaimGCController`` (vendored analog,
  vendor/.../nodeclaim/garbagecollection/controller.go:60-110): delete
  Registered NodeClaims whose providerID vanished from CloudProvider.List
  while the kubelet is not Ready.

GC correctness decides whether paid TPU slices leak (SURVEY.md §7 hard
part 3).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass

from ..apis import labels as wk
from ..apis.core import Node
from ..apis.karpenter import NodeClaim, REGISTERED
from ..apis.serde import now
from ..errors import NodeClaimNotFoundError
from ..runtime import NotFoundError
from ..runtime.client import Client
from .utils import list_managed

log = logging.getLogger("controllers.gc")


@dataclass
class GCOptions:
    interval: float = 120.0       # controller.go:123 (2 min)
    leak_grace: float = 30.0      # controller.go:81 (30 s)
    workers: int = 20             # controller.go:91
    # Watch-age liveness bound (ADVICE r3 / VERDICT r4 item 9): both GC
    # directions DELETE things based on a cached cluster view; if the
    # informer's watch is wedged AND its re-lists are failing, that view
    # can be arbitrarily stale — a deleted-then-recreated claim would look
    # leaked, a just-registered claim vanished. Refuse the pass past this
    # bound (2× the informer resync: one missed re-list is jitter, two is
    # an outage). 0 disables.
    max_cache_age: float = 600.0
    # Range-ownership predicate ``owns(name) -> bool`` for multi-process
    # shard workers (registry distribute_singletons): each worker's GC
    # loops reap only cloud/cluster resources in its leased ranges —
    # instance names equal claim names equal pool names, so one predicate
    # partitions both directions consistently. None = whole fleet.
    owns: object = None


def _cache_age(client, cls) -> float:
    """Age of the cached view ``client.list(cls)`` serves, 0.0 for clients
    without an informer cache (direct reads are always fresh)."""
    fn = getattr(client, "cache_age", None)
    return fn(cls) if fn is not None else 0.0


def _cache_too_stale(client, opts: GCOptions, who: str, *kinds) -> bool:
    if opts.max_cache_age <= 0:
        return False
    for cls in kinds:
        age = _cache_age(client, cls)
        if age > opts.max_cache_age:
            log.warning(
                "%s: skipping pass — cached %s view is %.0fs old "
                "(bound %.0fs); watch wedged and re-lists failing?",
                who, cls.__name__, age, opts.max_cache_age)
            return True
    return False


class InstanceGCController:
    NAME = "instance.garbagecollection"

    def __init__(self, client: Client, cloudprovider, options: GCOptions = None):
        self.client = client
        self.cp = cloudprovider
        self.opts = options or GCOptions()
        # name -> monotonic time this instance was FIRST observed orphaned.
        # Cloud creation timestamps come from a second-resolution label, so
        # "age > grace" alone brands a just-created instance one full
        # second old the moment the wall clock rolls — with the fake cloud
        # now settling creates server-side (crash-restart realism), that
        # raced in-flight direct creates. An orphan is reaped when its
        # label age EXCEEDS the grace by the 1s truncation error, or when
        # this controller has itself observed it orphaned for the grace.
        self._orphan_since: dict[str, float] = {}

    async def run_once(self) -> float:
        try:
            await self._collect()
        except Exception as e:  # noqa: BLE001 — GC must keep ticking
            log.warning("instance GC pass failed: %s", e, exc_info=True)
        return self.opts.interval

    async def _collect(self) -> None:
        if _cache_too_stale(self.client, self.opts, self.NAME,
                            NodeClaim, Node):
            return
        instances = await self.cp.list()
        if self.opts.owns is not None:
            instances = [i for i in instances
                         if self.opts.owns(i.metadata.name)]
        claims = {nc.metadata.name for nc in await list_managed(self.client)}

        leaked = []
        mono = asyncio.get_event_loop().time()
        orphan_since: dict[str, float] = {}
        for inst in instances:
            if inst.metadata.name in claims:
                continue
            first = orphan_since[inst.metadata.name] = \
                self._orphan_since.get(inst.metadata.name, mono)
            age = (now() - inst.metadata.creation_timestamp).total_seconds() \
                if inst.metadata.creation_timestamp else 0.0
            if (age - 1.0 > self.opts.leak_grace
                    or mono - first > self.opts.leak_grace):
                leaked.append(inst)
        # instances that regained a claim or vanished restart their clock
        self._orphan_since = orphan_since

        if leaked:
            log.info("instance GC: deleting %d leaked slices: %s",
                     len(leaked), [i.metadata.name for i in leaked])
            sem = asyncio.Semaphore(self.opts.workers)

            async def reap(inst):
                async with sem:
                    try:
                        await self.cp.delete(inst)
                    except NodeClaimNotFoundError:
                        pass
                    # forget the reaped orphan's first-seen clock: a
                    # same-named pool recreated later must start a fresh
                    # observed-for window, not inherit this one's
                    self._orphan_since.pop(inst.metadata.name, None)
            await asyncio.gather(*(reap(i) for i in leaked))

        await self._collect_orphan_nodes(claims, instances)

    async def _collect_orphan_nodes(self, claims: set[str], instances) -> None:
        """Delete managed Node objects whose slice has neither a NodeClaim nor
        a cloud instance (controller.go:99-120)."""
        live_pools = claims | {i.metadata.name for i in instances}
        for node in await self.client.list(Node):
            pool = node.metadata.labels.get(wk.GKE_NODEPOOL_LABEL)
            owned = node.metadata.labels.get(wk.NODEPOOL_LABEL) == wk.KAITO_NODEPOOL_NAME
            if not pool or not owned or pool in live_pools:
                continue
            if self.opts.owns is not None and not self.opts.owns(pool):
                continue
            if node.metadata.deletion_timestamp is not None:
                continue
            log.info("instance GC: deleting orphan node %s (pool %s)",
                     node.metadata.name, pool)
            try:
                await self.client.delete(Node, node.metadata.name)
            except NotFoundError:
                pass


class NodeClaimGCController:
    NAME = "nodeclaim.garbagecollection"

    def __init__(self, client: Client, cloudprovider, options: GCOptions = None):
        self.client = client
        self.cp = cloudprovider
        self.opts = options or GCOptions()

    async def run_once(self) -> float:
        try:
            await self._collect()
        except Exception as e:  # noqa: BLE001
            log.warning("nodeclaim GC pass failed: %s", e, exc_info=True)
        return self.opts.interval

    async def _collect(self) -> None:
        if _cache_too_stale(self.client, self.opts, self.NAME, NodeClaim):
            return
        cloud_ids = {i.status.provider_id for i in await self.cp.list()
                     if i.status.provider_id}
        doomed = []
        for nc in await list_managed(self.client):
            if (self.opts.owns is not None
                    and not self.opts.owns(nc.metadata.name)):
                continue
            if nc.metadata.deletion_timestamp is not None:
                continue
            reg = nc.status_conditions.get(REGISTERED)
            if reg is None or reg.status != "True":
                continue
            # Same grace the instance GC applies to fresh pools: a claim that
            # registered after the cloud list snapshot was taken would look
            # "vanished" for one pass — never reap inside the grace window.
            if (reg.last_transition_time is not None
                    and (now() - reg.last_transition_time).total_seconds()
                    <= self.opts.leak_grace):
                continue
            if not nc.status.provider_id or nc.status.provider_id in cloud_ids:
                continue
            if await self._kubelet_ready(nc):
                continue  # node still healthy → trust it over a list race
            doomed.append(nc)

        if doomed:
            log.info("nodeclaim GC: deleting %d claims with vanished instances: %s",
                     len(doomed), [n.metadata.name for n in doomed])
            sem = asyncio.Semaphore(self.opts.workers)

            async def reap(nc):
                async with sem:
                    try:
                        await self.client.delete(NodeClaim, nc.metadata.name)
                    except NotFoundError:
                        pass
            await asyncio.gather(*(reap(n) for n in doomed))

    async def _kubelet_ready(self, nc: NodeClaim) -> bool:
        if not nc.status.node_name:
            return False
        try:
            node = await self.client.get(Node, nc.status.node_name)
        except NotFoundError:
            return False
        return node.is_ready()
