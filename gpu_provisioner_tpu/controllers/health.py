"""Node health / auto-repair controller (V8).

Watches Node conditions; when one matches a CloudProvider RepairPolicy and has
been unhealthy longer than its toleration, force-deletes the owning NodeClaim
so KAITO recreates it (vendor/.../controllers/node/health/controller.go:
106-183; flow §3.5 in SURVEY.md). The reference's nodepool/cluster healthy-%
circuit breakers are commented out there (:130-151); here a cluster-level
breaker is kept behind an option, default off, to match active behavior while
leaving the seam.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ..apis.core import Node
from ..apis.karpenter import NodeClaim
from ..apis.serde import now
from ..runtime import NotFoundError, Request, Result
from ..runtime.client import Client
from ..runtime.events import Recorder
from .utils import nodeclaim_for_node

log = logging.getLogger("controllers.health")


@dataclass
class HealthOptions:
    # Cluster-wide circuit breaker: skip repair if more than this fraction of
    # managed nodes is unhealthy (0 disables, matching the reference's
    # commented-out breaker).
    max_unhealthy_fraction: float = 0.0
    # Watch-age liveness bound (VERDICT r4 item 9): repair deletes
    # NodeClaims partly on a cached Node view (the breaker's list and
    # nodeclaim correlation); refuse repair when that cache hasn't
    # observed the apiserver within this bound. 0 disables.
    max_cache_age: float = 600.0


class NodeHealthController:
    NAME = "node.health"

    def __init__(self, client: Client, cloudprovider,
                 recorder: Optional[Recorder] = None,
                 options: Optional[HealthOptions] = None):
        self.client = client
        self.cp = cloudprovider
        self.recorder = recorder
        self.opts = options or HealthOptions()

    async def reconcile(self, req: Request) -> Result:
        try:
            node = await self.client.get(Node, req.name)
        except NotFoundError:
            return Result()
        if node.metadata.deletion_timestamp is not None:
            return Result()

        match = self._match_policy(node)
        if match is None:
            return Result()
        condition, policy = match

        elapsed = 0.0
        if condition.last_transition_time is not None:
            elapsed = (now() - condition.last_transition_time).total_seconds()
        if elapsed < policy.toleration_duration:
            # requeue until the toleration elapses (health/controller.go:121-127)
            return Result(requeue_after=policy.toleration_duration - elapsed)

        if self._cache_too_stale():
            log.warning("repair of %s deferred: cached cluster view older "
                        "than %.0fs", node.metadata.name,
                        self.opts.max_cache_age)
            return Result(requeue_after=policy.toleration_duration)

        if await self._circuit_broken():
            log.warning("repair of %s skipped: cluster unhealthy fraction over limit",
                        node.metadata.name)
            return Result(requeue_after=policy.toleration_duration)

        nc = await nodeclaim_for_node(self.client, node)
        if nc is None or nc.metadata.deletion_timestamp is not None:
            return Result()
        log.info("repairing node %s: %s=%s for %.0fs; deleting nodeclaim %s",
                 node.metadata.name, condition.type, condition.status, elapsed,
                 nc.metadata.name)
        if self.recorder is not None:
            await self.recorder.publish(nc, "Warning", "NodeRepair",
                                        f"node {node.metadata.name} unhealthy: "
                                        f"{condition.type}={condition.status}")
        try:
            await self.client.delete(NodeClaim, nc.metadata.name)
        except NotFoundError:
            pass
        return Result()

    def _match_policy(self, node: Node):
        for policy in self.cp.repair_policies():
            for c in node.status.conditions:
                if c.type == policy.condition_type and c.status == policy.condition_status:
                    return c, policy
        return None

    def _cache_too_stale(self) -> bool:
        """A destructive decision must not act on a cache the watch stopped
        feeding — see GCOptions.max_cache_age for the rationale."""
        from .gc import _cache_age
        if self.opts.max_cache_age <= 0:
            return False
        return _cache_age(self.client, Node) > self.opts.max_cache_age

    async def _circuit_broken(self) -> bool:
        if self.opts.max_unhealthy_fraction <= 0:
            return False
        # MANAGED nodes only: system/CPU pools in the denominator would
        # dilute the fraction and let a bad rollout mass-delete every TPU
        # slice while the breaker reads "healthy enough"
        from ..apis import labels as wk
        nodes = await self.client.list(
            Node, labels={wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME})
        if not nodes:
            return False
        unhealthy = sum(1 for n in nodes if self._match_policy(n) is not None)
        return unhealthy / len(nodes) > self.opts.max_unhealthy_fraction
