"""Node health / auto-repair controller (V8) — slice-aware, flap-proof.

Watches Node conditions; when one matches a CloudProvider RepairPolicy and
has been unhealthy past its toleration, repairs the node by deleting the
owning NodeClaim so KAITO recreates it (vendor/.../controllers/node/health/
controller.go:106-183; flow §3.5 in SURVEY.md). This build extends the
reference's single-stable-condition force-delete into a repair state machine
built to survive the chaos/nodefaults.py fault profiles:

- **Hysteresis** — a per-node condition-history window: ``Ready`` flapping
  faster than the toleration *accrues* unhealthy score (N observed
  transitions inside W seconds == unhealthy) instead of resetting the
  toleration clock on every flip.
- **Observed-staleness anchoring** — a condition with no
  ``lastTransitionTime`` (or a second-truncated one) is judged by how long
  THIS controller has observed it unhealthy on its own monotonic clock
  (same idea as ``leaderelection._expired``), so such nodes are repaired
  instead of requeueing on the full toleration forever, and truncated
  timestamps can never fire a repair early.
- **Stale-heartbeat policy** — ``Ready.lastHeartbeatTime`` older than a
  bound is treated as the kubelet being dead even while ``Ready`` reads a
  stale ``True``; envtest has no node-lifecycle-controller to flip the
  condition to ``Unknown``, and a silently dead kubelet emits no watch
  events, so healthy nodes are re-polled on a requeue cadence while the
  bound is enabled.
- **RepairBudget** — token bucket on repairs/interval + max concurrent
  repairs + per-slice-group serialization, on top of the cluster
  unhealthy-fraction breaker (now DEFAULT ON, with a minimum-unhealthy
  count so a one-node fleet can still be repaired): a correlated failure
  wave (maintenance_wave) cannot mass-delete the fleet.
- **Drain-first escalation** — cordon + route pods through the termination
  controller's eviction path with a deadline (``BackoffLadder`` paces the
  drain polls); force-delete only once drained or the deadline expires.

Repair counters/durations accumulate module-side (``REPAIR_STATS``) and are
sampled into ``tpu_provisioner_repair_*`` at /metrics scrape time
(controllers/metrics.py) — this layer never imports prometheus.
"""

from __future__ import annotations

import asyncio
import logging
import math
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..apis.karpenter import NodeClaim
from ..apis.serde import now, wall_now
from ..providers.operations import BackoffLadder
from ..runtime import NotFoundError, Request, Result, probes
from ..runtime.client import Client, patch_retry
from ..runtime.events import Recorder
from .termination import drain_node, taint_disrupted
from .utils import nodeclaim_for_node

log = logging.getLogger("controllers.health")

# metav1.Time is second-resolution: any wall-clock age computed from a
# condition timestamp carries up to this much truncation error and must not
# fire a repair early on its own (same bound GC's leak grace applies).
_TRUNCATION_SLACK = 1.0

# ----------------------------------------------------------- repair metrics
# Sampled into tpu_provisioner_repair_* gauges + the duration histogram at
# scrape (controllers/metrics.update_runtime_gauges) — the convention every
# non-prometheus layer here uses (providers.cache.CACHE_STATS et al.).
REPAIR_STATS: dict[str, int] = defaultdict(int)
_REPAIR_DURATIONS: list[float] = []
_MAX_PENDING_DURATIONS = 4096


def record_repair_duration(seconds: float) -> None:
    if len(_REPAIR_DURATIONS) < _MAX_PENDING_DURATIONS:
        _REPAIR_DURATIONS.append(seconds)


def drain_repair_durations() -> list[float]:
    global _REPAIR_DURATIONS
    out, _REPAIR_DURATIONS = _REPAIR_DURATIONS, []
    return out


@dataclass
class HealthOptions:
    # Cluster-wide circuit breaker: skip repair when more than this fraction
    # of managed nodes is unhealthy. The reference comments its breaker out
    # (health/controller.go:130-151); here it DEFAULTS ON — for TPU fleets a
    # bad rollout or a maintenance wave marking many slices unhealthy at
    # once must not trigger a mass delete of expensive capacity. 0 disables.
    max_unhealthy_fraction: float = 0.5
    # The fraction alone would brick repair on tiny fleets (1/1 unhealthy is
    # 100%): the breaker can only trip when at least this many nodes are
    # unhealthy — below it, faults are independent hardware, not a wave.
    breaker_min_unhealthy: int = 3
    # Breaker verdict memo: a correlated wave has every sick node asking the
    # same cluster-wide question; one labeled-index list per TTL answers
    # them all instead of one list per repair decision.
    breaker_ttl: float = 1.0
    # Watch-age liveness bound (VERDICT r4 item 9): repair deletes
    # NodeClaims partly on a cached Node view (the breaker's list and
    # nodeclaim correlation); refuse repair when that cache hasn't observed
    # the apiserver within this bound. 0 disables.
    max_cache_age: float = 600.0
    # Hysteresis: this many observed condition transitions inside
    # flap_window seconds == unhealthy, regardless of the current status or
    # toleration clock. 0 disables (the pre-hysteresis behavior a flapping
    # node exploits — pinned by a regression test).
    flap_threshold: int = 5
    flap_window: float = 600.0
    # Stale-heartbeat repair: Ready.lastHeartbeatTime older than this bound
    # (plus truncation slack) == kubelet dead even though Ready reads True.
    # 0 disables — the safe default where a node-lifecycle-controller
    # already flips silent nodes to Unknown.
    heartbeat_bound: float = 0.0
    # Drain-first escalation: cordon + evict with this deadline; force-delete
    # only when drained or the deadline expires. 0 skips straight to the
    # force-delete (the reference's behavior).
    drain_deadline: float = 300.0
    drain_requeue: float = 2.0
    # RepairBudget: token bucket of repair_rate repairs per repair_interval
    # seconds (burst-capped), plus a cap on concurrently-active repairs.
    # 0 rate / 0 concurrency = unlimited; per-slice-group serialization is
    # always on (two repairs in one ICI group is never right).
    repair_rate: float = 0.0
    repair_interval: float = 3600.0
    repair_burst: int = 0
    max_concurrent_repairs: int = 0
    # Requeue cadence for throttled (budget/breaker-held) repairs.
    throttle_requeue: float = 5.0
    # Active-repair bookkeeping TTL: an entry whose node stopped producing
    # events (and never healed or vanished) must not pin its slice group
    # forever. 0 derives max(60, 4 × drain_deadline).
    repair_entry_ttl: float = 0.0

    def entry_ttl(self) -> float:
        return self.repair_entry_ttl or max(60.0, 4 * self.drain_deadline)


class RepairBudget:
    """Token bucket + concurrency cap + per-slice-group serialization.

    ``try_start`` either admits a repair (reserving the node's group) or
    returns a human-readable throttle reason; ``release`` frees the node's
    reservation when the repair completes, aborts, or its node vanishes.
    Time is injected (monotonic seconds) for deterministic unit tests.
    """

    def __init__(self, rate: float = 0.0, interval: float = 3600.0,
                 burst: int = 0, max_concurrent: int = 0):
        self.rate = rate
        self.interval = interval
        self.burst = burst if burst > 0 else max(1, math.ceil(rate or 1))
        self.max_concurrent = max_concurrent
        self._tokens = float(self.burst)
        self._last_refill: Optional[float] = None
        self.started_total = 0
        self.active: dict[str, str] = {}   # node -> group
        self._groups: dict[str, str] = {}  # group -> repairing node

    def _refill(self, mono: float) -> None:
        if self.rate <= 0:
            return
        if self._last_refill is not None:
            self._tokens = min(
                float(self.burst),
                self._tokens + (mono - self._last_refill) * self.rate / self.interval)
        self._last_refill = mono

    def try_start(self, node: str, group: str, mono: float) -> Optional[str]:
        if node in self.active:
            return None  # already holds its reservation (drain in progress)
        holder = self._groups.get(group)
        if holder is not None and holder != node:
            return f"slice group {group!r} already repairing node {holder!r}"
        if self.max_concurrent > 0 and len(self.active) >= self.max_concurrent:
            return (f"{len(self.active)} repairs in flight "
                    f"(max {self.max_concurrent})")
        self._refill(mono)
        if self.rate > 0 and self._tokens < 1.0:
            return (f"repair rate budget exhausted "
                    f"({self.rate:g}/{self.interval:.0f}s)")
        if self.rate > 0:
            self._tokens -= 1.0
        self.active[node] = group
        self._groups[group] = node
        self.started_total += 1
        return None

    def release(self, node: str) -> None:
        group = self.active.pop(node, None)
        if group is not None and self._groups.get(group) == node:
            self._groups.pop(group, None)


@dataclass
class _Repair:
    """One active repair: group reservation + drain-escalation ladder."""
    group: str
    started: float                      # monotonic
    ladder: BackoffLadder
    reason: str = ""
    deleted: bool = False               # force-delete issued; awaiting node GC


@dataclass
class _Diagnosis:
    reason: str                         # FlappingNode | StaleHeartbeat | <cond>
    detail: str
    due: bool
    requeue_after: float = 0.0


class NodeHealthController:
    NAME = "node.health"

    def __init__(self, client: Client, cloudprovider,
                 recorder: Optional[Recorder] = None,
                 options: Optional[HealthOptions] = None,
                 eviction=None, crashes=None):
        self.client = client
        self.cp = cloudprovider
        self.recorder = recorder
        self.opts = options or HealthOptions()
        # controllers/termination.EvictionQueue — the drain-first path; None
        # (unit constructions) degrades to treat every node as drained.
        self.eviction = eviction
        self.crashes = crashes
        self.budget = RepairBudget(
            rate=self.opts.repair_rate, interval=self.opts.repair_interval,
            burst=self.opts.repair_burst,
            max_concurrent=self.opts.max_concurrent_repairs)
        # the policy set is static per process: hoisted off the hot watch
        # path (every reconcile + every breaker refresh consults it); a
        # None cloudprovider (unit constructions) means no policies
        self._policies = (list(self.cp.repair_policies())
                          if self.cp is not None else [])
        self._watched = (frozenset(p.condition_type for p in self._policies)
                         | {"Ready"})
        # per-node observed state (all monotonic-clock, rebuilt from scratch
        # after a restart — observation is this incarnation's own)
        self._repairs: dict[str, _Repair] = {}
        self._last_status: dict[tuple[str, str], str] = {}
        self._transitions: dict[str, deque] = {}
        self._flapping: set[str] = set()
        # node -> uid last reconciled: repaired claims are recreated under
        # the SAME node names, and a delete event coalesced with the add in
        # the workqueue means no NotFound reconcile ever runs _forget — the
        # uid flip is what says "this is a different node, drop its history"
        self._node_uid: dict[str, str] = {}
        # (node, ctype, status) -> first-observed mono for conditions whose
        # timestamps can't be trusted; (node, "hb") for absent heartbeats
        self._observed_since: dict[tuple, float] = {}
        self._breaker_memo: Optional[tuple[float, bool]] = None

    # ------------------------------------------------------------ reconcile
    async def reconcile(self, req: Request) -> Result:
        mono = asyncio.get_event_loop().time()
        self._prune(mono)
        try:
            node = await self.client.get(Node, req.name)
        except NotFoundError:
            self._forget(req.name)
            return Result()
        if node.metadata.deletion_timestamp is not None:
            # teardown under way; the group reservation (if any) holds until
            # the node object is gone — that IS the serialization window
            return Result()

        uid = node.metadata.uid
        if uid:
            if self._node_uid.get(req.name, uid) != uid:
                self._forget(req.name)  # same-name replacement node
            self._node_uid[req.name] = uid

        self._observe(node, mono)
        self._reset_stale_anchors(node)
        diag = self._diagnose(node, mono)
        rep = self._repairs.get(req.name)

        if diag is None:
            if rep is not None and not rep.deleted:
                await self._abort_repair(node, rep)
            elif rep is None and any(t.key == wk.DISRUPTED_TAINT
                                     for t in node.spec.taints):
                # a wedged repair entry was pruned while the node was still
                # cordoned; the heal path above only runs while the entry
                # exists, so hand the capacity back here
                await self._uncordon(node.metadata.name)
            return self._healthy_result()
        if rep is not None and rep.deleted:
            return Result()  # claim delete issued; waiting out the node GC
        if not diag.due:
            # wakes: timer — waiting out the toleration deadline itself
            return Result(requeue_after=max(0.02, diag.requeue_after))

        if self._cache_too_stale():
            log.warning("repair of %s deferred: cached cluster view older "
                        "than %.0fs", node.metadata.name,
                        self.opts.max_cache_age)
            # wakes: timer — cache freshness recovers on its own clock
            return Result(requeue_after=self.opts.throttle_requeue)

        if await self._circuit_broken(mono):
            REPAIR_STATS["throttled"] += 1
            log.warning("repair of %s skipped: cluster unhealthy fraction "
                        "over limit", node.metadata.name)
            # wakes: timer — breaker TTL expiry, no event to subscribe to
            return Result(requeue_after=self.opts.throttle_requeue)

        nc = await nodeclaim_for_node(self.client, node)
        if nc is None or nc.metadata.deletion_timestamp is not None:
            if rep is not None and not rep.deleted:
                # the claim is already gone or tearing down — deletion IS
                # the repair; stop draining and wait out the node GC (the
                # group reservation holds until the node object vanishes,
                # which is the serialization window)
                rep.deleted = True
            return Result()

        if rep is None:
            why = self.budget.try_start(req.name, self._group_key(node), mono)
            if why is not None:
                REPAIR_STATS["throttled"] += 1
                log.info("repair of %s throttled: %s", req.name, why)
                # wakes: timer — budget tokens refill on the rate interval
                return Result(requeue_after=self.opts.throttle_requeue)
            rep = _Repair(
                group=self._group_key(node), started=mono,
                ladder=BackoffLadder(self.opts.drain_deadline or 0.0,
                                     max(self.opts.drain_requeue, 0.01)),
                reason=diag.reason)
            self._repairs[req.name] = rep
            REPAIR_STATS["started"] += 1
            probes.emit("repair-commit", req.name, reason=diag.reason,
                        group=rep.group)
            if diag.reason == "SpotPreempted":
                # Feed the placement engine's spot-zone demotion hysteresis:
                # enough preemptions inside the window and the engine sinks
                # this zone to the back of the spot candidate order, so the
                # replacement claim lands somewhere calmer. Lazy import —
                # controllers never depend on providers at module scope.
                from ..providers.placement import note_spot_preemption
                note_spot_preemption(
                    node.metadata.labels.get(wk.ZONE_LABEL, ""))
            log.info("repairing node %s (%s): %s", req.name, diag.reason,
                     diag.detail)
            if self.recorder is not None:
                await self.recorder.publish(
                    nc, "Normal", "NodeRepairStarted",
                    f"node {node.metadata.name} unhealthy ({diag.reason}): "
                    f"{diag.detail}; draining before replacement")

        # ---- drain-first escalation -----------------------------------
        await self._cordon(node)
        drained = True
        if self.eviction is not None and self.opts.drain_deadline > 0:
            drained = await drain_node(self.client, self.eviction, node)
        # cut line: cordon + budget token + queued evictions are in-memory
        # or cloud-invisible; the force-delete has not been issued
        self._crash("mid_repair", req.name)
        if not drained and not rep.ladder.expired():
            # wakes: timer — drain-ladder backoff; evictions emit no event
            return Result(requeue_after=rep.ladder.next_delay())

        log.info("repairing node %s: %s; %sdeleting nodeclaim %s",
                 node.metadata.name, diag.detail,
                 "" if drained else "drain deadline expired, ",
                 nc.metadata.name)
        if self.recorder is not None:
            await self.recorder.publish(
                nc, "Warning", "NodeRepair",
                f"node {node.metadata.name} unhealthy: {diag.detail}")
        try:
            await self.client.delete(NodeClaim, nc.metadata.name)
        except NotFoundError:
            pass  # someone beat us to it: not OUR force-delete
        else:
            REPAIR_STATS["succeeded"] += 1
            record_repair_duration(mono - rep.started)
            probes.emit("repair-success", req.name, reason=rep.reason,
                        duration=round(mono - rep.started, 4))
        rep.deleted = True
        return Result()

    def _healthy_result(self) -> Result:
        # a silently dead kubelet emits NO events — with the heartbeat bound
        # enabled, healthy nodes are re-polled so staleness is ever observed
        if self.opts.heartbeat_bound > 0:
            # wakes: timer — a silently dead kubelet emits nothing; polling
            # at half the bound is the only way staleness is ever observed
            return Result(requeue_after=max(0.05, self.opts.heartbeat_bound / 2))
        return Result()

    # ------------------------------------------------------------ diagnosis
    def _observe(self, node: Node, mono: float) -> None:
        """Record condition transitions for the hysteresis window. Observed
        status CHANGES are counted on this controller's monotonic clock —
        second-truncated (or reset) lastTransitionTimes can neither hide a
        flip nor double-count one."""
        if self.opts.flap_threshold <= 0:
            return
        name = node.metadata.name
        watched = self._watched
        trans = self._transitions.setdefault(
            name, deque(maxlen=4 * max(self.opts.flap_threshold, 1)))
        for c in node.status.conditions:
            if c.type not in watched:
                continue
            key = (name, c.type)
            prev = self._last_status.get(key)
            self._last_status[key] = c.status
            if prev is not None and prev != c.status:
                trans.append(mono)
        while trans and mono - trans[0] > self.opts.flap_window:
            trans.popleft()
        if len(trans) >= self.opts.flap_threshold:
            if name not in self._flapping:
                self._flapping.add(name)
                REPAIR_STATS["flap_detections"] += 1
                log.warning(
                    "node %s is flapping: %d condition transitions inside "
                    "%.0fs (threshold %d)", name, len(trans),
                    self.opts.flap_window, self.opts.flap_threshold)
        else:
            self._flapping.discard(name)

    def _diagnose(self, node: Node, mono: float) -> Optional[_Diagnosis]:
        name = node.metadata.name
        # 1. hysteresis verdict: flapping IS unhealthy, toleration already
        #    paid in transitions — even if the current status reads True
        if name in self._flapping:
            return _Diagnosis(
                reason="FlappingNode", due=True,
                detail=f"{len(self._transitions.get(name, ()))} condition "
                       f"transitions inside {self.opts.flap_window:.0f}s")
        # 2. stable policy match with truncation-robust toleration
        match = self._match_policy(node)
        if match is not None:
            cond, policy = match
            anchor_key = (name, cond.type, cond.status)
            anchor = self._observed_since.setdefault(anchor_key, mono)
            observed = mono - anchor
            tol = policy.toleration_duration
            due = observed >= tol
            remaining = tol - observed
            if cond.last_transition_time is not None:
                # label age overshoots real age by up to the truncation
                # slack — subtract it so a fresh flip can't fire early; the
                # observed-for anchor covers the small-toleration regime
                elapsed = (now() - cond.last_transition_time).total_seconds()
                if elapsed - _TRUNCATION_SLACK > tol:
                    due = True
                remaining = min(remaining,
                                tol + _TRUNCATION_SLACK - elapsed)
            return _Diagnosis(
                reason=cond.type, due=due, requeue_after=remaining,
                detail=f"{cond.type}={cond.status} "
                       f"(observed {observed:.1f}s, toleration {tol:.0f}s)")
        # 3. stale heartbeat: Ready reads True but the kubelet stopped
        #    reporting — envtest has no node-lifecycle-controller to flip it
        stale = self._heartbeat_stale(node, mono)
        if stale is not None:
            return _Diagnosis(reason="StaleHeartbeat", due=True, detail=stale)
        # healthy: clear CONDITION anchors (the 3-tuples) so a future
        # unhealthy spell starts fresh. The (name, "hb") anchor is NOT
        # condition state and must survive healthy passes — it is how long
        # we've waited for a first heartbeat, and popping it here would
        # restart that clock every reconcile so the bound could never
        # elapse for a kubelet that died before its first report
        # (_heartbeat_stale pops it itself once a heartbeat appears).
        for key in [k for k in self._observed_since
                    if k[0] == name and len(k) == 3]:
            self._observed_since.pop(key, None)
        return None

    def _reset_stale_anchors(self, node: Node) -> None:
        """An observed-unhealthy-for anchor is only meaningful while its
        (condition, status) pair is still CURRENT: any transition restarts
        the clock — which is precisely why plain anchoring cannot catch a
        flapping node and the hysteresis window exists."""
        name = node.metadata.name
        for c in node.status.conditions:
            for status in ("True", "False", "Unknown"):
                if status != c.status:
                    self._observed_since.pop((name, c.type, status), None)

    def _heartbeat_stale(self, node: Node, mono: float,
                         observe: bool = True) -> Optional[str]:
        """``observe=False`` is a side-effect-free view for the breaker: it
        neither plants nor clears anchors, so counting the fleet can't
        perturb per-node diagnosis state."""
        bound = self.opts.heartbeat_bound
        if bound <= 0:
            return None
        cond = node.ready_condition()
        if cond is None or cond.status != "True":
            return None
        name = node.metadata.name
        if cond.last_heartbeat_time is None:
            # never seen a heartbeat: anchor at first observation — the
            # observed-staleness idea again, so a kubelet that died before
            # its first report is still caught
            if observe:
                anchor = self._observed_since.setdefault((name, "hb"), mono)
            else:
                anchor = self._observed_since.get((name, "hb"))
                if anchor is None:
                    return None
            if mono - anchor > bound:
                return (f"no kubelet heartbeat observed for "
                        f"{mono - anchor:.1f}s (bound {bound:.0f}s)")
            return None
        if observe:
            self._observed_since.pop((name, "hb"), None)
        age = (wall_now() - cond.last_heartbeat_time).total_seconds()
        if age > bound + _TRUNCATION_SLACK:
            return (f"kubelet heartbeat is {age:.1f}s old "
                    f"(bound {bound:.0f}s); Ready is stale")
        return None

    def _match_policy(self, node: Node):
        for policy in self._policies:
            for c in node.status.conditions:
                if c.type == policy.condition_type and c.status == policy.condition_status:
                    return c, policy
        return None

    # ------------------------------------------------------------- plumbing
    def _group_key(self, node: Node) -> str:
        """Serialization domain: the multi-slice group when the node is in
        one, else its pool — two concurrent repairs inside one ICI domain
        is never right (and same-pool serialization is what keeps two sick
        hosts of one slice from double-deleting their shared claim)."""
        labels = node.metadata.labels
        return (labels.get(wk.TPU_SLICE_GROUP_LABEL)
                or labels.get(wk.TPU_SLICE_ID_LABEL)
                or labels.get(wk.GKE_NODEPOOL_LABEL)
                or node.metadata.name)

    async def _cordon(self, node: Node) -> None:
        def mutate(n: Node):
            if n.spec.unschedulable:
                return False
            n.spec.unschedulable = True
        await patch_retry(self.client, Node, node.metadata.name, mutate)
        await taint_disrupted(self.client, node)

    async def _uncordon(self, name: str) -> None:
        def mutate(n: Node):
            changed = n.spec.unschedulable
            n.spec.unschedulable = False
            before = len(n.spec.taints)
            n.spec.taints = [t for t in n.spec.taints
                             if t.key != wk.DISRUPTED_TAINT]
            return None if changed or len(n.spec.taints) != before else False
        try:
            await patch_retry(self.client, Node, name, mutate)
        except NotFoundError:
            pass

    async def _abort_repair(self, node: Node, rep: _Repair) -> None:
        """The node healed mid-drain (flap ended, maintenance cancelled):
        uncordon and hand the capacity back instead of finishing the kill."""
        log.info("aborting repair of %s (%s): node recovered",
                 node.metadata.name, rep.reason)
        await self._uncordon(node.metadata.name)
        self._repairs.pop(node.metadata.name, None)
        self.budget.release(node.metadata.name)
        if self.recorder is not None:
            nc = await nodeclaim_for_node(self.client, node)
            if nc is not None:
                await self.recorder.publish(
                    nc, "Normal", "NodeRepairAborted",
                    f"node {node.metadata.name} recovered ({rep.reason}); "
                    "drain aborted, node uncordoned")

    def _forget(self, name: str) -> None:
        self._repairs.pop(name, None)
        self.budget.release(name)
        self._transitions.pop(name, None)
        self._flapping.discard(name)
        self._node_uid.pop(name, None)
        for key in [k for k in self._last_status if k[0] == name]:
            self._last_status.pop(key, None)
        for key in [k for k in self._observed_since if k[0] == name]:
            self._observed_since.pop(key, None)

    def _prune(self, mono: float) -> None:
        """Drop repair entries whose node stopped producing events without
        ever healing or vanishing — a wedged entry must not pin its slice
        group (and a budget slot) forever."""
        ttl = self.opts.entry_ttl()
        for name, rep in list(self._repairs.items()):
            if mono - rep.started > ttl:
                log.warning("repair entry for %s older than %.0fs; releasing",
                            name, ttl)
                self._repairs.pop(name, None)
                self.budget.release(name)

    def _crash(self, point: str, key: str) -> None:
        if self.crashes is not None:
            self.crashes.hit(point, key)

    def _cache_too_stale(self) -> bool:
        """A destructive decision must not act on a cache the watch stopped
        feeding — see GCOptions.max_cache_age for the rationale."""
        from .gc import _cache_age
        if self.opts.max_cache_age <= 0:
            return False
        return _cache_age(self.client, Node) > self.opts.max_cache_age

    async def _circuit_broken(self, mono: Optional[float] = None) -> bool:
        if self.opts.max_unhealthy_fraction <= 0:
            return False
        mono = mono if mono is not None else asyncio.get_event_loop().time()
        # Memoized for breaker_ttl: during a correlated wave every sick node
        # reconciles at once and each asked this cluster-wide question with
        # its own Node list — one answer per TTL serves the whole wave.
        if (self._breaker_memo is not None
                and mono - self._breaker_memo[0] < self.opts.breaker_ttl):
            return self._breaker_memo[1]
        # MANAGED nodes only, via the label inverted index (store and
        # informer both serve this without a full scan): system/CPU pools in
        # the denominator would dilute the fraction and let a bad rollout
        # mass-delete every TPU slice while the breaker reads "healthy
        # enough".
        nodes = await self.client.list(
            Node, labels={wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME})
        # The numerator must see every diagnosis class, not just stable
        # condition matches: flapping and silently-dead nodes both read
        # Ready=True at list time, and a fleet-wide flap storm or heartbeat
        # blackout is exactly the correlated wave the breaker exists for.
        unhealthy = sum(
            1 for n in nodes
            if n.metadata.name in self._flapping
            or self._match_policy(n) is not None
            or self._heartbeat_stale(n, mono, observe=False) is not None)
        tripped = bool(
            nodes
            and unhealthy >= max(1, self.opts.breaker_min_unhealthy)
            and unhealthy / len(nodes) > self.opts.max_unhealthy_fraction)
        was = self._breaker_memo[1] if self._breaker_memo else False
        self._breaker_memo = (mono, tripped)
        if tripped and not was:
            # Transition INTO tripped only — the memoized steady state would
            # otherwise re-fire the flight-recorder trigger every TTL.
            probes.emit("repair-breaker-trip", "cluster",
                        unhealthy=unhealthy, nodes=len(nodes),
                        fraction=round(unhealthy / len(nodes), 4))
        return tripped
