"""NodeClaim lifecycle controller: the provisioning state machine (V2-V5).

Re-creates the active behavior of the reference's patched
vendor/.../controllers/nodeclaim/lifecycle/: add the termination finalizer
before launch (controller.go:134-144), run Launch → Registration →
Initialization sub-reconcilers (controller.go:149-157), and on deletion run
the finalize flow — delete the slice's Node objects, call
CloudProvider.Delete, mark InstanceTerminating, requeue every 5s until the
cloud reports NotFound, then drop the finalizer and emit termination metrics
(controller.go:183-268).

Deliberate departures, per SURVEY.md §7 step 5:
- Liveness timeouts are ENABLED by default (the reference comments them out,
  controller.go:156) but with TPU-appropriate budgets — a multi-host slice
  create can legitimately exceed the reference's 5-minute launch budget.
- Multi-host: registration requires *all* hosts' Node objects (with
  consistent worker indices) and syncs labels/taints/finalizer/owner-refs
  onto every node of the slice; initialization requires every host Ready
  with its TPU chips registered by the device plugin
  (initialization.go:119-134 generalized per-host).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from dataclasses import dataclass, field
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node, Taint
from ..apis.karpenter import (
    INITIALIZED, INSTANCE_TERMINATING, LAUNCHED, NodeClaim, REGISTERED,
)
from ..apis.meta import OwnerReference
from ..apis.serde import fmt_time, now
from ..errors import (
    CreateError, InsufficientCapacityError, NodeClaimNotFoundError,
    NodeClassNotReadyError, REASON_CREATE_IN_PROGRESS, reason_is_terminal,
)
from ..providers.operations import loop_now
from ..runtime import NotFoundError, Request, Result
from ..runtime.client import Client, patch_retry
from ..runtime.wakehub import SOURCE_LRO, SOURCE_NODE
from .statusbatch import write_claim_patches
from ..runtime.events import Recorder
from ..scheduling import merge_taints, remove_taint
from .metrics import (
    CHIPS_PROVISIONED, NODECLAIMS_CREATED, NODECLAIMS_TERMINATED,
    PROVISION_DURATION, TERMINATION_DURATION,
)
from .utils import expected_hosts, is_managed, parse_duration, slice_nodes

log = logging.getLogger("controllers.lifecycle")


@dataclass
class LifecycleOptions:
    # Reference values: 5 min launch / 15 min registration, disabled
    # (liveness.go:46-52, controller.go:156). Enabled here, sized for slices.
    liveness_enabled: bool = True
    launch_timeout: float = 30 * 60
    registration_timeout: float = 40 * 60
    termination_requeue: float = 5.0        # controller.go:246
    registration_requeue: float = 2.0
    launch_cache_ttl: float = 3600.0        # controller.go:81 (1h)
    # Requeue cadence while a tracked create LRO is in flight
    # (CreateError reason=CreateInProgress). A safety net, not the wake
    # mechanism: the operation tracker injects the claim back into the
    # workqueue the tick its operation completes — this only bounds how
    # long a claim can sit if that injection is ever missed.
    inprogress_requeue: float = 5.0
    # StatusWriteBatcher flush window (seconds). Read by the boot path /
    # envtest when constructing the batcher; 0 disables batching (every
    # _flush_status writes directly, the pre-batcher behavior).
    status_flush_window: float = 0.05


@dataclass
class _CacheEntry:
    created: NodeClaim
    at: float = field(default_factory=loop_now)


class NodeClaimLifecycleController:
    NAME = "nodeclaim.lifecycle"

    def __init__(self, client: Client, cloudprovider, recorder: Optional[Recorder] = None,
                 options: Optional[LifecycleOptions] = None, tracer=None,
                 status_batcher=None):
        self.client = client
        self.cp = cloudprovider
        self.recorder = recorder
        # claimtrace tracer (duck-typed, optional): status-write spans +
        # the launched/registered/ready annotations the critical-path
        # analyzer keys off.
        self.tracer = tracer
        # StatusWriteBatcher (optional): _flush_status submits into its
        # window instead of writing; None = direct writes (tests, window=0).
        self.batcher = status_batcher
        self.opts = options or LifecycleOptions()
        # Launch idempotence cache by UID: survives duplicate reconciles when
        # the status write raced (launch.go:64-74).
        self._launched: dict[str, _CacheEntry] = {}

    async def _publish(self, obj, etype, reason, message):
        if self.recorder is not None:
            await self.recorder.publish(obj, etype, reason, message)

    def _annotate(self, claim: str, event: str, **attrs) -> None:
        if self.tracer is not None:
            self.tracer.annotate(claim, event, **attrs)

    def _span(self, claim: str, name: str, **attrs):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(claim, name, **attrs)

    # ------------------------------------------------------------ reconcile
    async def reconcile(self, req: Request) -> Result:
        try:
            nc = await self.client.get(NodeClaim, req.name)
        except NotFoundError:
            self._gc_cache()
            return Result()
        if not is_managed(nc):
            return Result()
        if self.batcher is not None:
            # Read-your-batched-writes: a reconcile inside the flush window
            # must see its predecessor's (still pending) status or it will
            # redo sub-reconciler work against pre-batch conditions.
            nc = self.batcher.overlay(nc)
        if self.tracer is not None:
            attrs = {"uid": nc.metadata.uid}
            group = nc.metadata.labels.get(wk.TPU_SLICE_GROUP_LABEL)
            if group:
                attrs["slice_group"] = group
            self.tracer.set_trace_attrs(nc.metadata.name, **attrs)
        if nc.metadata.deletion_timestamp is not None:
            return await self._finalize(nc)

        if wk.TERMINATION_FINALIZER not in nc.metadata.finalizers:
            # Finalizer must land before launch (controller.go:134-144).
            def add_finalizer(obj):
                if wk.TERMINATION_FINALIZER in obj.metadata.finalizers:
                    return False
                obj.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
            nc = await patch_retry(self.client, NodeClaim, req.name, add_finalizer)
            if nc is None:
                return Result()

        # All sub-reconcilers run even when one errors (the reference
        # aggregates errors with multierr, controller.go:149-157) — liveness
        # must still fire while launch is failing.
        requeues: list[tuple[float, Optional[str]]] = []
        preserve = False
        error: Optional[Exception] = None
        for sub in (self._launch, self._registration, self._initialization,
                    self._liveness):
            try:
                res = await sub(nc)
            except (asyncio.CancelledError,):
                raise
            except Exception as e:  # noqa: BLE001 — error still flushes status
                error = error or e
                continue
            if res is None:
                return Result()  # nodeclaim was deleted by the sub-reconciler
            if res.requeue_after is not None:
                requeues.append((res.requeue_after, res.wake_source))
            preserve = preserve or res.preserve_failures
        await self._flush_status(nc)
        if error is not None:
            raise error
        if not requeues:
            return Result(preserve_failures=preserve)
        after, source = min(requeues, key=lambda p: p[0])
        # The min park's wake source survives the fold so the controller
        # can skip its safety-net arm — but an UN-sourced deadline folded
        # above it (the liveness budget: nothing but a timer can end that
        # wait) must still be armed, or the skip would silently disable the
        # liveness enforcement clock.
        fallback = None
        if source is not None:
            unsourced = [a for a, s in requeues if s is None]
            if unsourced:
                fallback = min(unsourced)
        # wakes: aggregate — min of the sub-reconcilers' annotated waits
        # provgraph: disable=PG002 — 'aggregate' is not a wake SOURCE: each
        # folded requeue_after carries its own `# wakes:` annotation at the
        # sub-reconciler site, and those are the edges PG002 checks; this
        # line only documents the min() fold
        return Result(requeue_after=after, preserve_failures=preserve,
                      wake_source=source, fallback_after=fallback)

    async def _flush_status(self, nc: NodeClaim, direct: bool = False) -> None:
        """Persist ``nc``'s meta+status. With a batcher, submit into its
        flush window (latest-wins coalescing); ``direct=True`` bypasses the
        window — used by terminal paths that delete the claim immediately
        after, where a delayed flush would race the delete — and drops any
        pending snapshot so a stale batch cannot land after the direct
        write. The write itself (no-op suppression, additive meta merge,
        meta-before-status ordering) lives in
        ``statusbatch.write_claim_patches``, shared with the batcher."""
        if self.batcher is not None:
            if not direct:
                await self.batcher.submit(nc)
                return
            self.batcher.drop(nc.metadata.name)
        await write_claim_patches(self.client, nc, tracer=self.tracer)

    # --------------------------------------------------------------- launch
    async def _launch(self, nc: NodeClaim) -> Optional[Result]:
        cs = nc.status_conditions
        if cs.is_true(LAUNCHED):
            return Result()

        cached = self._launched.get(nc.metadata.uid)
        if cached is not None:
            created = cached.created
        else:
            try:
                created = await self.cp.create(nc)
            except (InsufficientCapacityError, NodeClassNotReadyError) as e:
                # Terminal: delete the NodeClaim; KAITO recreates with a new
                # shape if it wants (launch.go:84-109).
                log.warning("nodeclaim %s launch terminal failure: %s",
                            nc.metadata.name, e)
                await self._publish(nc, "Warning", type(e).__name__, str(e))
                cs.set_false(LAUNCHED, type(e).__name__, str(e))
                await self._flush_status(nc, direct=True)
                try:
                    await self.client.delete(NodeClaim, nc.metadata.name)
                except NotFoundError:
                    pass
                return None
            except CreateError as e:
                cs.set_false(LAUNCHED, e.reason, str(e))
                if reason_is_terminal(e.reason):
                    # Terminal verdict from the create path itself (e.g.
                    # Stockout after the placement walk exhausted every
                    # candidate): retrying cannot succeed, so take the same
                    # exit as InsufficientCapacityError above — Event, flush,
                    # delete the claim, let KAITO re-shape if it wants.
                    log.warning("nodeclaim %s launch terminal failure (%s): %s",
                                nc.metadata.name, e.reason, e)
                    await self._publish(nc, "Warning", e.reason, str(e))
                    await self._flush_status(nc, direct=True)
                    try:
                        await self.client.delete(NodeClaim, nc.metadata.name)
                    except NotFoundError:
                        pass
                    return None
                if e.reason == REASON_CREATE_IN_PROGRESS:
                    # Non-blocking provisioning: the operation tracker owns
                    # the wait — this is progress, not failure. Requeue at
                    # the in-progress cadence (no failure counter accrues,
                    # no backoff ladder climbs) and let the tracker's
                    # completion injection wake the claim the moment the
                    # LRO resolves. preserve_failures: the lap must not
                    # FORGET history either — a create that keeps landing
                    # ERROR alternates fail→re-register, and wiping the
                    # counter each lap would pin its retry cadence flat
                    # instead of climbing the ladder.
                    # wakes: lro — tracker completion via the WakeHub
                    return Result(requeue_after=self.opts.inprogress_requeue,
                                  preserve_failures=True,
                                  wake_source=SOURCE_LRO)
                # Other transient reasons (NodesNotReady, QueuedProvisioning)
                # deliberately take the workqueue's exponential error backoff:
                # at fleet scale it is the self-stabilizing mechanism — a
                # fixed retry cadence was measured to keep a 512-claim wave
                # saturated indefinitely.
                raise
            self._launched[nc.metadata.uid] = _CacheEntry(created)

        # Populate labels + status from the cloud view (launch.go:75-77,130-141).
        for k, v in created.metadata.labels.items():
            nc.metadata.labels.setdefault(k, v)
        nc.status.provider_id = created.status.provider_id
        nc.status.image_id = created.status.image_id
        if created.status.capacity:
            nc.status.capacity = created.status.capacity
        cs.set_true(LAUNCHED, "Launched")
        self._annotate(nc.metadata.name, "launched")
        NODECLAIMS_CREATED.labels(self.cp.name()).inc()
        return Result()

    # --------------------------------------------------------- registration
    async def _registration(self, nc: NodeClaim) -> Optional[Result]:
        cs = nc.status_conditions
        if not cs.is_true(LAUNCHED):
            cs.set_unknown(REGISTERED)
            return Result()
        if cs.is_true(REGISTERED):
            return Result()

        hosts = expected_hosts(nc)
        nodes = [n for n in await slice_nodes(self.client, nc.metadata.name)
                 if n.spec.provider_id]
        if len(nodes) < hosts:
            cs.set_false(REGISTERED, "AwaitingNodes",
                         f"{len(nodes)}/{hosts} slice nodes present")
            # wakes: node — Node watch source wakes the claim on arrival
            return Result(requeue_after=self.opts.registration_requeue,
                          wake_source=SOURCE_NODE)

        for node in nodes:
            await self._sync_node(nc, node)

        worker0 = min(nodes, key=_worker_index)
        nc.status.node_name = worker0.metadata.name
        if not nc.status.provider_id:
            nc.status.provider_id = worker0.spec.provider_id
        cs.set_true(REGISTERED, "Registered")
        self._annotate(nc.metadata.name, "registered", hosts=hosts)
        return Result()

    async def _sync_node(self, nc: NodeClaim, node: Node) -> None:
        """Merge NodeClaim identity onto a slice node: managed labels, taints,
        finalizer, owner-ref; drop the unregistered taint
        (registration.go:96-147)."""
        def mutate(n: Node):
            changed = False
            for k, v in nc.metadata.labels.items():
                domain = k.split("/")[0]
                managed = any(domain == d or domain.endswith("." + d)
                              for d in wk.MANAGED_LABEL_DOMAINS)
                if managed and n.metadata.labels.get(k) != v:
                    n.metadata.labels[k] = v
                    changed = True
            desired = merge_taints(n.spec.taints, nc.spec.taints)
            desired = remove_taint(desired, wk.UNREGISTERED_TAINT)
            if [t.__dict__ for t in desired] != [t.__dict__ for t in n.spec.taints]:
                n.spec.taints = desired
                changed = True
            if wk.TERMINATION_FINALIZER not in n.metadata.finalizers:
                n.metadata.finalizers.append(wk.TERMINATION_FINALIZER)
                changed = True
            if not any(o.uid == nc.metadata.uid for o in n.metadata.owner_references):
                n.metadata.owner_references.append(OwnerReference(
                    api_version=NodeClaim.API_VERSION, kind=NodeClaim.KIND,
                    name=nc.metadata.name, uid=nc.metadata.uid, controller=True,
                    block_owner_deletion=True))
                changed = True
            return None if changed else False
        await patch_retry(self.client, Node, node.metadata.name, mutate)

    # ------------------------------------------------------- initialization
    async def _initialization(self, nc: NodeClaim) -> Optional[Result]:
        cs = nc.status_conditions
        if not cs.is_true(REGISTERED):
            cs.set_unknown(INITIALIZED)
            return Result()
        if cs.is_true(INITIALIZED):
            return Result()

        hosts = expected_hosts(nc)
        nodes = await slice_nodes(self.client, nc.metadata.name)
        not_ready = [n.metadata.name for n in nodes if not n.is_ready()]
        if len(nodes) < hosts or not_ready:
            cs.set_false(INITIALIZED, "NodesNotReady",
                         f"waiting on {not_ready or 'missing nodes'}")
            # wakes: node — readiness flips arrive on the Node watch
            return Result(requeue_after=self.opts.registration_requeue,
                          wake_source=SOURCE_NODE)

        startup_tainted = [n.metadata.name for n in nodes
                           if _has_startup_taints(n, nc)]
        if startup_tainted:
            cs.set_false(INITIALIZED, "StartupTaintsPresent",
                         f"startup taints on {startup_tainted}")
            # wakes: node — taint removal arrives on the Node watch
            return Result(requeue_after=self.opts.registration_requeue,
                          wake_source=SOURCE_NODE)

        missing = [n.metadata.name for n in nodes if not _tpu_registered(n)]
        if missing:
            # Device plugin hasn't registered google.com/tpu yet — the analog
            # of waiting for nvidia.com/gpu (initialization.go:119-134).
            cs.set_false(INITIALIZED, "ResourcesNotRegistered",
                         f"google.com/tpu not registered on {missing}")
            # wakes: node — device-plugin registration is a Node update
            return Result(requeue_after=self.opts.registration_requeue,
                          wake_source=SOURCE_NODE)

        cs.set_true(INITIALIZED, "Initialized")
        self._annotate(nc.metadata.name, "ready")
        self._observe_provision(nc)
        return Result()

    def _observe_provision(self, nc: NodeClaim) -> None:
        created = nc.metadata.creation_timestamp
        if created is not None:
            PROVISION_DURATION.labels(
                self.cp.name(),
                nc.metadata.labels.get(wk.INSTANCE_TYPE_LABEL, "unknown"),
            ).observe((now() - created).total_seconds())
        chips = nc.metadata.labels.get(wk.TPU_CHIPS_LABEL)
        gen = nc.metadata.labels.get(wk.TPU_ACCELERATOR_LABEL, "unknown")
        if chips and chips.isdigit():
            CHIPS_PROVISIONED.labels(gen).inc(int(chips))

    # ------------------------------------------------------------- liveness
    async def _liveness(self, nc: NodeClaim) -> Optional[Result]:
        """Launch/registration deadlines (liveness.go:46-67) — flag-gated and
        generous instead of disabled (SURVEY.md §7 step 5)."""
        if not self.opts.liveness_enabled:
            return Result()
        cs = nc.status_conditions
        created = nc.metadata.creation_timestamp
        if created is None or cs.is_true(INITIALIZED):
            return Result()
        age = (now() - created).total_seconds()
        if not cs.is_true(LAUNCHED):
            budget = self.opts.launch_timeout
        elif not cs.is_true(REGISTERED):
            budget = self.opts.registration_timeout
        else:
            return Result()
        if age > budget:
            log.warning("nodeclaim %s liveness expired after %.0fs; deleting",
                        nc.metadata.name, age)
            await self._publish(nc, "Warning", "LivenessTimeout",
                                f"not ready after {int(age)}s")
            try:
                await self.client.delete(NodeClaim, nc.metadata.name)
            except NotFoundError:
                pass
            return None
        # wakes: timer — a liveness deadline IS the timer; nothing else
        # can end this wait early (progress cancels it via the other subs)
        return Result(requeue_after=max(1.0, budget - age))

    # ------------------------------------------------------------- finalize
    async def _finalize(self, nc: NodeClaim) -> Result:
        if wk.TERMINATION_FINALIZER not in nc.metadata.finalizers:
            return Result()
        cs = nc.status_conditions

        self._annotate_termination_deadline(nc)

        # Delete the slice's Node objects; the node-termination controller
        # drains them behind their own finalizer (controller.go:197-215).
        # Deliberately NOT gated on: the instance delete below proceeds in
        # parallel with the drain — drain races cloud teardown by design, and
        # gating either on the other would deadlock (the node finalizer only
        # drops once the instance is gone).
        for n in await slice_nodes(self.client, nc.metadata.name):
            if n.metadata.deletion_timestamp is None:
                try:
                    await self.client.delete(Node, n.metadata.name)
                except NotFoundError:
                    pass

        try:
            await self.cp.delete(nc)
            changed = cs.set_true(INSTANCE_TERMINATING, "InstanceTerminating")
            if changed:
                await self._flush_status(nc)
            # wakes: lro — the queued cloud delete completes via the tracker
            return Result(requeue_after=self.opts.termination_requeue)
        except NodeClaimNotFoundError:
            pass  # instance gone

        # Hold the finalizer until the slice's Node objects are fully gone so
        # nodeclaim_for_node keeps resolving during node teardown.
        if await slice_nodes(self.client, nc.metadata.name):
            # wakes: node — node deletion events arrive on the Node watch
            return Result(requeue_after=min(1.0, self.opts.termination_requeue))

        def drop_finalizer(obj):
            if wk.TERMINATION_FINALIZER not in obj.metadata.finalizers:
                return False
            obj.metadata.finalizers.remove(wk.TERMINATION_FINALIZER)
        await patch_retry(self.client, NodeClaim, nc.metadata.name, drop_finalizer)
        self._annotate(nc.metadata.name, "terminated")
        NODECLAIMS_TERMINATED.labels(self.cp.name()).inc()
        if nc.metadata.deletion_timestamp is not None:
            TERMINATION_DURATION.labels(self.cp.name()).observe(
                (now() - nc.metadata.deletion_timestamp).total_seconds())
        self._launched.pop(nc.metadata.uid, None)
        return Result()

    def _annotate_termination_deadline(self, nc: NodeClaim) -> None:
        """Stamp the drain deadline from spec.terminationGracePeriod
        (controller.go:269-283)."""
        grace = parse_duration(nc.spec.termination_grace_period)
        if grace is None or wk.TERMINATION_TIMESTAMP_ANNOTATION in nc.metadata.annotations:
            return
        from datetime import timedelta
        deadline = nc.metadata.deletion_timestamp + timedelta(seconds=grace)
        nc.metadata.annotations[wk.TERMINATION_TIMESTAMP_ANNOTATION] = fmt_time(deadline)

    def _gc_cache(self) -> None:
        cutoff = loop_now() - self.opts.launch_cache_ttl
        self._launched = {k: v for k, v in self._launched.items() if v.at > cutoff}


def _worker_index(node: Node) -> int:
    try:
        return int(node.metadata.labels.get(wk.TPU_WORKER_INDEX_LABEL, "0"))
    except ValueError:
        return 0


def _has_startup_taints(node: Node, nc: NodeClaim) -> bool:
    return any(any(t.matches(st) for st in nc.spec.startup_taints)
               for t in node.spec.taints)


def _tpu_registered(node: Node) -> bool:
    if node.metadata.labels.get(wk.KAITO_MACHINE_TYPE_LABEL) != "tpu":
        return True  # non-TPU nodes have no extended resource to wait for
    try:
        return int(node.status.allocatable.get(wk.TPU_RESOURCE_NAME, "0")) > 0
    except ValueError:
        return False
