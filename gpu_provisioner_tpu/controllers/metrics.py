"""Controller-level Prometheus metrics.

Name-compatible with the reference's nodeclaim metrics
(vendor/sigs.k8s.io/karpenter/pkg/metrics/metrics.go:33-60 and
lifecycle/controller.go:249-266), plus a provision-duration histogram — the
headline NodeClaim→Ready latency from BASELINE.json that the reference never
measured — and the robustness surface: reconcile deadline/retry-exhaustion
counters, workqueue depth/backlog gauges, and circuit-breaker state
(refreshed from live objects by ``update_runtime_gauges`` at scrape time).
"""

from prometheus_client import REGISTRY, Counter, Gauge, Histogram

from ..providers import operations as ops
from ..providers.cache import CACHE_STATS, CLOUD_CALLS
from ..transport import BREAKER_HALF_OPEN, BREAKER_OPEN, BREAKERS


def _get_or_create(cls, name, doc, labelnames, **kw):
    try:
        return cls(name, doc, labelnames, **kw)
    except ValueError:
        return REGISTRY._names_to_collectors[name]


NODECLAIMS_CREATED = _get_or_create(
    Counter, "karpenter_nodeclaims_created_total",
    "NodeClaims launched, by provider.", ["provider"])

NODECLAIMS_TERMINATED = _get_or_create(
    Counter, "karpenter_nodeclaims_terminated_total",
    "NodeClaims terminated, by provider.", ["provider"])

TERMINATION_DURATION = _get_or_create(
    Histogram, "karpenter_nodeclaims_termination_duration_seconds",
    "Time from deletion request to finalizer removal.", ["provider"],
    buckets=(1, 5, 15, 30, 60, 120, 300, 600, 1800))

PROVISION_DURATION = _get_or_create(
    Histogram, "karpenter_nodeclaims_provision_duration_seconds",
    "Time from NodeClaim creation to Initialized (NodeClaim→Ready).",
    ["provider", "instance_type"],
    buckets=(5, 15, 30, 60, 120, 180, 300, 420, 600, 900))

CHIPS_PROVISIONED = _get_or_create(
    Counter, "tpu_chips_provisioned_total",
    "Total TPU chips brought to Ready.", ["generation"])

# ---------------------------------------------------------------- robustness

RECONCILE_TIMEOUTS = _get_or_create(
    Counter, "tpu_provisioner_reconcile_timeouts_total",
    "Reconciles cancelled at the per-reconcile deadline.", ["controller"])

RECONCILE_RETRIES_EXHAUSTED = _get_or_create(
    Counter, "tpu_provisioner_reconcile_retries_exhausted_total",
    "Items that hit the per-item retry bound and degraded to slow retry.",
    ["controller"])

RECONCILE_DURATION = _get_or_create(
    Histogram, "tpu_provisioner_reconcile_duration_seconds",
    "Per-reconcile wall time by controller (success and failure alike).",
    ["controller"],
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 15, 60))

# Reconcile durations are reported from the runtime metrics hook (that layer
# never imports prometheus) and buffered here until the next scrape drains
# them into RECONCILE_DURATION — the OPERATION_WAIT idiom. Bounded: under
# scrape starvation the buffer drops the oldest samples rather than growing.
_MAX_PENDING_DURATIONS = 4096
_pending_reconcile_durations: list[tuple[str, float]] = []


def record_reconcile_duration(controller: str, seconds: float) -> None:
    _pending_reconcile_durations.append((controller, seconds))
    if len(_pending_reconcile_durations) > _MAX_PENDING_DURATIONS:
        del _pending_reconcile_durations[:_MAX_PENDING_DURATIONS // 2]


def drain_reconcile_durations() -> list[tuple[str, float]]:
    out = _pending_reconcile_durations[:]
    _pending_reconcile_durations.clear()
    return out

WORKQUEUE_DEPTH = _get_or_create(
    Gauge, "tpu_provisioner_workqueue_depth",
    "Items ready for a worker right now.", ["controller"])

WORKQUEUE_DELAYED = _get_or_create(
    Gauge, "tpu_provisioner_workqueue_delayed",
    "Items parked in rate-limit backoff.", ["controller"])

WORKQUEUE_RETRYING = _get_or_create(
    Gauge, "tpu_provisioner_workqueue_retrying",
    "Items with a live failure count (requeued since their last forget).",
    ["controller"])

# Cumulative values sampled into gauges at scrape time (the counters live on
# runtime objects prometheus can't own) — named WITHOUT the _total suffix,
# which is reserved for true Counter semantics.
WORKQUEUE_REQUEUES = _get_or_create(
    Gauge, "tpu_provisioner_workqueue_requeues",
    "Cumulative rate-limited requeues (sampled from the queue counter).",
    ["controller"])

SHARD_QUEUE_DEPTH = _get_or_create(
    Gauge, "tpu_provisioner_shard_queue_depth",
    "Ready items summed across this process's controllers by shard index — "
    "the shard-imbalance view (singletons and key-less requests pile onto "
    "shard 0; see docs/PERFORMANCE.md).", ["shard"])

# True Counter fed by DELTA from the runtime wakehub's module ledger at
# scrape time (the runtime layer never imports prometheus) — the
# STOCKOUTS_TOTAL idiom. Counts wakes that actually landed an enqueue;
# dedup-collapsed wakes are invisible by design.
REQUEUE_WAKES_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_requeue_wakes_total",
    "Workqueue enqueues by wake source (watch/node/lro/timer/stockout/"
    "status-flush/inject). 'timer' means a requeue_after safety net had to "
    "fire — residual polling the wake graph should be eliminating.",
    ["source"])

_wakes_seen: dict[str, int] = {}

# Worker-process wake ledgers fold through the shard IPC snapshots (see
# update_runtime_gauges): cumulative per (worker, source), so a restarted
# worker's counter reset shows up as a negative delta and is skipped.
_worker_wakes_seen: dict[tuple[str, str], int] = {}

# --------------------------------------------------------- crash recovery

RECOVERY_ADOPTED = _get_or_create(
    Counter, "tpu_provisioner_recovery_adopted",
    "Half-created cloud resources (with a living NodeClaim) adopted by the "
    "startup resync pass; the lifecycle re-drive resumes them.", ["resource"])

RECOVERY_REAPED = _get_or_create(
    Counter, "tpu_provisioner_recovery_reaped",
    "Orphaned cloud resources (NodeClaim gone) reaped by the startup "
    "resync pass ahead of the GC interval.", ["resource"])

RECOVERY_RESUMED = _get_or_create(
    Counter, "tpu_provisioner_recovery_resumed",
    "Queued resources found mid-ladder with a living NodeClaim; the queued "
    "create path re-enters the ladder where the dead incarnation left it.",
    ["resource"])

FENCED_RECONCILES = _get_or_create(
    Gauge, "tpu_provisioner_fenced_reconciles",
    "Reconciles dropped because this replica's fencing token went stale "
    "(deposed leader; sampled).", ["controller"])

# 0 = closed, 1 = half-open, 2 = open (alert on >= 1).
BREAKER_STATE = _get_or_create(
    Gauge, "tpu_provisioner_circuit_breaker_state",
    "Circuit breaker state: 0 closed, 1 half-open, 2 open.", ["name"])

BREAKER_REJECTED = _get_or_create(
    Gauge, "tpu_provisioner_circuit_breaker_rejected",
    "Cumulative calls rejected locally while the breaker was open "
    "(sampled).", ["name"])

# ------------------------------------------------------- provisioning cache
# Sampled-cumulative gauges (same convention as WORKQUEUE_REQUEUES: the
# counters live on provider-layer objects prometheus can't own) fed from the
# providers.cache registries at scrape time.

INSTANCE_CACHE_HITS = _get_or_create(
    Gauge, "tpu_provisioner_instance_cache_hits",
    "Read-through instance cache hits (sampled).", ["cache"])

INSTANCE_CACHE_MISSES = _get_or_create(
    Gauge, "tpu_provisioner_instance_cache_misses",
    "Read-through instance cache misses (sampled).", ["cache"])

INSTANCE_CACHE_COALESCED = _get_or_create(
    Gauge, "tpu_provisioner_instance_cache_coalesced",
    "Reads coalesced onto an in-flight fetch (singleflight, sampled).",
    ["cache"])

INSTANCE_CACHE_NEGATIVE_HITS = _get_or_create(
    Gauge, "tpu_provisioner_instance_cache_negative_hits",
    "Reads served a cached NotFound (sampled).", ["cache"])

INSTANCE_CACHE_INVALIDATIONS = _get_or_create(
    Gauge, "tpu_provisioner_instance_cache_invalidations",
    "Explicit cache invalidations on create/delete/state transition "
    "(sampled).", ["cache"])

CLOUD_API_CALLS = _get_or_create(
    Gauge, "tpu_provisioner_cloud_api_calls",
    "Cloud API calls by endpoint (scope.method, sampled).", ["endpoint"])

# ------------------------------------------------- non-blocking provisioning
# The operation tracker's surface: how many LROs the multiplexer is carrying
# right now, how many batched polls it has issued (one nodepools.list per
# tick, vs one get per op per interval before), and how long operations take
# end-to-end (begin_create/begin_delete → resolved).

INFLIGHT_OPERATIONS = _get_or_create(
    Gauge, "tpu_provisioner_inflight_operations",
    "In-flight tracked cloud operations by kind (sampled across live "
    "operation trackers).", ["kind"])

OPERATION_POLL_BATCHES = _get_or_create(
    Gauge, "tpu_provisioner_operation_poll_batches",
    "Cumulative batched operation polls — one nodepools.list resolving "
    "every in-flight operation (sampled).", [])

OPERATION_WAIT = _get_or_create(
    Histogram, "tpu_provisioner_operation_wait_seconds",
    "Tracked operation duration from registration to resolution.", ["kind"],
    buckets=(0.1, 0.5, 1, 5, 15, 30, 60, 120, 300, 600, 1800))

# ------------------------------------------------------------- node repair
# Sampled-cumulative gauges (the counters live on controllers/health.py's
# module registry, which never imports prometheus) + a duration histogram
# drained at scrape like OPERATION_WAIT.

REPAIR_STARTED = _get_or_create(
    Gauge, "tpu_provisioner_repair_started",
    "Node repairs committed (cordon + drain begun; sampled).", [])

REPAIR_SUCCEEDED = _get_or_create(
    Gauge, "tpu_provisioner_repair_succeeded",
    "Node repairs that force-deleted the owning NodeClaim (sampled).", [])

REPAIR_THROTTLED = _get_or_create(
    Gauge, "tpu_provisioner_repair_throttled",
    "Repair attempts held back by the budget (tokens/concurrency/slice-group "
    "serialization) or the unhealthy-fraction breaker (sampled).", [])

REPAIR_FLAP_DETECTIONS = _get_or_create(
    Gauge, "tpu_provisioner_repair_flap_detections",
    "Nodes whose condition-transition history crossed the hysteresis "
    "threshold (sampled).", [])

REPAIR_DURATION = _get_or_create(
    Histogram, "tpu_provisioner_repair_duration_seconds",
    "Repair duration from commit (cordon) to NodeClaim force-delete.", [],
    buckets=(0.1, 0.5, 1, 5, 15, 30, 60, 120, 300, 600, 1800))

# ------------------------------------------------------ capacity placement
# True Counters (hence the _total names — counters only go up) fed by DELTA
# from the placement engine's module registries at scrape time: the
# providers layer never imports prometheus, so each scrape increments by
# what accumulated since its last-seen snapshot.

STOCKOUTS_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_stockouts_total",
    "Zonal stockouts observed by the placement walk: terminal "
    "RESOURCE_EXHAUSTED from begin_create, plus memo-suppressed probes of "
    "a known-dry zone.", ["zone"])

FALLBACK_PLACEMENTS_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_fallback_placements_total",
    "Claims placed on a candidate other than their first preference, by "
    "preferred and actual zone.", ["from_zone", "to_zone"])

SPOT_PREEMPTIONS_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_spot_preemptions_total",
    "Spot slices reclaimed by the cloud (repairs committed for a "
    "SpotPreempted condition), by zone.", ["zone"])

_stockouts_seen: dict[str, int] = {}
_fallbacks_seen: dict[tuple[str, str], int] = {}
_spot_preemptions_seen: dict[str, int] = {}

# ------------------------------------------------------------ fleet SLO
# The fleetscope aggregator's surface (observability/fleet.py): streaming
# time-to-ready percentiles per placement key, declared-objective state,
# and multi-window burn rate. Digests live on the aggregator (that layer
# never imports prometheus) and are sampled at scrape — the REPAIR_STATS
# convention. The wake-share gauge rides here too: the bench's "producer
# fell off the wake hub" safety-net signal, finally live at /metrics.

TIMER_WAKE_SHARE = _get_or_create(
    Gauge, "tpu_provisioner_timer_wake_share",
    "Fraction of workqueue wakes sourced from requeue_after timers (vs "
    "event wakes) since process start — residual polling. Near 0 is "
    "healthy; a climb toward 1 means producers fell off the wake hub.", [])

SLO_TIME_TO_READY = _get_or_create(
    Gauge, "tpu_provisioner_slo_time_to_ready_seconds",
    "Streaming time-to-ready quantiles per {zone, generation, tier, shard} "
    "placement key (fixed-bucket digest, sampled).",
    ["zone", "generation", "tier", "shard", "quantile"])

SLO_PHASE_MEAN = _get_or_create(
    Gauge, "tpu_provisioner_slo_phase_mean_seconds",
    "Mean per-claim seconds attributed to each critical-path phase across "
    "all observed claims (sampled).", ["phase"])

SLO_CLAIMS_OBSERVED = _get_or_create(
    Gauge, "tpu_provisioner_slo_claims_observed",
    "Ready claims folded into the fleet digests (sampled).", [])

SLO_OBJECTIVE_TARGET = _get_or_create(
    Gauge, "tpu_provisioner_slo_objective_target_seconds",
    "Declared time-to-ready target per SLO objective.", ["objective"])

SLO_BURN_RATE = _get_or_create(
    Gauge, "tpu_provisioner_slo_error_budget_burn_rate",
    "Error-budget burn rate per objective and window (fast/slow); the "
    "fast-burn alert fires when BOTH exceed the objective's threshold.",
    ["objective", "window"])

SLO_VIOLATIONS_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_slo_violations_total",
    "Claims whose time-to-ready exceeded the objective target (delta-fed "
    "from the aggregator's cumulative count).", ["objective"])

_slo_violations_seen: dict[str, int] = {}

FLIGHT_RECORDER_EVENTS = _get_or_create(
    Gauge, "tpu_provisioner_flight_recorder_events",
    "Semantic control-plane events captured by the flight recorder "
    "(cumulative, sampled).", [])

FLIGHT_RECORDER_BUNDLES = _get_or_create(
    Gauge, "tpu_provisioner_flight_recorder_bundles",
    "Diagnostic bundles snapshotted by anomaly triggers (cumulative, "
    "sampled; repeats of a trigger are deduped, not bundled).", [])

# ------------------------------------------------------- apiserver health
# The degraded-mode control plane (runtime/apihealth.py): mode machine plus
# the watch-gap/relist/shed ledger, sampled at scrape like WAKES.

DEGRADED_MODE = _get_or_create(
    Gauge, "tpu_provisioner_degraded_mode",
    "APIHealthGovernor degraded-mode state: 0 HEALTHY, 1 BROWNOUT, "
    "2 PARTITIONED, 3 CATCHUP (the worst across live governors).", [])

WATCH_GAPS_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_watch_gaps_total",
    "Watch streams that answered 410 Gone / expired resourceVersion "
    "(delta-fed from the runtime apihealth ledger).", [])

RELISTS_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_relists_total",
    "Gap-resync relists completed (diff synthesized through the informer "
    "relays; delta-fed from the runtime apihealth ledger).", [])

API_SHED_TOTAL = _get_or_create(
    Counter, "tpu_provisioner_api_shed_total",
    "Work deferred by overload shedding: paced reconcile/write waits plus "
    "widened status-batch windows (delta-fed).", [])

_apihealth_seen: dict[str, int] = {}

# ---------------------------------------------------------- serving engine
# models/engine.py stats() bridged into gauges via the fleet ENGINES
# registry (weak values — a dead engine leaves the scrape). The autoscaler
# input signal: slot occupancy and queue depth are the demand curve.

ENGINE_SLOTS = _get_or_create(
    Gauge, "tpu_provisioner_engine_slots",
    "Decode slots by engine and state (total/active).", ["engine", "state"])

ENGINE_QUEUE_DEPTH = _get_or_create(
    Gauge, "tpu_provisioner_engine_queue_depth",
    "Requests queued behind the batcher, by engine.", ["engine"])

ENGINE_REQUESTS = _get_or_create(
    Gauge, "tpu_provisioner_engine_requests",
    "Cumulative requests by engine and state (submitted/finished; "
    "sampled).", ["engine", "state"])

ENGINE_TOKENS_EMITTED = _get_or_create(
    Gauge, "tpu_provisioner_engine_tokens_emitted",
    "Cumulative tokens emitted across finished and active requests, by "
    "engine (sampled).", ["engine"])

ENGINE_PREFIX_CACHE = _get_or_create(
    Gauge, "tpu_provisioner_engine_prefix_cache",
    "Prefix-cache effectiveness by engine and stat (entries/hits/misses; "
    "sampled).", ["engine", "stat"])

_CACHE_GAUGES = (
    ("hits", INSTANCE_CACHE_HITS),
    ("misses", INSTANCE_CACHE_MISSES),
    ("coalesced", INSTANCE_CACHE_COALESCED),
    ("negative_hits", INSTANCE_CACHE_NEGATIVE_HITS),
    ("invalidations", INSTANCE_CACHE_INVALIDATIONS),
)

_BREAKER_STATE_VALUE = {BREAKER_OPEN: 2.0, BREAKER_HALF_OPEN: 1.0}
_exported_breakers: set[str] = set()


def update_runtime_gauges(manager) -> None:
    """Refresh workqueue + breaker gauges from live objects. Called by the
    /metrics handler at scrape time (and by soak tests directly) — gauges
    sample state that lives in the runtime layer, which must not import
    prometheus."""
    shard_depths: dict[int, int] = {}
    for c in getattr(manager, "controllers", []):
        q = c.queue
        WORKQUEUE_DEPTH.labels(c.name).set(q.depth())
        WORKQUEUE_DELAYED.labels(c.name).set(q.delayed())
        WORKQUEUE_RETRYING.labels(c.name).set(q.retrying())
        WORKQUEUE_REQUEUES.labels(c.name).set(q.requeues_total)
        FENCED_RECONCILES.labels(c.name).set(c.fenced_total)
        shard = getattr(c, "shard_index", 0)
        shard_depths[shard] = shard_depths.get(shard, 0) + q.depth()
    for shard, depth in shard_depths.items():
        SHARD_QUEUE_DEPTH.labels(str(shard)).set(depth)
    from ..runtime import wakehub as _wakehub
    for source, n in list(_wakehub.WAKES.items()):
        delta = n - _wakes_seen.get(source, 0)
        if delta > 0:
            REQUEUE_WAKES_TOTAL.labels(source).inc(delta)
            _wakes_seen[source] = n
    # Multi-process shards: each worker pushes a cumulative stats snapshot
    # over the shard IPC socket; the parent's scrape folds them in here —
    # queue depths as shard={worker} series, wake ledgers delta-fed into
    # the same counter family the local hub feeds.
    from ..runtime import shardipc as _shardipc
    worker_wakes: dict[str, int] = {}
    for server in list(_shardipc.SERVERS):
        for worker, snap in list(server.snapshots.items()):
            SHARD_QUEUE_DEPTH.labels(worker).set(
                sum(snap.get("depths", {}).values()))
            for source, n in snap.get("wakes", {}).items():
                worker_wakes[source] = worker_wakes.get(source, 0) + n
                delta = n - _worker_wakes_seen.get((worker, source), 0)
                if delta > 0:
                    REQUEUE_WAKES_TOTAL.labels(source).inc(delta)
                if delta:
                    _worker_wakes_seen[(worker, source)] = n
    for name, stats in CACHE_STATS.items():
        for stat, gauge in _CACHE_GAUGES:
            gauge.labels(name).set(stats[stat])
    for endpoint, calls in CLOUD_CALLS.items():
        CLOUD_API_CALLS.labels(endpoint).set(calls)
    inflight = {ops.OP_CREATE: 0, ops.OP_DELETE: 0}
    for tracker in list(ops.TRACKERS):
        for kind, n in tracker.inflight().items():
            inflight[kind] = inflight.get(kind, 0) + n
    for kind, n in inflight.items():
        INFLIGHT_OPERATIONS.labels(kind).set(n)
    OPERATION_POLL_BATCHES.set(ops.POLL_BATCHES["count"])
    # completed-operation durations accumulate provider-side (that layer
    # never imports prometheus) and drain into the histogram at scrape
    for kind, seconds in ops.drain_operation_waits():
        OPERATION_WAIT.labels(kind).observe(seconds)
    for controller, seconds in drain_reconcile_durations():
        RECONCILE_DURATION.labels(controller).observe(seconds)
    from . import health as _health
    REPAIR_STARTED.set(_health.REPAIR_STATS["started"])
    REPAIR_SUCCEEDED.set(_health.REPAIR_STATS["succeeded"])
    REPAIR_THROTTLED.set(_health.REPAIR_STATS["throttled"])
    REPAIR_FLAP_DETECTIONS.set(_health.REPAIR_STATS["flap_detections"])
    for seconds in _health.drain_repair_durations():
        REPAIR_DURATION.observe(seconds)
    from ..providers import placement as _placement
    for zone, n in list(_placement.STOCKOUTS.items()):
        delta = n - _stockouts_seen.get(zone, 0)
        if delta > 0:
            STOCKOUTS_TOTAL.labels(zone).inc(delta)
            _stockouts_seen[zone] = n
    for (src, dst), n in list(_placement.FALLBACKS.items()):
        delta = n - _fallbacks_seen.get((src, dst), 0)
        if delta > 0:
            FALLBACK_PLACEMENTS_TOTAL.labels(src, dst).inc(delta)
            _fallbacks_seen[(src, dst)] = n
    for zone, n in list(_placement.SPOT_PREEMPTIONS.items()):
        delta = n - _spot_preemptions_seen.get(zone, 0)
        if delta > 0:
            SPOT_PREEMPTIONS_TOTAL.labels(zone).inc(delta)
            _spot_preemptions_seen[zone] = n
    # Drop series for breakers whose client closed — a stale "open" reading
    # would keep an alert firing for an endpoint nothing gates on anymore.
    for name in _exported_breakers - set(BREAKERS):
        try:
            BREAKER_STATE.remove(name)
            BREAKER_REJECTED.remove(name)
        except KeyError:
            pass
    _exported_breakers.intersection_update(BREAKERS)
    for name, breaker in BREAKERS.items():
        BREAKER_STATE.labels(name).set(
            _BREAKER_STATE_VALUE.get(breaker.state, 0.0))
        BREAKER_REJECTED.labels(name).set(breaker.rejected_total)
        _exported_breakers.add(name)
    # Wake-source share: local ledger plus every worker's, timer wakes over
    # all DELIVERED wakes since process start. The skipped-arm ledger key is
    # bookkeeping (timers never armed), not a wake — excluded from both
    # sides so the diet shrinks the numerator without inflating the total.
    combined = dict(_wakehub.WAKES)
    for source, n in worker_wakes.items():
        combined[source] = combined.get(source, 0) + n
    combined.pop(_wakehub.SKIPPED_TIMER_ARM, None)
    total_wakes = sum(combined.values())
    if total_wakes:
        TIMER_WAKE_SHARE.set(
            combined.get(_wakehub.SOURCE_TIMER, 0) / total_wakes)
    from ..observability import fleet as _fleet
    from ..observability import flightrecorder as _flightrecorder
    claims = 0
    phase_totals: dict[str, tuple[float, int]] = {}
    slo_state: dict[str, dict] = {}
    for agg in list(_fleet.AGGREGATORS):
        claims += agg.claims_observed
        for key, digest in list(agg.digests.items()):
            zone, generation, tier, shard = key
            for q, qv in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
                SLO_TIME_TO_READY.labels(
                    zone, generation, tier, shard, q).set(digest.quantile(qv))
        for phase, digest in list(agg.phase_digests.items()):
            t, n = phase_totals.get(phase, (0.0, 0))
            phase_totals[phase] = (t + digest.total, n + digest.count)
        for trk in agg.slos:
            st = slo_state.setdefault(
                trk.objective.name,
                {"target": trk.objective.target, "bad": 0,
                 "burn": {"fast": 0.0, "slow": 0.0}})
            st["bad"] += trk.bad
            for window, rate in trk.burn_rates().items():
                st["burn"][window] = max(st["burn"][window], rate)
    SLO_CLAIMS_OBSERVED.set(claims)
    for phase, (total, n) in phase_totals.items():
        SLO_PHASE_MEAN.labels(phase).set(total / n if n else 0.0)
    for objective, st in slo_state.items():
        SLO_OBJECTIVE_TARGET.labels(objective).set(st["target"])
        for window, rate in st["burn"].items():
            SLO_BURN_RATE.labels(objective, window).set(rate)
        delta = st["bad"] - _slo_violations_seen.get(objective, 0)
        if delta > 0:
            SLO_VIOLATIONS_TOTAL.labels(objective).inc(delta)
            _slo_violations_seen[objective] = st["bad"]
    from ..runtime import apihealth as _apihealth
    _LEDGER_COUNTERS = (("watch_gaps", WATCH_GAPS_TOTAL),
                        ("relists", RELISTS_TOTAL),
                        ("shed", API_SHED_TOTAL))
    for key, counter in _LEDGER_COUNTERS:
        n = _apihealth.APIHEALTH.get(key, 0)
        delta = n - _apihealth_seen.get(key, 0)
        if delta > 0:
            counter.inc(delta)
            _apihealth_seen[key] = n
    DEGRADED_MODE.set(max(
        (g.mode_value() for g in list(_apihealth.GOVERNORS)), default=0))
    events = bundles = 0
    for rec in list(_flightrecorder.RECORDERS):
        events += rec.events_recorded
        bundles += len(rec.bundles())
    FLIGHT_RECORDER_EVENTS.set(events)
    FLIGHT_RECORDER_BUNDLES.set(bundles)
    for engine, stats in _fleet.engine_stats().items():
        ENGINE_SLOTS.labels(engine, "total").set(stats["slots"])
        ENGINE_SLOTS.labels(engine, "active").set(stats["slots_active"])
        ENGINE_QUEUE_DEPTH.labels(engine).set(stats["queue_depth"])
        ENGINE_REQUESTS.labels(engine, "submitted").set(
            stats["requests_submitted"])
        ENGINE_REQUESTS.labels(engine, "finished").set(
            stats["requests_finished"])
        ENGINE_TOKENS_EMITTED.labels(engine).set(stats["tokens_emitted"])
        for stat in ("entries", "hits", "misses"):
            ENGINE_PREFIX_CACHE.labels(engine, stat).set(
                stats[f"prefix_cache_{stat}"])
