"""Controller-level Prometheus metrics.

Name-compatible with the reference's nodeclaim metrics
(vendor/sigs.k8s.io/karpenter/pkg/metrics/metrics.go:33-60 and
lifecycle/controller.go:249-266), plus a provision-duration histogram — the
headline NodeClaim→Ready latency from BASELINE.json that the reference never
measured.
"""

from prometheus_client import REGISTRY, Counter, Histogram


def _get_or_create(cls, name, doc, labelnames, **kw):
    try:
        return cls(name, doc, labelnames, **kw)
    except ValueError:
        return REGISTRY._names_to_collectors[name]


NODECLAIMS_CREATED = _get_or_create(
    Counter, "karpenter_nodeclaims_created_total",
    "NodeClaims launched, by provider.", ["provider"])

NODECLAIMS_TERMINATED = _get_or_create(
    Counter, "karpenter_nodeclaims_terminated_total",
    "NodeClaims terminated, by provider.", ["provider"])

TERMINATION_DURATION = _get_or_create(
    Histogram, "karpenter_nodeclaims_termination_duration_seconds",
    "Time from deletion request to finalizer removal.", ["provider"],
    buckets=(1, 5, 15, 30, 60, 120, 300, 600, 1800))

PROVISION_DURATION = _get_or_create(
    Histogram, "karpenter_nodeclaims_provision_duration_seconds",
    "Time from NodeClaim creation to Initialized (NodeClaim→Ready).",
    ["provider", "instance_type"],
    buckets=(5, 15, 30, 60, 120, 180, 300, 420, 600, 900))

CHIPS_PROVISIONED = _get_or_create(
    Counter, "tpu_chips_provisioned_total",
    "Total TPU chips brought to Ready.", ["generation"])
