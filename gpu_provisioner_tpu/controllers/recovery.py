"""Startup resync / orphan adoption — the crash-restart recovery pass.

A restarted (or newly-elected) operator inherits whatever a dead
incarnation stranded in the cloud: pools mid-create with a living
NodeClaim, queued resources mid-ladder, and half-deleted or claimless
resources nothing will ever finish. The watch replay re-drives every
NodeClaim through the normal controllers (store.watch initial-list
semantics), so per-claim *resumption* needs no special casing — the
idempotent create / conflict-adoption path in ``providers/instance.py``
picks the work back up. What the replay can NOT see is cloud state with no
claim behind it: that leaks until the next instance-GC tick (minutes).

This singleton runs one audit pass at boot — i.e. immediately after this
replica becomes leader, since the manager only starts then — and then
re-audits at a slow cadence as insurance:

- **adopt**   a pool whose NodeClaim still exists but whose launch never
              recorded: counted (``tpu_provisioner_recovery_adopted``);
              the lifecycle re-drive resumes the LRO.
- **reap**    a pool or queued resource whose NodeClaim is gone: deleted
              NOW instead of waiting out the GC interval
              (``tpu_provisioner_recovery_reaped``).
- **resume**  a queued resource mid-ladder with a living claim: counted
              (``tpu_provisioner_recovery_resumed``); the queued create
              path re-enters the ladder where it left off.

Ordering makes orphan detection race-free without a grace window: a
NodeClaim always exists before its pool/QR is created, so listing cloud
resources FIRST and claims SECOND means a resource whose claim is absent
from the later claim list is a true orphan, not a creation race. The pass
still refuses to act on a stale cached claim view (same watch-age guard as
GC): reaping on a wedged informer would delete live capacity.
"""

from __future__ import annotations

import contextlib
import logging
from dataclasses import dataclass
from typing import Optional

from ..apis.karpenter import LAUNCHED, NodeClaim
from ..errors import NodeClaimNotFoundError
# provgraph: disable=PG001 — the recovery scan classifies orphaned pools by
# GCP nodepool/QR state constants that still live in the cloud module;
# hoisting a cloud-neutral state enum behind the provider seam is exactly
# the ROADMAP item-4 second-backend refactor, tracked there
from ..providers.gcp import (
    NP_ERROR, NP_PROVISIONING, NP_STOPPING, QR_ACTIVE,
)
from ..providers.instance import (
    parse_ts_label, pool_created_from_nodeclaim, pool_owned_by_kaito,
)
from ..apis import labels as wk
from ..apis.serde import now
from ..runtime import probes
from ..runtime.client import Client
from .gc import _cache_too_stale, GCOptions
from .metrics import RECOVERY_ADOPTED, RECOVERY_REAPED, RECOVERY_RESUMED
from .utils import list_managed

log = logging.getLogger("controllers.recovery")


@dataclass
class RecoveryOptions:
    # Boot pass fires immediately (singleton semantics); afterwards the
    # audit repeats at this slow cadence as insurance — GC owns steady-state.
    interval: float = 600.0
    # Skip cloud resources younger than this (creation-timestamp label,
    # second resolution) — the same leak grace GC applies. The
    # pools-then-claims ordering makes orphan detection race-free for the
    # controller path, but direct provider use (tests, manual tooling)
    # creates pools no claim ever backs.
    grace: float = 30.0
    # Refuse to reap on a stale cached claim view (GC's watch-age bound).
    max_cache_age: float = 600.0
    # Range-ownership predicate for multi-process shard workers (same
    # contract as GCOptions.owns): the audit adopts/reaps only pools and
    # queued resources whose name falls in this worker's leased ranges.
    owns: object = None


class RecoveryController:
    NAME = "operator.recovery"

    def __init__(self, client: Client, cloudprovider,
                 options: Optional[RecoveryOptions] = None,
                 recorder=None, tracer=None):
        self.client = client
        self.cp = cloudprovider
        self.opts = options or RecoveryOptions()
        # Recorder + claimtrace tracer (both optional): an adoption is one
        # of the lifecycle moments that used to log only — it now emits an
        # Event carrying the trace id, and re-anchors the adopted claim's
        # trace (the pre-crash trace died with the old incarnation's store).
        self.recorder = recorder
        self.tracer = tracer
        # count each (fate, resource) once per incarnation, not once per pass
        self._counted: set[tuple[str, str, str]] = set()

    @property
    def provider(self):
        # InstanceProvider behind the metrics decorator (both the decorator
        # and the bare TPUCloudProvider expose .instances)
        return self.cp.instances

    async def _publish(self, obj, etype, reason, message) -> None:
        if self.recorder is not None:
            await self.recorder.publish(obj, etype, reason, message)

    def _span(self, claim: str, name: str, **attrs):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(claim, name, **attrs)

    async def run_once(self) -> float:
        try:
            await self._resync()
        except Exception as e:  # noqa: BLE001 — recovery must keep ticking
            log.warning("recovery pass failed: %s", e, exc_info=True)
        return self.opts.interval

    async def _resync(self) -> None:
        gc_guard = GCOptions(max_cache_age=self.opts.max_cache_age)
        if _cache_too_stale(self.client, gc_guard, self.NAME, NodeClaim):
            return
        provider = self.provider
        # cloud FIRST, claims SECOND — see module docstring
        pools = await provider.nodepools.list()
        queued = (await provider.queued.list()
                  if provider.queued is not None else [])
        claims = {nc.metadata.name: nc
                  for nc in await list_managed(self.client)}

        for pool in pools:
            if self.opts.owns is not None and not self.opts.owns(pool.name):
                continue
            if not (pool_owned_by_kaito(pool)
                    and pool_created_from_nodeclaim(pool)):
                continue
            nc = claims.get(pool.name)
            if nc is None:
                # STOPPING: a delete is already in flight. PROVISIONING: a
                # create is in flight — possibly a direct provider.create
                # racing this pass (no claim ever backs those) — and the
                # verdict belongs to GC once the pool settles; reaping here
                # would yank a pool out from under a live node wait.
                if (pool.status in (NP_STOPPING, NP_PROVISIONING)
                        or self._young(pool)):
                    continue
                await self._reap_pool(pool.name)
            elif (nc.metadata.deletion_timestamp is None
                  and (pool.status in (NP_PROVISIONING, NP_ERROR)
                       or not nc.status_conditions.is_true(LAUNCHED))):
                # half-created: a previous incarnation died mid-create.
                # Re-register the stranded LRO with the operation tracker
                # (batched polling + completion wake) so resumption never
                # blind-waits; with no tracker wired the lifecycle re-drive
                # resumes it through conflict adoption instead.
                resumed = False
                if pool.status != NP_ERROR:
                    resumed = provider.resume_create(pool.name,
                                                     pool.initial_node_count)
                if not self._count("pool", pool.name, RECOVERY_ADOPTED,
                                   "adopting half-created pool"):
                    continue
                # Re-anchor the claim's trace (the pre-crash one died with
                # the old store) and surface the adoption as an Event — it
                # used to be visible only in this controller's log line.
                if self.tracer is not None:
                    self.tracer.reanchor(pool.name, uid=nc.metadata.uid,
                                         pool_status=pool.status)
                probes.emit("recovery-adopt", pool.name, resource="pool",
                            pool_status=pool.status, resumed=resumed)
                with self._span(pool.name, "adopt", pool_status=pool.status):
                    if resumed:
                        await self._publish(
                            nc, "Normal", "LROAdopted",
                            f"adopted in-flight create LRO for pool "
                            f"{pool.name} ({pool.status}) on restart")
                    else:
                        await self._publish(
                            nc, "Normal", "CreateResumed",
                            f"create of pool {pool.name} ({pool.status}) "
                            "resumed after restart via lifecycle re-drive")

        for qr in queued:
            if self.opts.owns is not None and not self.opts.owns(qr.name):
                continue
            nc = claims.get(qr.name)
            if nc is None:
                await self._reap_qr(qr.name)
            elif (qr.state != QR_ACTIVE
                  and nc.metadata.deletion_timestamp is None):
                if not self._count("qr", qr.name, RECOVERY_RESUMED,
                                   "resuming queued-resource ladder"):
                    continue
                probes.emit("recovery-adopt", qr.name, resource="qr",
                            qr_state=qr.state)
                with self._span(qr.name, "adopt", qr_state=qr.state):
                    await self._publish(
                        nc, "Normal", "CreateResumed",
                        f"queued-resource ladder for {qr.name} "
                        f"({qr.state}) resumed after restart")

    def _young(self, pool) -> bool:
        if self.opts.grace <= 0:
            return False
        created = parse_ts_label(
            pool.config.labels.get(wk.KAITO_CREATION_TIMESTAMP_LABEL, ""))
        if created is None:
            return False
        # -1.0: the creation label is second-truncated, so the raw age
        # over-reports by up to a second — reap only on the age LOWER bound
        # (fresh orphans that slip through fall to GC's observed-for grace)
        return (now() - created).total_seconds() - 1.0 < self.opts.grace

    def _count(self, kind: str, name: str, counter, what: str) -> bool:
        # dedup per (fate, resource): the SAME resource can legitimately be
        # counted under different counters across passes (adopted at boot,
        # reaped after its claim dies) — only repeat observations of the
        # same fate are suppressed. Returns True on the FIRST observation:
        # the adoption Event + trace re-anchor key off it, so a later audit
        # pass neither re-publishes nor resets the re-anchored trace.
        key = (counter._name, kind, name)
        if key in self._counted:
            return False
        self._counted.add(key)
        counter.labels(kind).inc()
        log.info("recovery: %s %s", what, name)
        return True

    async def _reap_pool(self, name: str) -> None:
        # provider.delete is the full teardown (queued cleanup first, then
        # the pool) and is idempotent against concurrent GC/termination
        try:
            await self.provider.delete(name)
        except NodeClaimNotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 — per-item; GC is the backstop
            log.warning("recovery: reaping orphan pool %s failed: %s", name, e)
            return
        self._count("pool", name, RECOVERY_REAPED, "reaped orphan pool")

    async def _reap_qr(self, name: str) -> None:
        try:
            # the provider's fenced QR-teardown path (NotFound is success):
            # a deposed leader's in-flight audit must not delete a queued
            # resource the new leader may be driving
            await self.provider.delete_queued(name)
        except Exception as e:  # noqa: BLE001 — per-item; GC is the backstop
            log.warning("recovery: reaping orphan queued resource %s "
                        "failed: %s", name, e)
            return
        self._count("qr", name, RECOVERY_REAPED, "reaped orphan queued resource")
