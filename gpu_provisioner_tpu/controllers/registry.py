"""Controller registry: builds the active controller set (V1 analog).

The reference's registry (vendor/.../controllers/controllers.go:39-122) is a
patched Karpenter list with most controllers commented out; the active subset
is: nodeclaim lifecycle, node termination, nodeclaim GC, node health (iff
repair policies + feature gate), plus the first-party instance GC
(pkg/controllers/controllers.go:26-31). This mirrors that set exactly and
keeps the seam open for future controllers (SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..apis.karpenter import NodeClaim
from ..runtime import Controller, Request, Singleton
from ..runtime.client import Client
from ..runtime.events import Recorder
from ..runtime.wakehub import (
    SOURCE_LRO, SOURCE_NODE, SOURCE_STATUS_FLUSH, WakeHub,
)
from .gc import GCOptions, InstanceGCController, NodeClaimGCController
from .health import HealthOptions, NodeHealthController
from .lifecycle import LifecycleOptions, NodeClaimLifecycleController
from .metrics import (
    RECONCILE_RETRIES_EXHAUSTED, RECONCILE_TIMEOUTS, record_reconcile_duration,
)
from .recovery import RecoveryController, RecoveryOptions
from .slicegroup import SliceGroupController, group_requests
from .termination import EvictionQueue, NodeTerminationController, TerminationOptions
from .utils import shard_owns


def _node_pool(node: Node) -> Optional[str]:
    """The claim/pool name a Node correlates (and shards) under — ONE
    home for the label precedence so lifecycle mapping and shard
    partitioning can never disagree about a node's owner."""
    return (node.metadata.labels.get(wk.TPU_SLICE_ID_LABEL)
            or node.metadata.labels.get(wk.GKE_NODEPOOL_LABEL))


def node_to_nodeclaim_requests(node: Node) -> list[Request]:
    pool = _node_pool(node)
    return [Request(name=pool)] if pool else []


def build_controllers(client: Client, cloudprovider,
                      recorder: Optional[Recorder] = None,
                      lifecycle_options: Optional[LifecycleOptions] = None,
                      termination_options: Optional[TerminationOptions] = None,
                      gc_options: Optional[GCOptions] = None,
                      health_options: Optional[HealthOptions] = None,
                      node_repair: bool = True,
                      max_concurrent_reconciles: int = 64,
                      cluster: str = "kaito",
                      shards: int = 1, shard_index: int = 0,
                      reconcile_timeout: Optional[float] = None,
                      # By 30 consecutive failures the jittered ladder has
                      # reached the queue's max-delay cap anyway, so the
                      # bound changes observability (warning event + metric
                      # + counter reset), not cadence — and it can never
                      # out-race a liveness budget the way a tighter bound
                      # could (the ladder's cumulative delay at 30 exceeds
                      # any configured launch timeout's first check).
                      max_retries: int = 30,
                      recovery_options: Optional[RecoveryOptions] = None,
                      crashes=None,
                      fence=None,
                      tracker=None,
                      tracer=None,
                      wakehub=None,
                      status_batcher=None,
                      owns=None,
                      distribute_singletons: bool = False,
                      ) -> tuple[list[Controller], EvictionQueue]:
    """Assemble the active controller set. ``max_concurrent_reconciles``
    scales the lifecycle worker pool (reference: 1000-5000 CPU-scaled,
    lifecycle/controller.go:56-58,89 — asyncio workers are cheap but bounded
    for fairness).

    ``shards``/``shard_index``: claim-shard horizontal scaling past the
    single-event-loop ceiling (shard_owns): per-claim controllers
    (lifecycle, termination, health) enqueue only objects whose claim/pool
    name hashes to this shard — filtering at the WATCH→request boundary,
    so foreign objects never occupy a worker; cluster-scoped singletons
    (both GC directions, slice-group assignment) run on shard 0 only.
    Every shard watches the full stream (the apiserver fans out watches
    anyway); the partition costs one crc32 per event. Nodes without a
    pool label fall to shard 0 so nothing is orphaned.

    ``reconcile_timeout``/``max_retries`` apply the runtime hardening to
    every per-object controller (singletons are self-requeuing and own
    their cadence): a hung reconcile is cancelled at the deadline, and a
    persistently-failing item degrades to slow retry after ``max_retries``
    requeues — both are counted in the tpu_provisioner_reconcile_* metric
    families, and retry exhaustion on a NodeClaim also publishes a Warning
    event on the claim.

    Crash-restart recovery wiring: ``crashes`` (chaos.CrashPoints) arms the
    mid_drain cut line in the termination controller; the startup
    resync/orphan-adoption singleton (controllers/recovery.py) runs on
    shard 0 alongside the GC loops; ``fence`` (a leadership FencingToken)
    is applied to EVERY controller — including the cloud-mutating GC and
    recovery singletons — so a deposed leader's workers drop items instead
    of reconciling.

    ``tracker`` (providers.operations.OperationTracker): when the instance
    provider runs in non-blocking mode, completed create/delete operations
    are injected straight into the lifecycle workqueue (the early-wake
    seam) — a claim parked on ``Result(requeue_after=...)`` reconciles the
    tick its LRO resolves. Tracked operations are keyed by pool name ==
    claim name, so the injected request lands on the right shard's
    controller by construction (foreign shards never see the tracker).

    ``tracer`` (observability.Tracer): claimtrace wiring. Per-object
    controllers get a reconcile span seam (queue-wait + reconcile spans,
    trace/span ids in every log line and Event emitted underneath);
    singletons are excluded — their self-requeuing tick is not claim work.
    When a tracker is present its completions also back-fill the
    ``lro:create``/``lro:delete`` and LRO-side ``node-wait`` spans from the
    operation timestamps, which no coroutine awaits across (the whole point
    of non-blocking mode)."""
    if not 0 <= shard_index < shards:
        raise ValueError(f"shard_index {shard_index} outside [0, {shards})")
    # ``owns``: dynamic range-ownership predicate (a ShardLeaseTable's
    # ``owns`` in a multi-process worker) — supersedes the static crc32
    # partition. Unlike the static split it can CHANGE between enqueue and
    # dequeue (lease handoff), so claim-keyed controllers also re-check it
    # at dequeue (Controller.owns) and the singletons run per-range lessees
    # (``distribute_singletons``) instead of pinning to shard 0.
    dynamic_owns = owns is not None
    if owns is None:
        owns = (lambda name: True) if shards == 1 else \
            (lambda name: shard_owns(name, shards, shard_index))

    def claim_map(nc) -> list[Request]:
        name = nc.metadata.name
        return [Request(name=name)] if owns(name) else []

    def node_claim_map(node: Node) -> list[Request]:
        return [r for r in node_to_nodeclaim_requests(node)
                if owns(r.name)]

    def node_map(node: Node) -> list[Request]:
        key = _node_pool(node)
        # Pool-less nodes hash by their own name — routing them ALL to
        # shard 0 (the old rule) piled every unlabeled node onto the shard
        # that already runs both GC loops, recovery, and slice-group
        # assignment (measured as shard_queue_depth imbalance at 10k
        # claims). Any consistent owner works: these requests are keyed by
        # node name end to end, so no cross-shard correlation exists to
        # preserve.
        mine = owns(key) if key else owns(node.metadata.name)
        return [Request(name=node.metadata.name)] if mine else []

    # The wake graph: out-of-band completion sources (LRO resolution, the
    # status batcher's flush) fan into lifecycle's workqueue through the
    # hub; callers that pass their own hub (envtest, __main__) share it
    # with the provider's stockout parking.
    if wakehub is None:
        wakehub = WakeHub()
    # Announce the live event wake producers (gates the safety-net timer
    # diet — Result.wake_source parks skip their arm only for announced
    # sources): the Node watch is always wired into lifecycle below; LRO
    # completions only exist with a tracker; status-flush with a batcher.
    wakehub.announce(SOURCE_NODE)
    if tracker is not None:
        wakehub.announce(SOURCE_LRO)
    if status_batcher is not None:
        wakehub.announce(SOURCE_STATUS_FLUSH)
    lifecycle = NodeClaimLifecycleController(client, cloudprovider, recorder,
                                            lifecycle_options, tracer=tracer,
                                            status_batcher=status_batcher)
    eviction = EvictionQueue(client, recorder=recorder)
    termination = NodeTerminationController(client, cloudprovider, eviction,
                                            recorder, termination_options,
                                            crashes=crashes)

    hardening = dict(reconcile_timeout=reconcile_timeout,
                     max_retries=max_retries)
    lifecycle_controller = (
        Controller(lifecycle.NAME, lifecycle,
                   max_concurrent=max_concurrent_reconciles, **hardening)
        .watches(NodeClaim, map_fn=claim_map)
        # Node events are wake-ups for claims parked on registration/
        # initialization requeues — label them so idle-gap attribution
        # (and the wakes counter) sees "node", not generic "watch".
        .watches(Node, map_fn=node_claim_map, wake_source=SOURCE_NODE))
    wakehub.register(lifecycle_controller.inject)
    if tracker is not None:
        # early wake: tracked-operation completion → hub → lifecycle
        # workqueue, labeled "lro" for attribution
        tracker.subscribe(lambda op: wakehub.wake(op.name, SOURCE_LRO))
    if tracker is not None and tracer is not None:
        tracker.subscribe(lambda op: _record_operation_spans(tracer, op))
    controllers = [
        lifecycle_controller,
        Controller(termination.NAME, termination, max_concurrent=16,
                   **hardening)
        .watches(Node, map_fn=node_map),
    ]
    slicegroup_map = group_requests
    if distribute_singletons:
        # Per-range lessees instead of shard-0 pins: every worker runs the
        # GC/recovery/slice-group loops over ITS OWNED RANGE ONLY — the
        # owns predicate filters both cloud listings (GC/recovery) and the
        # group-keyed watch map (slice-group). A dead worker's range moves
        # with its leases, so its GC debt is adopted, not orphaned.
        if gc_options is None:
            gc_options = GCOptions()
        if recovery_options is None:
            recovery_options = RecoveryOptions()
        gc_options.owns = owns
        recovery_options.owns = owns

        def slicegroup_map(obj, _owns=owns):  # noqa: F811 — scoped override
            return [r for r in group_requests(obj) if _owns(r.name)]
    if shard_index == 0 or distribute_singletons:
        instance_gc = InstanceGCController(client, cloudprovider, gc_options)
        nodeclaim_gc = NodeClaimGCController(client, cloudprovider,
                                             gc_options)
        recovery = RecoveryController(client, cloudprovider, recovery_options,
                                      recorder=recorder, tracer=tracer)
        controllers += [
            Controller(instance_gc.NAME, Singleton(instance_gc.run_once),
                       max_concurrent=1).as_singleton(),
            Controller(nodeclaim_gc.NAME, Singleton(nodeclaim_gc.run_once),
                       max_concurrent=1).as_singleton(),
            # boot-time resync: the singleton request fires at manager
            # start, i.e. immediately after leadership is won
            Controller(recovery.NAME, Singleton(recovery.run_once),
                       max_concurrent=1).as_singleton(),
            Controller(SliceGroupController.NAME,
                       SliceGroupController(client, cluster=cluster),
                       max_concurrent=4, **hardening)
            .watches(Node, map_fn=slicegroup_map)
            .watches(NodeClaim, map_fn=slicegroup_map),
        ]
    # Node health only with repair policies + gate (controllers.go:110-113).
    # Repair drains through the SAME eviction queue the termination
    # controller owns (drain-first escalation), and carries the mid_repair
    # crash cut line.
    if node_repair and cloudprovider.repair_policies():
        health = NodeHealthController(client, cloudprovider, recorder,
                                      health_options, eviction=eviction,
                                      crashes=crashes)
        controllers.append(
            Controller(health.NAME, health, max_concurrent=8, **hardening)
            .watches(Node, map_fn=node_map))
    exhausted_hook = _make_exhausted_hook(client, recorder)
    trace_seam = None
    if tracer is not None:
        trace_seam = (lambda name, req, queue_wait, wake_source=None:
                      tracer.reconcile_span(name, req.name,
                                            queue_wait=queue_wait,
                                            wake_source=wake_source))
    for c in controllers:
        c.set_metrics_hook(_reconcile_metrics_hook)
        c.set_exhausted_hook(exhausted_hook)
        c.fence = fence
        c.shard_index = shard_index  # labels the shard queue-depth gauge
        c.wake_hub = wakehub  # gates the Result.wake_source timer-arm skip
        # Dequeue-time ownership fence, dynamic partitions only: applied to
        # the controllers whose REQUEST KEY is the partition key (claim
        # name for lifecycle, group name for slice-group) — node-keyed
        # controllers shard by pool label, which the dequeue-side check
        # cannot recompute from the request alone.
        if dynamic_owns and c.name in (lifecycle.NAME,
                                       SliceGroupController.NAME):
            c.owns = owns
        # singletons reconcile a synthetic tick, not a claim — tracing
        # them would grow one junk trace per singleton name
        if trace_seam is not None and not c.singleton:
            c.set_trace_seam(trace_seam)
    return controllers, eviction


async def _record_operation_spans(tracer, op) -> None:
    """Back-fill LRO spans from tracked-operation timestamps: nothing awaits
    across an LRO in non-blocking mode, so there is no coroutine to wrap —
    the spans are reconstructed when the tracker resolves the operation. A
    create op completes only once its nodes carry providerIDs; lro_done_at
    (first RUNNING/RECONCILING poll) splits that wait into the LRO proper
    and the node-join tail."""
    end = op.completed_at
    if not end:
        return
    lro_end = op.lro_done_at or end
    tracer.record_span(op.name, f"lro:{op.kind}", op.started, lro_end,
                       reason=op.reason, phase=op.phase)
    if op.lro_done_at and end > op.lro_done_at:
        tracer.record_span(op.name, "node-wait", op.lro_done_at, end,
                           hosts=op.hosts)


def _reconcile_metrics_hook(controller: str, duration: float,
                            err: Optional[str]) -> None:
    record_reconcile_duration(controller, duration)
    if err == "ReconcileTimeout":
        RECONCILE_TIMEOUTS.labels(controller).inc()


def _make_exhausted_hook(client: Client, recorder: Optional[Recorder]):
    async def hook(controller: str, req, failures: int) -> None:
        RECONCILE_RETRIES_EXHAUSTED.labels(controller).inc()
        if recorder is None:
            return
        try:
            nc = await client.get(NodeClaim, req.name)
        except Exception:  # noqa: BLE001 — Node-keyed or deleted: no event
            return
        await recorder.publish(
            nc, "Warning", "ReconcileRetriesExhausted",
            f"{controller} gave up fast retries after {failures} failures; "
            f"degrading to slow retry")
    return hook
