"""Slice-group identity controller: converge multi-slice labels on nodes.

The instance provider stamps per-pool identity at create
(providers/instance.py:_slice_group_identity): slice-index is sticky and
never rewritten here, but the *group-wide* facts — num-slices and the
coordinator (worker 0 of slice 0) — change as membership changes: a member
joining an existing group, or the slice-0 pool being deleted and replaced
under a new claim name. Pool labels are only applied to nodes at join, so
this controller re-stamps the *Node* labels (what workloads consume via
``SliceTopology.from_node_labels``) whenever the group drifts.

Reconcile key = the slice-group name; Node/NodeClaim watch events map to
their group. Extends the reference's create-time label seam
(/root/reference/pkg/providers/instance/instance.go:321-369) with the
continuous label sync of
vendor/sigs.k8s.io/karpenter/pkg/controllers/nodeclaim/lifecycle/registration.go:120-147,
applied at group scope.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..apis.karpenter import NodeClaim
from ..runtime import Request, Result
from ..runtime.client import Client, patch_retry

log = logging.getLogger("controllers.slicegroup")


def group_requests(obj) -> list[Request]:
    group = obj.metadata.labels.get(wk.TPU_SLICE_GROUP_LABEL, "")
    return [Request(name=group)] if group else []


class SliceGroupController:
    NAME = "slicegroup.identity"

    def __init__(self, client: Client, cluster: str = "kaito",
                 resync_seconds: float = 60.0):
        self.client = client
        self.cluster = cluster
        self.resync = resync_seconds

    async def reconcile(self, req: Request) -> Result:
        group = req.name
        nodes = await self.client.list(
            Node, labels={wk.TPU_SLICE_GROUP_LABEL: group})
        if not nodes:
            return Result()

        # sticky per-pool indices, read back from the nodes themselves
        pool_index: dict[str, int] = {}
        for n in nodes:
            pool = (n.metadata.labels.get(wk.TPU_SLICE_ID_LABEL)
                    or n.metadata.labels.get(wk.GKE_NODEPOOL_LABEL, ""))
            idx = n.metadata.labels.get(wk.TPU_SLICE_INDEX_LABEL, "")
            if pool and idx.isdigit():
                pool_index[pool] = int(idx)
        if not pool_index:
            return Result()

        claims = await self.client.list(
            NodeClaim, labels={wk.TPU_SLICE_GROUP_LABEL: group})
        declared = 0
        for c in claims:
            d = c.metadata.labels.get(wk.TPU_NUM_SLICES_LABEL, "")
            if d.isdigit():
                declared = max(declared, int(d))
        num_slices = declared or max(len(pool_index), len(claims),
                                     max(pool_index.values()) + 1)

        from ..providers.instance import instance_name

        desired = {wk.TPU_NUM_SLICES_LABEL: str(num_slices)}
        drop: list[str] = []
        owner0 = next((p for p, i in pool_index.items() if i == 0), None)
        if owner0 is not None:
            # worker 0 of the slice-0 pool, via the one naming-convention seam
            desired[wk.TPU_COORDINATOR_LABEL] = instance_name(
                self.cluster, owner0, 0)
        else:
            # Slice 0 is gone (deleted, or mid-repair): a stale coordinator
            # label would point workloads at a dead host — strip it until a
            # replacement pool takes index 0 and gets re-stamped.
            drop.append(wk.TPU_COORDINATOR_LABEL)

        for n in nodes:
            if (all(n.metadata.labels.get(k) == v for k, v in desired.items())
                    and not any(k in n.metadata.labels for k in drop)):
                continue

            def mutate(obj, _desired=desired, _drop=drop):
                changed = False
                for k, v in _desired.items():
                    if obj.metadata.labels.get(k) != v:
                        obj.metadata.labels[k] = v
                        changed = True
                for k in _drop:
                    if k in obj.metadata.labels:
                        del obj.metadata.labels[k]
                        changed = True
                return True if changed else False

            await patch_retry(self.client, Node, n.metadata.name, mutate)
            log.info("slice-group %s: synced identity labels onto node %s "
                     "(%s%s)", group, n.metadata.name, desired,
                     f", dropped {drop}" if drop else "")

        # periodic resync guards against missed watch events (group members
        # appear via pool joins the Node watch does see, but cheap insurance)
        # wakes: node — watch-driven; this resync timer is the insurance
        return Result(requeue_after=self.resync)
