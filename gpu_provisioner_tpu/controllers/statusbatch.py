"""StatusWriteBatcher: coalesce per-claim meta+status patches.

BENCH_pr02 flagged the per-claim patch storm (906 ``nodepools.get``-era
call profile); every lifecycle reconcile ends in ``_flush_status`` — up to
two writes per lap, each bumping resourceVersion and fanning a watch event
back into every shard's pump. During a wave a claim reconciles many times
in quick succession (launch, registration laps, initialization laps), and
only the LAST state matters to any reader: coalescing those writes inside
a short flush window cuts both the kube-call volume and the self-inflicted
watch-event churn out of the wave hot path.

Semantics, in priority order:

- **Latest-wins per claim.** ``submit`` replaces any pending snapshot for
  the same claim; the flush writes one meta patch + one status patch per
  claim per window, maximum.
- **Meta before status.** The same invariant ``_flush_status`` documents:
  Ready must never be observable while launch-merged labels are unwritten.
  Preserved per claim because the flush calls :func:`write_claim_patches`,
  which orders the two patches, not because of batch ordering.
- **Fence-checked at flush.** Acceptance into the batch is cheap and
  unfenced; the fence (assigned post-election, like the provider's) is
  checked when the batch actually writes. A deposed leader drops its
  pending batch on the floor — the new leader's reconciles rebuild the
  same status from fresh state, exactly like the worker-level fence drop.
- **Self-clocking window.** The next flush window stretches to the last
  flush's duration (capped at ``max_window``, group-commit style): a
  small fleet's ms flushes leave the base window untouched, a mega-wave
  backlog whose flush takes seconds widens the window so the condition
  cascade (Registered → Initialized → Ready) coalesces instead of
  writing once per lap.
- **Crash-adoptable.** Pending snapshots live only in this process; a
  crash between accept and flush simply loses them. That is safe by the
  same argument as the fence drop: status is *derived* state — recovery
  adoption re-reconciles every claim from the store + cloud truth and
  re-materializes whatever the lost flush would have written.

Direct writes remain available for paths that must not race a delayed
flush (terminal failures that delete the claim right after writing):
``lifecycle._flush_status(nc, direct=True)`` drops any pending snapshot
and writes synchronously through the same helper.
"""

from __future__ import annotations

import asyncio
import contextlib
import copy
import logging
from typing import Optional

from ..apis.karpenter import NodeClaim
from ..runtime import NotFoundError, apihealth, probes
from ..runtime.client import Client, ConflictError, patch_retry
from ..runtime.wakehub import SOURCE_STATUS_FLUSH

log = logging.getLogger("controllers.statusbatch")


async def write_claim_patches(client: Client, nc: NodeClaim,
                              tracer=None) -> bool:
    """Write ``nc``'s meta (additive label/annotation merge) then status
    onto the stored claim; returns True if either patch actually wrote.

    This is ``lifecycle._flush_status``'s write path, extracted so the
    batcher and the direct path share one implementation of the two
    load-bearing invariants: no-op suppression (a no-op write would bump
    resourceVersion → watch event → another reconcile, a self-sustaining
    hot loop) and meta-before-status ordering (conditions, incl. Ready,
    must never be observable while launch-merged labels are unwritten —
    ``_launch`` never re-merges once Launched persists).
    """
    wrote = {"any": False}

    def copy_status(obj):
        if obj.status == nc.status:
            return False
        obj.status = nc.status
        wrote["any"] = True

    def copy_meta(obj):
        # Additive merge, NEVER wholesale replace: a concurrent reconcile
        # whose snapshot predates the launch label-merge must not clobber
        # the labels launch just flushed (a real lost update — claim Ready
        # without its topology labels).
        changed = False
        for k, v in nc.metadata.labels.items():
            if obj.metadata.labels.get(k) != v:
                obj.metadata.labels[k] = v
                changed = True
        for k, v in nc.metadata.annotations.items():
            if obj.metadata.annotations.get(k) != v:
                obj.metadata.annotations[k] = v
                changed = True
        if changed:
            wrote["any"] = True
        return None if changed else False

    span = (tracer.span(nc.metadata.name, "status-write")
            if tracer is not None else contextlib.nullcontext())
    try:
        with span:
            await patch_retry(client, NodeClaim, nc.metadata.name, copy_meta)
            probes.emit("meta-patch", nc.metadata.name)
            await patch_retry(client, NodeClaim, nc.metadata.name,
                              copy_status, status=True)
            probes.emit("status-patch", nc.metadata.name)
    except ConflictError:
        pass  # next reconcile sees fresh state
    return wrote["any"]


class StatusWriteBatcher:
    """Window-coalescing writer for NodeClaim meta+status patches.

    One background task; wake-on-submit then sleep ``window`` so a wave's
    burst of submits for the same claim collapses into one write. Started
    and stopped by the boot path / envtest alongside the tracker (the
    envtest leak gate enumerates ``_task``).
    """

    def __init__(self, client: Client, window: float = 0.05, fence=None,
                 tracer=None, wakehub=None, max_window: float = 1.0):
        self.client = client
        self.window = window
        # Self-clocking ceiling (group-commit style): the NEXT window
        # stretches to the duration of the LAST flush, capped here. A small
        # fleet's ms flushes never move it; a 10k-claim backlog whose flush
        # takes seconds widens the window so a claim's Registered →
        # Initialized → Ready cascade coalesces into one write instead of
        # three. The cost is bounded extra status latency under exactly the
        # load where per-write churn hurts most.
        self.max_window = max_window
        self._last_flush_s = 0.0
        # Like the provider/controller fences: assigned post-election by
        # the boot path; None means unfenced (tests, single-process).
        self.fence = fence
        self.tracer = tracer
        self.wakehub = wakehub
        # APIHealthGovernor, assigned post-construction like the fence.
        # Status writes shed FIRST under apiserver distress: the window
        # widens by the governor's factor (more coalescing, fewer writes)
        # and each write is paced — deferred, never dropped.
        self.governor = None
        self.shed_windows = 0
        self._pending: dict[str, NodeClaim] = {}
        self._wake = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self.submitted = 0
        self.coalesced = 0
        self.flushes = 0
        self.fence_dropped = 0
        self.writes = 0
        self.retried = 0

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="status-batcher")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # Final drain: flush whatever was accepted but not yet written so a
        # clean shutdown loses nothing (a crash legitimately does — see the
        # module docstring's crash-adoptable contract). Bounded retries:
        # a transient write error leaves its entry pending, and with the
        # run task gone nothing else would drain it.
        for _ in range(3):
            if not self._pending:
                break
            await self._flush_round()

    async def submit(self, nc: NodeClaim) -> None:
        """Accept a claim snapshot for the next flush window; latest wins."""
        self.submitted += 1
        if nc.metadata.name in self._pending:
            self.coalesced += 1
        self._pending[nc.metadata.name] = nc
        self._wake.set()

    def drop(self, name: str) -> None:
        """Forget any pending snapshot for ``name`` — the direct-write path
        calls this first so a stale batched flush cannot land AFTER the
        synchronous write it bypassed the window for."""
        self._pending.pop(name, None)

    def overlay(self, obj: NodeClaim) -> NodeClaim:
        """Read-your-batched-writes: apply the pending snapshot for this
        claim onto a fresh GET. Without this, a reconcile inside the flush
        window would see pre-batch status (e.g. Launched not yet True) and
        redo work — the ``_launched`` UID cache backstops launch, but
        every sub-reconciler would churn. Spec and deletion_timestamp stay
        the GET's own (the batcher never owns those); the status is
        deep-copied so the reconcile's mutations don't alias the pending
        snapshot mid-flush."""
        pend = self._pending.get(obj.metadata.name)
        if pend is None:
            return obj
        for k, v in pend.metadata.labels.items():
            obj.metadata.labels[k] = v
        for k, v in pend.metadata.annotations.items():
            obj.metadata.annotations[k] = v
        obj.status = copy.deepcopy(pend.status)
        return obj

    def pending(self) -> int:
        return len(self._pending)

    def _next_window(self) -> float:
        """Base window, stretched to the last flush's duration (capped at
        ``max_window``) — flush cost is the load signal. Under a degraded
        apiserver the governor's factor widens it further: status is the
        least-durable write class (always re-derivable from a reconcile),
        so it sheds before meta or cloud mutations slow down at all."""
        base = max(self.window, min(self._last_flush_s, self.max_window))
        if self.governor is not None:
            factor = self.governor.status_window_factor()
            if factor > 1.0:
                self.shed_windows += 1
                apihealth.note_shed()
                return min(base * factor, self.max_window * factor)
        return base

    async def _run(self) -> None:
        while True:
            await self._wake.wait()
            await asyncio.sleep(self._next_window())
            # Clear BEFORE draining: a submit that lands during the flush
            # re-arms the event and gets the NEXT window, never lost.
            self._wake.clear()
            if not self._pending:
                continue
            await self._flush_round()

    async def _flush_round(self) -> None:
        """Flush a snapshot view of the pending map, WITHOUT popping it
        first: a flush under load runs for seconds, and a reconcile landing
        mid-flush must still see its claim through ``overlay()`` — popping
        up front blinded it, so that reconcile re-derived conditions from
        the stale store and re-stamped their lastTransitionTimes, a
        spurious extra status write per claim per flush-race. Entries are
        removed only after they flush, and only if no newer submit
        superseded them (latest-wins holds throughout)."""
        batch = dict(self._pending)
        done = await self._flush(batch)
        for name in done:
            if self._pending.get(name) is batch[name]:
                self._pending.pop(name)

    async def _flush(self, batch: dict[str, NodeClaim]) -> set[str]:
        """Write every snapshot in ``batch``; returns the names that are
        DONE (written, no-op, deleted, or fence-dropped). Names that hit a
        transient error are excluded — their entries stay pending and the
        re-armed wake retries them next window."""
        self.flushes += 1
        t0 = asyncio.get_event_loop().time()
        try:
            return await self._flush_inner(batch)
        finally:
            self._last_flush_s = asyncio.get_event_loop().time() - t0

    async def _flush_inner(self, batch: dict[str, NodeClaim]) -> set[str]:
        if self.fence is not None and not self.fence.valid():
            # Deposed: the new leader's reconciles own status now. Dropping
            # is correct for the same reason the worker fence drop is.
            self.fence_dropped += len(batch)
            return set(batch)
        sem = asyncio.Semaphore(64)
        done: set[str] = set()

        async def one(nc: NodeClaim) -> None:
            async with sem:
                try:
                    if self.governor is not None:
                        # paced, never dropped: the meta+status pair rides
                        # the same AIMD limit the reconcile workers do
                        await self.governor.pace()
                    changed = await write_claim_patches(self.client, nc,
                                                        tracer=self.tracer)
                except NotFoundError:
                    done.add(nc.metadata.name)  # claim deleted since accept
                    return
                except Exception:
                    # Transient apiserver error (e.g. chaos-injected 5xx).
                    # The inline path got retries for free — the error
                    # propagated out of reconcile and the controller
                    # requeued with backoff. The batcher has no reconcile
                    # to lean on, so its entry stays pending and the next
                    # window retries it (latest-wins: a newer submit
                    # supersedes the failed snapshot). Crucially the
                    # batcher task must NOT die: one dropped flush loses a
                    # write, a dead batcher loses them all.
                    log.warning("status flush for %s failed; retrying "
                                "next window", nc.metadata.name,
                                exc_info=True)
                    self.retried += 1
                    self._wake.set()
                    return
                done.add(nc.metadata.name)
                if changed:
                    self.writes += 1
                    if self.wakehub is not None:
                        await self.wakehub.wake(nc.metadata.name,
                                                SOURCE_STATUS_FLUSH)

        await asyncio.gather(*(one(nc) for nc in batch.values()))
        return done
