"""Node termination controller + Terminator + eviction queue (V7).

Re-creates the node-finalizer flow of vendor/.../controllers/node/termination/:
taint ``karpenter.sh/disrupted:NoSchedule`` (controller.go:135-141), drain the
pods through a rate-limited eviction queue (terminator/terminator.go:96-117,
eviction.go:93-140), await volume detachment, await instance termination, then
remove the node finalizer (controller.go:143-190). Drain short-circuits when
the backing instance is already gone (controller.go:117-127) and when the
NodeClaim's termination-grace deadline has passed.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node, Pod, Taint, VolumeAttachment
from ..apis.karpenter import DRAINED, NodeClaim, VOLUMES_DETACHED
from ..apis.serde import now, parse_time
from ..errors import NodeClaimNotFoundError
from ..runtime import NotFoundError, Request, Result
from ..runtime.client import (Client, ConflictError, EvictionBlockedError,
                              patch_retry)
from ..runtime.events import NORMAL, WARNING, Recorder
from .utils import nodeclaim_for_node

log = logging.getLogger("controllers.termination")


class EvictionQueue:
    """Rate-limited pod evictor (terminator/eviction.go:93-140) over the
    Client.evict seam: a plain delete in-process, the policy/v1 Eviction
    subresource against a real apiserver.

    Failure handling matches the reference's rate-limiter composition
    (eviction.go:57-58,131-136): per-pod exponential backoff from BASE_DELAY
    capped at MAX_DELAY, layered under a global QPS limit. A pod blocked by a
    PodDisruptionBudget gets a Warning event once the blockage persists
    (NodeFailedToDrain analog, eviction.go:199-207) and keeps retrying at the
    capped delay — retry-forever is deliberate: the termination controller's
    grace-deadline escalation (_grace_expired) bounds how long a stuck drain
    can hold the node, so the queue never has to guess when to give up.
    Entries are keyed by (namespace, name, uid) so a replacement pod reusing
    the name is never evicted by a stale entry (eviction.go:162-168)."""

    BASE_DELAY = 0.1     # eviction.go:57 evictionQueueBaseDelay
    MAX_DELAY = 10.0     # eviction.go:58 evictionQueueMaxDelay
    WARN_AFTER = 3       # consecutive PDB blocks before the Warning event

    def __init__(self, client: Client, qps: float = 10.0,
                 recorder: Optional[Recorder] = None):
        self.client = client
        self.recorder = recorder
        self.interval = 1.0 / qps
        self._pods: dict[tuple[str, str, str], Pod] = {}
        self._failures: dict[tuple[str, str, str], int] = {}
        self._q: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._timers: set[asyncio.Task] = set()

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="eviction-queue")

    async def stop(self) -> None:
        for t in list(self._timers):
            t.cancel()
        self._timers.clear()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        # Entries parked in cancelled timers would otherwise dedup their pods
        # out of any future enqueue; the next drain pass re-discovers them.
        self._pods.clear()
        self._failures.clear()
        self._q = asyncio.Queue()

    def enqueue(self, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name, pod.metadata.uid)
        if key not in self._pods:
            self._pods[key] = pod
            self._q.put_nowait(key)

    def _done(self, key: tuple[str, str, str]) -> None:
        self._pods.pop(key, None)
        self._failures.pop(key, None)

    def _requeue_later(self, key: tuple[str, str, str]) -> None:
        fails = self._failures[key] = self._failures.get(key, 0) + 1
        delay = min(self.MAX_DELAY, self.BASE_DELAY * 2 ** (fails - 1))

        async def timer() -> None:
            await asyncio.sleep(delay)
            if key in self._pods:
                self._q.put_nowait(key)

        t = asyncio.create_task(timer())
        self._timers.add(t)
        t.add_done_callback(self._timers.discard)

    async def _warn_blocked(self, pod: Pod, err: Exception, fails: int) -> None:
        if self.recorder is None or fails < self.WARN_AFTER:
            return
        # Warn at the threshold, then on a doubling schedule (3, 6, 12, 24
        # attempts, ...): a long-blocked drain stays visible in events
        # without paying the recorder's get+update apiserver round-trip on
        # every ~10s capped-delay retry for the whole blocked duration.
        times_over, rem = divmod(fails, self.WARN_AFTER)
        if rem or (times_over & (times_over - 1)):
            return
        await self.recorder.publish(
            pod, WARNING, "FailedDraining",
            f"Failed to evict pod after {fails} attempts: {err}")

    async def _run(self) -> None:
        while True:
            key = await self._q.get()
            pod = self._pods.get(key)
            if pod is None:
                continue
            ns, name, uid = key
            try:
                await self.client.evict(name, ns, uid=uid)
            except (NotFoundError, ConflictError):
                # 404: already gone. 409: replaced by a different pod under
                # the same name — not ours to evict (eviction.go:189-194).
                self._done(key)
            except EvictionBlockedError as e:
                self._requeue_later(key)
                await self._warn_blocked(pod, e, self._failures[key])
            except Exception as e:  # noqa: BLE001 — backoff on transient errors
                log.warning("evicting %s/%s: %s", ns, name, e)
                self._requeue_later(key)
            else:
                if self.recorder is not None:
                    await self.recorder.publish(pod, NORMAL, "Evicted",
                                                "Evicted pod")
                self._done(key)
            await asyncio.sleep(self.interval)


async def taint_disrupted(client: Client, node: Node) -> None:
    """Cordon-taint a node ``karpenter.sh/disrupted:NoSchedule``
    (controller.go:135-141). Shared by node termination and node repair —
    repair's drain-first escalation cordons through the same seam so the
    scheduler sees one disruption vocabulary."""
    def mutate(n: Node):
        if any(t.key == wk.DISRUPTED_TAINT for t in n.spec.taints):
            return False
        n.spec.taints.append(Taint(key=wk.DISRUPTED_TAINT, effect="NoSchedule"))
    await patch_retry(client, Node, node.metadata.name, mutate)


async def drain_node(client: Client, queue: EvictionQueue, node: Node) -> bool:
    """Evict all drainable pods on ``node``; True when none remain
    (terminator.go:96-117). Daemonset pods and terminal pods are skipped;
    higher-priority pods are evicted only after lower-priority ones are gone
    (the reference drains in priority waves). One home for the drain pass:
    the termination controller's finalizer flow and the health controller's
    drain-first repair escalation both route evictions through here."""
    pods = [p for p in await client.list(Pod)
            if p.spec.node_name == node.metadata.name
            and not p.is_owned_by_daemonset() and not p.is_terminal()]
    if not pods:
        return True
    min_priority = min(p.spec.priority for p in pods)
    for p in pods:
        if p.spec.priority == min_priority:
            queue.enqueue(p)
    return False


@dataclass
class TerminationOptions:
    requeue: float = 1.0
    instance_requeue: float = 5.0
    volume_detach_timeout: float = 60.0


class NodeTerminationController:
    NAME = "node.termination"

    def __init__(self, client: Client, cloudprovider, queue: EvictionQueue,
                 recorder: Optional[Recorder] = None,
                 options: Optional[TerminationOptions] = None,
                 crashes=None):
        self.client = client
        self.cp = cloudprovider
        self.queue = queue
        self.recorder = recorder
        self.opts = options or TerminationOptions()
        # chaos.CrashPoints (None in production): the mid_drain cut line —
        # evictions queued in-memory, drain unfinished — lives here because
        # the eviction queue's parked state is exactly what a crash loses.
        self.crashes = crashes

    def _crash(self, point: str, key: str) -> None:
        if self.crashes is not None:
            self.crashes.hit(point, key)

    async def reconcile(self, req: Request) -> Result:
        try:
            node = await self.client.get(Node, req.name)
        except NotFoundError:
            return Result()
        if (node.metadata.deletion_timestamp is None
                or wk.TERMINATION_FINALIZER not in node.metadata.finalizers):
            return Result()

        await self._taint_disrupted(node)
        nc = await nodeclaim_for_node(self.client, node)

        # Node-initiated teardown cascades to the owning NodeClaim (the
        # reference e2e relies on this: deleting a Node unwinds everything,
        # suite_test.go:252,529) — the claim's finalize then deletes the
        # instance, which is what lets _instance_gone flip below.
        if nc is not None and nc.metadata.deletion_timestamp is None:
            try:
                await self.client.delete(NodeClaim, nc.metadata.name)
            except NotFoundError:
                pass

        if not await self._instance_gone(node):
            if not self._grace_expired(nc):
                drained = await self._drain(node)
                if not drained:
                    # cut line: pods are parked in the in-memory eviction
                    # queue and nothing durable records the drain progress
                    self._crash("mid_drain", node.metadata.name)
                if nc is not None:
                    await self._set_cond(nc, DRAINED, drained, "Draining")
                if not drained:
                    # wakes: timer — eviction progress has no watch event
                    return Result(requeue_after=self.opts.requeue)

                detached = await self._volumes_detached(node)
                if nc is not None:
                    await self._set_cond(nc, VOLUMES_DETACHED, detached, "AwaitingDetach")
                if not detached and not self._detach_timed_out(node):
                    # wakes: timer — volume detach is polled, not watched
                    return Result(requeue_after=self.opts.requeue)

            # Grace expiry abandons the drain, never the instance wait: the
            # finalizer must not drop while the TPU VM is alive or the kubelet
            # re-registers the Node. NodeClaim finalize drives the delete.
            if not await self._instance_gone(node):
                # wakes: timer — the delete LRO wakes the claim's finalize
                # (lro), not this Node-keyed wait; the poll is the primary
                return Result(requeue_after=self.opts.instance_requeue)

        def drop(obj: Node):
            if wk.TERMINATION_FINALIZER not in obj.metadata.finalizers:
                return False
            obj.metadata.finalizers.remove(wk.TERMINATION_FINALIZER)
        await patch_retry(self.client, Node, node.metadata.name, drop)
        return Result()

    async def _taint_disrupted(self, node: Node) -> None:
        await taint_disrupted(self.client, node)

    async def _instance_gone(self, node: Node) -> bool:
        if not node.spec.provider_id:
            return True
        try:
            await self.cp.get(node.spec.provider_id)
            return False
        except NodeClaimNotFoundError:
            return True

    def _grace_expired(self, nc: Optional[NodeClaim]) -> bool:
        """Past the termination-grace deadline, drain is abandoned
        (terminator checks the annotation stamped by the lifecycle finalize)."""
        if nc is None:
            return False
        raw = nc.metadata.annotations.get(wk.TERMINATION_TIMESTAMP_ANNOTATION)
        if not raw:
            return False
        try:
            return now() >= parse_time(raw)
        except ValueError:
            return False

    async def _drain(self, node: Node) -> bool:
        return await drain_node(self.client, self.queue, node)

    async def _volumes_detached(self, node: Node) -> bool:
        attachments = [va for va in await self.client.list(VolumeAttachment)
                       if va.spec.node_name == node.metadata.name]
        return not attachments

    def _detach_timed_out(self, node: Node) -> bool:
        dt = node.metadata.deletion_timestamp
        return dt is not None and (now() - dt).total_seconds() > self.opts.volume_detach_timeout

    async def _set_cond(self, nc: NodeClaim, ctype: str, ok: bool, reason: str) -> None:
        def mutate(obj: NodeClaim):
            cs = obj.status_conditions
            before = [c.status for c in obj.status.conditions if c.type == ctype]
            if ok:
                cs.set_true(ctype, ctype)
            else:
                cs.set_false(ctype, reason)
            after = [c.status for c in obj.status.conditions if c.type == ctype]
            return None if before != after else False
        try:
            await patch_retry(self.client, NodeClaim, nc.metadata.name, mutate,
                              status=True)
        except NotFoundError:
            pass
