"""Node termination controller + Terminator + eviction queue (V7).

Re-creates the node-finalizer flow of vendor/.../controllers/node/termination/:
taint ``karpenter.sh/disrupted:NoSchedule`` (controller.go:135-141), drain the
pods through a rate-limited eviction queue (terminator/terminator.go:96-117,
eviction.go:93-140), await volume detachment, await instance termination, then
remove the node finalizer (controller.go:143-190). Drain short-circuits when
the backing instance is already gone (controller.go:117-127) and when the
NodeClaim's termination-grace deadline has passed.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node, Pod, Taint, VolumeAttachment
from ..apis.karpenter import DRAINED, NodeClaim, VOLUMES_DETACHED
from ..apis.serde import now, parse_time
from ..errors import NodeClaimNotFoundError
from ..runtime import NotFoundError, Request, Result
from ..runtime.client import Client, patch_retry
from ..runtime.events import Recorder
from .utils import nodeclaim_for_node

log = logging.getLogger("controllers.termination")


class EvictionQueue:
    """Rate-limited pod evictor (terminator/eviction.go:93-140) over the
    Client.evict seam: a plain delete in-process, the policy/v1 Eviction
    subresource against a real apiserver (PDB-aware; 429s requeue)."""

    def __init__(self, client: Client, qps: float = 10.0):
        self.client = client
        self.interval = 1.0 / qps
        self._queued: set[tuple[str, str]] = set()
        self._q: asyncio.Queue = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run(), name="eviction-queue")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def enqueue(self, pod: Pod) -> None:
        key = (pod.metadata.namespace, pod.metadata.name)
        if key not in self._queued:
            self._queued.add(key)
            self._q.put_nowait(key)

    async def _run(self) -> None:
        while True:
            ns, name = await self._q.get()
            try:
                await self.client.evict(name, ns)
            except NotFoundError:
                self._queued.discard((ns, name))  # already gone — allow re-use
            except Exception as e:  # noqa: BLE001 — requeue on transient errors
                log.warning("evicting %s/%s: %s", ns, name, e)
                self._q.put_nowait((ns, name))
            else:
                self._queued.discard((ns, name))
            await asyncio.sleep(self.interval)


@dataclass
class TerminationOptions:
    requeue: float = 1.0
    instance_requeue: float = 5.0
    volume_detach_timeout: float = 60.0


class NodeTerminationController:
    NAME = "node.termination"

    def __init__(self, client: Client, cloudprovider, queue: EvictionQueue,
                 recorder: Optional[Recorder] = None,
                 options: Optional[TerminationOptions] = None):
        self.client = client
        self.cp = cloudprovider
        self.queue = queue
        self.recorder = recorder
        self.opts = options or TerminationOptions()

    async def reconcile(self, req: Request) -> Result:
        try:
            node = await self.client.get(Node, req.name)
        except NotFoundError:
            return Result()
        if (node.metadata.deletion_timestamp is None
                or wk.TERMINATION_FINALIZER not in node.metadata.finalizers):
            return Result()

        await self._taint_disrupted(node)
        nc = await nodeclaim_for_node(self.client, node)

        # Node-initiated teardown cascades to the owning NodeClaim (the
        # reference e2e relies on this: deleting a Node unwinds everything,
        # suite_test.go:252,529) — the claim's finalize then deletes the
        # instance, which is what lets _instance_gone flip below.
        if nc is not None and nc.metadata.deletion_timestamp is None:
            try:
                await self.client.delete(NodeClaim, nc.metadata.name)
            except NotFoundError:
                pass

        if not await self._instance_gone(node):
            if not self._grace_expired(nc):
                drained = await self._drain(node)
                if nc is not None:
                    await self._set_cond(nc, DRAINED, drained, "Draining")
                if not drained:
                    return Result(requeue_after=self.opts.requeue)

                detached = await self._volumes_detached(node)
                if nc is not None:
                    await self._set_cond(nc, VOLUMES_DETACHED, detached, "AwaitingDetach")
                if not detached and not self._detach_timed_out(node):
                    return Result(requeue_after=self.opts.requeue)

            # Grace expiry abandons the drain, never the instance wait: the
            # finalizer must not drop while the TPU VM is alive or the kubelet
            # re-registers the Node. NodeClaim finalize drives the delete.
            if not await self._instance_gone(node):
                return Result(requeue_after=self.opts.instance_requeue)

        def drop(obj: Node):
            if wk.TERMINATION_FINALIZER not in obj.metadata.finalizers:
                return False
            obj.metadata.finalizers.remove(wk.TERMINATION_FINALIZER)
        await patch_retry(self.client, Node, node.metadata.name, drop)
        return Result()

    async def _taint_disrupted(self, node: Node) -> None:
        def mutate(n: Node):
            if any(t.key == wk.DISRUPTED_TAINT for t in n.spec.taints):
                return False
            n.spec.taints.append(Taint(key=wk.DISRUPTED_TAINT, effect="NoSchedule"))
        await patch_retry(self.client, Node, node.metadata.name, mutate)

    async def _instance_gone(self, node: Node) -> bool:
        if not node.spec.provider_id:
            return True
        try:
            await self.cp.get(node.spec.provider_id)
            return False
        except NodeClaimNotFoundError:
            return True

    def _grace_expired(self, nc: Optional[NodeClaim]) -> bool:
        """Past the termination-grace deadline, drain is abandoned
        (terminator checks the annotation stamped by the lifecycle finalize)."""
        if nc is None:
            return False
        raw = nc.metadata.annotations.get(wk.TERMINATION_TIMESTAMP_ANNOTATION)
        if not raw:
            return False
        try:
            return now() >= parse_time(raw)
        except ValueError:
            return False

    async def _drain(self, node: Node) -> bool:
        """Evict all drainable pods; True when none remain
        (terminator.go:96-117). Daemonset pods and terminal pods are skipped;
        higher-priority pods are evicted only after lower-priority ones are
        gone (the reference drains in priority waves)."""
        pods = [p for p in await self.client.list(Pod)
                if p.spec.node_name == node.metadata.name
                and not p.is_owned_by_daemonset() and not p.is_terminal()]
        if not pods:
            return True
        min_priority = min(p.spec.priority for p in pods)
        for p in pods:
            if p.spec.priority == min_priority:
                self.queue.enqueue(p)
        return False

    async def _volumes_detached(self, node: Node) -> bool:
        attachments = [va for va in await self.client.list(VolumeAttachment)
                       if va.spec.node_name == node.metadata.name]
        return not attachments

    def _detach_timed_out(self, node: Node) -> bool:
        dt = node.metadata.deletion_timestamp
        return dt is not None and (now() - dt).total_seconds() > self.opts.volume_detach_timeout

    async def _set_cond(self, nc: NodeClaim, ctype: str, ok: bool, reason: str) -> None:
        def mutate(obj: NodeClaim):
            cs = obj.status_conditions
            before = [c.status for c in obj.status.conditions if c.type == ctype]
            if ok:
                cs.set_true(ctype, ctype)
            else:
                cs.set_false(ctype, reason)
            after = [c.status for c in obj.status.conditions if c.type == ctype]
            return None if before != after else False
        try:
            await patch_retry(self.client, NodeClaim, nc.metadata.name, mutate,
                              status=True)
        except NotFoundError:
            pass
