"""Shared controller helpers: ownership model, node↔nodeclaim correlation.

The patched ownership model is load-bearing (SURVEY.md §2b V11): a NodeClaim
is managed iff its NodeClassRef matches a supported NodeClass **or** it
carries the kaito workspace/ragengine labels
(vendor/.../utils/nodeclaim/nodeclaim.go:40-75).
"""

from __future__ import annotations

import re
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..apis.kaito import KaitoNodeClass
from ..apis.karpenter import NodeClaim
from ..runtime.client import Client

_SUPPORTED_NODECLASS_KINDS = {(KaitoNodeClass.API_VERSION.split("/")[0], KaitoNodeClass.KIND)}


def is_managed(nc: NodeClaim) -> bool:
    if wk.KAITO_WORKSPACE_LABEL in nc.metadata.labels:
        return True
    if wk.KAITO_RAGENGINE_LABEL in nc.metadata.labels:
        return True
    ref = nc.spec.node_class_ref
    return ref is not None and (ref.group, ref.kind) in _SUPPORTED_NODECLASS_KINDS


async def list_managed(client: Client) -> list[NodeClaim]:
    return [nc for nc in await client.list(NodeClaim) if is_managed(nc)]


def shard_owns(name: str, shards: int, shard_index: int) -> bool:
    """Claim-shard ownership: stable name-hash partitioning of the
    reconcile workload across operator replicas. The single asyncio event
    loop is the documented throughput ceiling above ~2048 concurrent
    claims (BENCH_NOTES_r04/r05); N shards run N processes, each owning
    the claims (and their nodes, keyed by pool name == claim name) whose
    crc32 lands on its index. crc32 is stable across processes and
    platforms — every replica computes the same partition independently,
    no coordination required."""
    import zlib
    return zlib.crc32(name.encode()) % shards == shard_index


async def slice_nodes(client: Client, nodeclaim_name: str) -> list[Node]:
    """All Node objects of a NodeClaim's slice, correlated by the GKE
    node-pool label (the analog of getNodesByName's agentpool-label match,
    reference instance.go:371-385). One node for single-host shapes, N for
    multi-host."""
    return await client.list(Node, labels={wk.GKE_NODEPOOL_LABEL: nodeclaim_name})


async def nodeclaim_for_node(client: Client, node: Node) -> Optional[NodeClaim]:
    """Correlate a Node back to its NodeClaim: owner reference first, then the
    slice-id/node-pool label, then providerID (reference correlates purely by
    providerID via a field index)."""
    for ref in node.metadata.owner_references:
        if ref.kind == NodeClaim.KIND:
            try:
                return await client.get(NodeClaim, ref.name)
            except Exception:
                return None
    pool = (node.metadata.labels.get(wk.TPU_SLICE_ID_LABEL)
            or node.metadata.labels.get(wk.GKE_NODEPOOL_LABEL))
    if pool:
        try:
            return await client.get(NodeClaim, pool)
        except Exception:
            pass
    pid = node.spec.provider_id
    if pid:
        for nc in await client.list(NodeClaim):
            if nc.status.provider_id == pid:
                return nc
    return None


_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)([smh])")
_UNIT = {"s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(s: Optional[str]) -> Optional[float]:
    """Parse a metav1.Duration-ish string ("30s", "5m", "1h30m") to seconds."""
    if not s:
        return None
    out, matched = 0.0, False
    for m in _DURATION_RE.finditer(s):
        out += float(m.group(1)) * _UNIT[m.group(2)]
        matched = True
    return out if matched else None


def expected_hosts(nc: NodeClaim) -> int:
    try:
        return max(1, int(nc.metadata.labels.get(wk.TPU_HOSTS_LABEL, "1")))
    except ValueError:
        return 1
