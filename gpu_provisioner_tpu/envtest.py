"""envtest: the whole provisioner in-process against the simulated cloud.

The reference defers realism to a real-AKS e2e suite and tests units against
mocks (SURVEY.md §4); BASELINE.json asks the TPU build to do better with an
envtest config — reconcile real NodeClaim manifests through the real
controllers against the fake cloud, entirely in-process. This harness is that
config, reused by unit/e2e tests, ``bench.py`` and the operator's
``--simulate`` mode.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional

from .apis.core import Node
from .apis.karpenter import NodeClaim
from .apis.meta import CONDITION_READY
from .cloudprovider import MetricsDecorator, TPUCloudProvider
from .controllers.gc import GCOptions
from .controllers.health import HealthOptions
from .controllers.lifecycle import LifecycleOptions
from .controllers.recovery import RecoveryOptions
from .controllers.registry import build_controllers
from .controllers.statusbatch import StatusWriteBatcher
from .controllers.termination import TerminationOptions
from .fake.cloud import FakeCloud
from .providers.instance import InstanceProvider, ProviderConfig
from .providers.operations import OperationTracker
from .runtime import InMemoryClient, Manager
from .runtime.events import Recorder
from .runtime.wakehub import WakeHub


@dataclass
class EnvtestOptions:
    create_latency: float = 0.05
    delete_latency: float = 0.02
    node_join_delay: float = 0.0
    node_ready_delay: float = 0.0
    qr_step_latency: float = 0.02
    node_wait_interval: float = 0.02
    node_wait_attempts: int = 30
    # Non-blocking provisioning (providers/operations.py): the default
    # wiring runs create/delete as resumable state machines over a shared
    # OperationTracker — one batched nodepools.list per tick drives every
    # in-flight LRO, and completions are injected into the lifecycle
    # workqueue. blocking_create=True restores the worker-pinning shape
    # (poll_until_done + node-wait sleep loop) — kept as the benchmark
    # baseline, like ProviderConfig.legacy_list for the read path.
    blocking_create: bool = False
    # Tracker tick cadence; defaults to node_wait_interval.
    operation_poll_interval: Optional[float] = None
    # Capacity-aware placement (providers/placement.py + fake/cloud.py):
    # zone name -> {generation -> chip inventory}. Order is preference
    # order — it seeds both the fake cloud's per-zone pools and the
    # provider's candidate walk. None keeps the legacy single-zone world
    # (infinite capacity, no fallback).
    zones: Optional[dict] = None
    # How long the fake cloud lets a preempted spot slice linger between
    # the SpotPreempted notice and the reclaim delete (GKE's ~grace).
    spot_reclaim_grace: float = 0.25
    # Stockout-memo TTL (envtest timescale; production default is 5s) and
    # the spot-zone demotion hysteresis knobs.
    stockout_memo_ttl: float = 0.5
    spot_demote_threshold: int = 3
    spot_demote_window: float = 10.0
    # Read-through instance cache (providers/cache.py), scaled to envtest's
    # time compression (real default is 1s). 0 disables positive caching
    # but keeps singleflight coalescing.
    instance_cache_ttl: float = 0.2
    instance_cache_negative_ttl: float = 0.1
    gc_interval: float = 0.2
    leak_grace: float = 0.2
    lifecycle: LifecycleOptions = field(default_factory=lambda: LifecycleOptions(
        termination_requeue=0.05, registration_requeue=0.05,
        inprogress_requeue=0.1, status_flush_window=0.01))
    termination: TerminationOptions = field(default_factory=lambda: TerminationOptions(
        requeue=0.05, instance_requeue=0.05))
    # Scaled-down reference toleration (10 min → 30 s): must stay well above
    # simulated node-ready lag under load or repair reaps claims mid-launch;
    # repair tests shrink it explicitly.
    repair_toleration: float = 30.0
    # Unhealthy-fraction breaker: now DEFAULT ON (0.5), guarded by the
    # minimum-unhealthy count so small fleets (most tests) still repair —
    # the breaker exists for correlated waves, not independent faults.
    repair_max_unhealthy_fraction: float = 0.5
    repair_breaker_min_unhealthy: int = 3
    # Hysteresis window, envtest timescale (production: 5 flips / 10 min).
    repair_flap_threshold: int = 4
    repair_flap_window: float = 10.0
    # Stale-heartbeat repair bound; 0 (off) unless a scenario runs the
    # node-fault injector (the injector is envtest's only heartbeat source,
    # so enabling this without it would brand every node dead).
    repair_heartbeat_bound: float = 0.0
    # Drain-first escalation + budget, envtest timescale.
    repair_drain_deadline: float = 1.0
    repair_drain_requeue: float = 0.05
    repair_throttle_requeue: float = 0.1
    repair_rate: float = 0.0
    repair_rate_interval: float = 60.0
    repair_burst: int = 0
    repair_max_concurrent: int = 0
    repair_breaker_ttl: float = 0.05
    # Node-fault injection (chaos.NodeFaultInjector or a profile built by
    # chaos.node_fault_profile(name, seed)): started against the RAW client
    # with the Env (faults are the world's doing — kube chaos must not gate
    # them) and stopped at teardown. start() is idempotent, so a
    # RestartableEnv's incarnations share one injector and its per-node
    # fault clocks.
    node_faults: object = None
    max_concurrent_reconciles: int = 64
    # Claim-shard partitioning (controllers/registry.py): an Env built with
    # shards>1 runs ONE shard's controller set — partition tests assert a
    # shard only reconciles its own claims.
    shards: int = 1
    shard_index: int = 0
    # Layer the informer cache between controllers/provider and the store,
    # as the real operator wires it (__main__.py) — bench.py turns this on
    # so fleet-scale runs exercise (and size) the cache; unit tests keep the
    # raw client's read-your-writes simplicity.
    use_informer: bool = False
    # Chaos injection (chaos.ChaosPolicy or a profile built by
    # chaos.profile(name, seed)): wired into the fake cloud APIs and, for
    # kube.* rules, a ChaosClient wrapped around the client handed to the
    # provider/controllers. env.client stays raw so test assertions and
    # helpers never see injected faults.
    chaos: object = None
    # API-fault injection (chaos.ApiFaultInjector or a profile built by
    # chaos.api_fault_profile(name, seed)): wraps the kube client handed to
    # the provider/controllers/informers with brownout latency, seeded
    # 429/503 bursts, partition windows, and watch gaps that heal into a
    # 410 Gone. Layered OUTSIDE ChaosClient and INSIDE the governor, so
    # injected weather is felt by informer relists and classified by the
    # APIHealthGovernor exactly like real apiserver weather would be.
    # env.client stays raw so assertions/helpers never see faults.
    api_faults: object = None
    # Adaptive overload shedding (runtime/apihealth.py), ON by default like
    # tracing/fleetscope: the governor is passive (no background tasks) and
    # its pace() is a no-op fast path while HEALTHY, so healthy runs pay
    # nothing. Off, env.governor is None and nothing is paced or fenced.
    api_governor: bool = True
    # Runtime hardening knobs (runtime/controller.py): per-reconcile
    # deadline and per-item retry bound for the per-object controllers.
    reconcile_timeout: Optional[float] = None
    max_reconcile_retries: int = 30
    # Crash-point schedule (chaos.CrashPoints): armed cut lines raise
    # SimulatedCrash through the operator; the SAME object is handed to
    # every incarnation a RestartableEnv boots, so budgets persist across
    # restarts (crash once, then recover clean).
    crashes: object = None
    # Startup resync/orphan-adoption cadence (controllers/recovery.py);
    # the boot pass always fires immediately.
    recovery_interval: float = 600.0
    # Multi-process shard workers (operator/shardworker.py): a dynamic
    # range-ownership predicate (a runtime/shardlease.ShardLeaseTable's
    # ``owns``) supersedes the static crc32 shards/shard_index partition,
    # and distribute_singletons runs GC/recovery/slice-group assignment as
    # per-range lessees instead of pinning them to shard 0.
    owns_fn: object = None
    distribute_singletons: bool = False
    # Runtime detectors (analysis/detectors.py), ON by default — every
    # envtest-driven test runs under them:
    # - stall_budget: the event-loop stall detector fails the Env at
    #   teardown if anything held the loop longer than this (sync I/O,
    #   time.sleep, pathological CPU sections — the BENCH r04/r05 scaling
    #   ceiling made mechanical). 0 disables.
    # - leak_check: at teardown, enumerate every component's background
    #   -task seam (manager workers/pumps, workqueue timers, eviction
    #   queue + timers, tracker poller + notify tasks, informers, fault
    #   injector) and raise if any survived — the PR 4 tracker-only gate
    #   generalized. Also catches non-daemon threads started mid-Env.
    stall_budget: float = 1.0
    stall_interval: float = 0.05
    leak_check: bool = True
    # claimtrace (observability/): per-claim lifecycle traces, ON by default
    # — the tracer is passive (contextvar + ring buffer, no background
    # tasks), so every envtest run carries waterfalls for free and the bench
    # gates its overhead. tracing=False builds the overhead baseline.
    tracing: bool = True
    trace_buffer: int = 512
    trace_max_spans: int = 256
    # fleetscope (observability/fleet.py + flightrecorder.py), ON by
    # default like tracing — both are passive (listener + probe sink, no
    # background tasks), so every envtest run carries fleet SLO digests and
    # a flight recorder for free and the bench gates their overhead.
    # - fleet needs tracing (it subscribes to trace annotations); with
    #   tracing off it silently stays off.
    # - slo_objectives=None declares one generous default objective
    #   (p95 ≤ 60s — envtest waves finish in milliseconds, so ordinary
    #   tests never burn budget; chaos tests pass tight targets +
    #   second-scale windows to force the fast-burn trigger).
    # - bundle_dir=None keeps bundles in memory only (served at
    #   /debugz/bundle); tests point it at tmp_path to prove the disk
    #   round-trip.
    fleet: bool = True
    slo_objectives: object = None
    flight_recorder: bool = True
    recorder_capacity: int = 2048
    bundle_dir: Optional[str] = None


def _make_cloud(opts: EnvtestOptions, client: InMemoryClient) -> FakeCloud:
    return FakeCloud(
        client,
        create_latency=opts.create_latency,
        delete_latency=opts.delete_latency,
        node_join_delay=opts.node_join_delay,
        node_ready_delay=opts.node_ready_delay,
        qr_step_latency=opts.qr_step_latency,
        zones=opts.zones,
        spot_reclaim_grace=opts.spot_reclaim_grace,
        chaos=opts.chaos)


class Env:
    """One in-process provisioner: store + fake cloud + full controller set.

    ``client``/``cloud`` may be supplied to build an operator *incarnation*
    over pre-existing durable state (the crash-restart harness,
    :class:`RestartableEnv`); by default each Env owns a fresh store and
    cloud. ``fence`` is a leadership fencing token applied to every
    controller and the instance provider.
    """

    def __init__(self, options: Optional[EnvtestOptions] = None,
                 client: Optional[InMemoryClient] = None,
                 cloud: Optional[FakeCloud] = None,
                 fence=None):
        self.opts = options or EnvtestOptions()
        self.client = client if client is not None else InMemoryClient()
        # remote clients (runtime/shardipc.SocketClient) have no local
        # store; the supervisor registers the index on the parent's
        store = getattr(self.client, "store", None)
        if store is not None:
            store.add_index(Node, "spec.providerID",
                            lambda o: [o.spec.provider_id])
        if cloud is None:
            cloud = _make_cloud(self.opts, self.client)
        elif self.opts.chaos is not None and cloud.chaos is not self.opts.chaos:
            cloud.set_chaos(self.opts.chaos)
        self.cloud = cloud
        self.chaos = self.opts.chaos
        kube = self.client
        if self.chaos is not None:
            from .chaos import ChaosClient
            kube = ChaosClient(self.client, self.chaos)
        # API-fault layer: apiserver weather (brownout/partition/watch-gap)
        # injected OUTSIDE kube chaos so both compose, and INSIDE the
        # governor so every injected 429/503/timeout classifies into it.
        self.api_faults = self.opts.api_faults
        if self.api_faults is not None:
            from .chaos import ApiFaultClient
            kube = ApiFaultClient(kube, self.api_faults)
        # Overload governor: classifies every verb outcome (AIMD rate +
        # degraded-mode state machine); consumers (workers, status batcher,
        # provider fence, informers) are handed the SAME instance below.
        self.governor = None
        if self.opts.api_governor:
            from .runtime.apihealth import APIHealthGovernor, GovernedClient
            self.governor = APIHealthGovernor()
            kube = GovernedClient(kube, self.governor)
        self.informers = None
        if self.opts.use_informer:
            from .runtime.informer import CachedListClient
            # layered over the (possibly chaos-wrapped) client: informer
            # re-lists then feel injected apiserver weather too
            kube = CachedListClient(kube, (Node, NodeClaim))
            # register the providerID index on the cached client too, the
            # way the real operator wires it (__main__.py) — without it
            # _pool_name_for silently degrades to the O(nodes) full scan
            kube.add_index(Node, "spec.providerID",
                           lambda o: [o.spec.provider_id])
            self.informers = kube
        self.tracer = None
        self.trace_store = None
        trace_ids = None
        if self.opts.tracing:
            from .observability import (
                Tracer, TraceStore, current_ids, install_log_record_factory,
            )
            self.trace_store = TraceStore(
                max_traces=self.opts.trace_buffer,
                max_spans=self.opts.trace_max_spans)
            self.tracer = Tracer(self.trace_store)
            install_log_record_factory()
            trace_ids = current_ids
        # fleetscope: SLO aggregator (trace listener) + flight recorder
        # (probes sink, attached in __aenter__ / detached in __aexit__ so a
        # torn-down Env's recorder never sees another Env's events).
        self.fleet = None
        if self.opts.fleet and self.tracer is not None:
            from .observability.fleet import FleetAggregator, SLOObjective
            objectives = self.opts.slo_objectives
            if objectives is None:
                # envtest timescale: windows in seconds, not minutes; the
                # 60s target is unreachable by design for healthy waves
                objectives = (SLOObjective(target=60.0, fast_window=5.0,
                                           slow_window=60.0),)
            self.fleet = FleetAggregator(objectives=objectives,
                                         shard=self.opts.shard_index)
            self.tracer.add_listener(self.fleet.on_trace_event)
        self.flight_recorder = None
        if self.opts.flight_recorder:
            from .observability.flightrecorder import FlightRecorder
            self.flight_recorder = FlightRecorder(
                capacity=self.opts.recorder_capacity,
                bundle_dir=self.opts.bundle_dir)
            if self.fleet is not None:
                self.fleet.on_fast_burn = self.flight_recorder.slo_fast_burn
        # Event-driven wake graph (runtime/wakehub.py): one hub per Env —
        # inject() bypasses the watch map-fns' shard filtering, so a hub
        # shared across shard Envs would enqueue foreign claims into this
        # shard's queue (single-writer violation). Every wake producer in
        # this Env (tracker completions, Node watch, stockout parking,
        # status-flush) routes through it.
        self.wakehub = WakeHub()
        self.provider = InstanceProvider(
            self.cloud.nodepools, kube,
            ProviderConfig(
                node_wait_interval=self.opts.node_wait_interval,
                node_wait_attempts=self.opts.node_wait_attempts,
                cache_ttl=self.opts.instance_cache_ttl,
                qr_cache_ttl=0.0,
                cache_negative_ttl=self.opts.instance_cache_negative_ttl,
                zones=tuple(self.opts.zones) if self.opts.zones else (),
                stockout_memo_ttl=self.opts.stockout_memo_ttl,
                spot_demote_threshold=self.opts.spot_demote_threshold,
                spot_demote_window=self.opts.spot_demote_window),
            queued=self.cloud.queuedresources,
            crashes=self.opts.crashes, fence=fence, tracer=self.tracer)
        # assigned post-construction, like the fence: the provider's
        # stockout-park path arms hub timers when configured to
        self.provider.wakehub = self.wakehub
        # Status-write coalescing (controllers/statusbatch.py): batches the
        # lifecycle's per-claim meta+status flushes over the same
        # (chaos/informer-wrapped) client the controllers write with.
        # window <= 0 keeps the legacy synchronous flush.
        self.status_batcher = None
        if self.opts.lifecycle.status_flush_window > 0:
            self.status_batcher = StatusWriteBatcher(
                kube, window=self.opts.lifecycle.status_flush_window,
                fence=fence, tracer=self.tracer, wakehub=self.wakehub)
        self.tracker = None
        if not self.opts.blocking_create:
            # the tracker polls through the provider's COUNTED seam so its
            # batched lists show up in the per-endpoint cloud-call
            # accounting, and through the same (informer/chaos-wrapped)
            # kube client the provider reads nodes with
            self.tracker = OperationTracker(
                self.provider.nodepools, kube,
                interval=(self.opts.operation_poll_interval
                          or self.opts.node_wait_interval))
            self.provider.tracker = self.tracker
        self.cloudprovider = MetricsDecorator(TPUCloudProvider(
            self.provider, repair_toleration=self.opts.repair_toleration))
        self.recorder = Recorder(self.client, trace_ids=trace_ids)
        controllers, self.eviction = build_controllers(
            kube, self.cloudprovider, self.recorder,
            lifecycle_options=self.opts.lifecycle,
            termination_options=self.opts.termination,
            gc_options=GCOptions(interval=self.opts.gc_interval,
                                 leak_grace=self.opts.leak_grace),
            health_options=HealthOptions(
                max_unhealthy_fraction=self.opts.repair_max_unhealthy_fraction,
                breaker_min_unhealthy=self.opts.repair_breaker_min_unhealthy,
                breaker_ttl=self.opts.repair_breaker_ttl,
                flap_threshold=self.opts.repair_flap_threshold,
                flap_window=self.opts.repair_flap_window,
                heartbeat_bound=self.opts.repair_heartbeat_bound,
                drain_deadline=self.opts.repair_drain_deadline,
                drain_requeue=self.opts.repair_drain_requeue,
                throttle_requeue=self.opts.repair_throttle_requeue,
                repair_rate=self.opts.repair_rate,
                repair_interval=self.opts.repair_rate_interval,
                repair_burst=self.opts.repair_burst,
                max_concurrent_repairs=self.opts.repair_max_concurrent),
            max_concurrent_reconciles=self.opts.max_concurrent_reconciles,
            shards=self.opts.shards, shard_index=self.opts.shard_index,
            reconcile_timeout=self.opts.reconcile_timeout,
            max_retries=self.opts.max_reconcile_retries,
            recovery_options=RecoveryOptions(
                interval=self.opts.recovery_interval,
                grace=self.opts.leak_grace),
            crashes=self.opts.crashes, fence=fence,
            tracker=self.tracker, tracer=self.tracer,
            wakehub=self.wakehub, status_batcher=self.status_batcher,
            owns=self.opts.owns_fn,
            distribute_singletons=self.opts.distribute_singletons)
        # The manager pumps watch through the SAME (chaos/informer-wrapped)
        # client the controllers read from — with the informer on, events
        # arrive via its post-cache-update relay, so a woken reconcile can
        # never list a cache that doesn't hold the event that woke it (the
        # real operator wires Manager(kube) identically). ChaosClient
        # passes watch() through, so kube chaos still never gates events.
        self.manager = Manager(kube).register(*controllers)
        # Governor fan-out, assigned post-construction like the fence and
        # the wakehub: per-object workers pace admission, the status batcher
        # widens its window (status writes shed FIRST), the provider fences
        # cloud mutations while PARTITIONED, and informers report watch
        # gaps. Singletons (gc/recovery) have no worker admission seam.
        if self.governor is not None:
            for c in controllers:
                if hasattr(c, "governor"):
                    c.governor = self.governor
            if self.status_batcher is not None:
                self.status_batcher.governor = self.governor
            self.provider.api_governor = self.governor
            if self.informers is not None:
                for inf in self.informers._informers.values():
                    inf.governor = self.governor
        if self.flight_recorder is not None:
            from .observability.flightrecorder import wire_default_sources
            wire_default_sources(self.flight_recorder,
                                 manager=self.manager,
                                 tracker=self.tracker,
                                 placement=self.provider.placement,
                                 trace_store=self.trace_store)
        # runtime detectors (analysis/detectors.py), armed in __aenter__
        self.stall = None
        self._threads_before: set = set()

    def _attach_observers(self) -> None:
        """Hook the flight recorder into the live seams: the probes sink,
        the transport breaker listeners, and the stall detector. Paired
        with :meth:`_detach_observers` on every exit path — a torn-down
        Env's recorder must not keep seeing other Envs' events through the
        module-global seams."""
        if self.governor is not None:
            # transport 429s (pacing, not failure) feed the AIMD governor;
            # bound method so _detach_observers can remove exactly it
            from .transport import add_throttle_listener
            add_throttle_listener(self._on_throttled)
            if self.flight_recorder is not None:
                # one bundle per degraded-mode ENTRY (flaps suppressed by
                # the recorder's trigger dedup)
                self.governor.add_degraded_listener(
                    self.flight_recorder.degraded_entered)
        if self.flight_recorder is None:
            return
        from .runtime import probes
        from .transport import add_breaker_listener
        probes.add_sink(self.flight_recorder.probe)
        add_breaker_listener(self.flight_recorder.breaker_opened)
        if self.stall is not None:
            self.stall.on_stall = self.flight_recorder.stall

    def _on_throttled(self, name: str, retry_after: float) -> None:
        """transport.add_throttle_listener adapter → governor AIMD."""
        self.governor.note_throttle(retry_after)

    def _detach_observers(self) -> None:
        if self.governor is not None:
            from .transport import remove_throttle_listener
            remove_throttle_listener(self._on_throttled)
            if self.flight_recorder is not None:
                self.governor.remove_degraded_listener(
                    self.flight_recorder.degraded_entered)
        if self.flight_recorder is None:
            return
        from .runtime import probes
        from .transport import remove_breaker_listener
        probes.remove_sink(self.flight_recorder.probe)
        remove_breaker_listener(self.flight_recorder.breaker_opened)

    async def __aenter__(self) -> "Env":
        import os
        from .analysis.detectors import StallDetector, thread_snapshot
        self._threads_before = thread_snapshot()
        self.stall = None
        # Operability escape hatch for contended CI machines: the sentinel
        # measures wall-clock oversleep, so whole-process CPU starvation
        # (a parallel build, a noisy neighbor) is indistinguishable from
        # loop-blocking code. PROVLINT_STALL_BUDGET relaxes (or, at 0,
        # disables) every Env's budget without code changes.
        budget = self.opts.stall_budget
        env_budget = os.environ.get("PROVLINT_STALL_BUDGET")
        if env_budget is not None and budget > 0:
            relaxed = float(env_budget)
            budget = 0.0 if relaxed <= 0 else max(budget, relaxed)
        if budget > 0:
            self.stall = StallDetector(budget=budget,
                                       interval=self.opts.stall_interval)
            self.stall.start()
        self._attach_observers()
        try:
            if self.informers is not None:
                await self.informers.start()  # sync before first reconcile
            if self.tracker is not None:
                self.tracker.start()
            if self.opts.node_faults is not None:
                # raw client: the injector is the world (kubelets/
                # hardware), not part of the operator — kube chaos must
                # not gate its writes
                self.opts.node_faults.start(self.client)
            if self.status_batcher is not None:
                self.status_batcher.start()
            self.eviction.start()
            await self.manager.start()
        except BaseException:
            # a failed startup never reaches __aexit__ — unwind whatever
            # DID start (every stop is a no-op for a never-started
            # component) or the half-born Env leaks its tasks into every
            # later test in the process: the leak gate's own bug class
            for closer in (self.manager.stop, self.eviction.stop,
                           *((self.status_batcher.stop,)
                             if self.status_batcher is not None else ()),
                           *((self.opts.node_faults.stop,)
                             if self.opts.node_faults is not None else ()),
                           *((self.tracker.stop,)
                             if self.tracker is not None else ()),
                           self.wakehub.stop,
                           *((self.informers.stop,)
                             if self.informers is not None else ())):
                try:
                    await closer()
                except Exception:  # noqa: BLE001 — don't mask the cause
                    pass
            self._detach_observers()
            if self.stall is not None:
                await self.stall.stop()
            raise
        return self

    async def __aexit__(self, *exc) -> None:
        from .analysis import detectors
        # detach the recorder from the module-global seams first — teardown
        # chatter (hub-stop and friends) and, above all, OTHER Envs' events
        # after this one returns must not land in this Env's ring
        self._detach_observers()
        # Exception-safe teardown: one failing stop must not strand the
        # components after it (the half-torn-down Env would leak its tasks
        # into every later test — the same bug class the startup unwind in
        # __aenter__ guards). Run every stop; re-raise the FIRST failure.
        stop_error: Optional[BaseException] = None
        # batcher stops right after the manager (its final drain flushes
        # the last batch while the store is still live); the hub stops
        # after the tracker, whose completion subscribers call hub.wake
        for closer in (self.manager.stop,
                       *((self.status_batcher.stop,)
                         if self.status_batcher is not None else ()),
                       self.eviction.stop,
                       *((self.opts.node_faults.stop,)
                         if self.opts.node_faults is not None else ()),
                       *((self.tracker.stop,)
                         if self.tracker is not None else ()),
                       self.wakehub.stop,
                       *((self.informers.stop,)
                         if self.informers is not None else ()),
                       *((self.stall.stop,)
                         if self.stall is not None else ())):
            try:
                await closer()
            # provlint: disable=cancellation-swallow — not swallowed:
            # the first failure (incl. a CancelledError delivered to the
            # exiting test) is re-raised right below, AFTER the remaining
            # components have stopped
            except BaseException as e:  # noqa: BLE001 — re-raised below
                stop_error = stop_error or e
        if stop_error is not None:
            raise stop_error
        # Runtime detector gates, suppressed when the body is already
        # raising so they never mask a test failure. Scoped to THIS Env's
        # own components (a RestartableEnv zombie's rival legitimately
        # keeps its own tracker alive) — the PR 4 tracker-only "poller
        # outlived its Env" check generalized to every background task.
        if exc and exc[0] is not None:
            return
        if self.opts.leak_check:
            detectors.check_no_leaked_tasks(self._component_tasks())
            detectors.check_no_leaked_threads(self._threads_before)
        if self.stall is not None:
            self.stall.check()

    def _component_tasks(self):
        """Every (component, task) seam this Env's operator half owns —
        the leak gate's enumeration. New components that spawn background
        tasks must be added here (docs/STATIC_ANALYSIS.md)."""
        named: list[tuple[str, object]] = []
        named += [("manager", t) for t in self.manager._tasks]
        for c in self.manager.controllers:
            named.append((f"workqueue-timer/{c.name}", c.queue._timer))
        named.append(("eviction-queue", self.eviction._task))
        named += [("eviction-timer", t) for t in self.eviction._timers]
        if self.tracker is not None:
            named.append(("operation-tracker poller", self.tracker._task))
            named += [("operation-tracker notify", t)
                      for t in self.tracker._notify_tasks]
        if self.informers is not None:
            named += [(f"informer/{cls.KIND}", inf._task)
                      for cls, inf in self.informers._informers.items()]
        if self.opts.node_faults is not None:
            named.append(("node-fault-injector",
                          getattr(self.opts.node_faults, "_task", None)))
        if self.status_batcher is not None:
            named.append(("status-batcher", self.status_batcher._task))
        named += [("wakehub wake", t) for t in self.wakehub._tasks]
        return named

    def informer_cache_sizes(self) -> dict[str, int]:
        """Cached object count per kind (empty when informers are off) —
        the bench reports this as the informer memory proxy."""
        if self.informers is None:
            return {}
        return {cls.KIND: len(inf._cache)
                for cls, inf in self.informers._informers.items()}

    # ------------------------------------------------------------- helpers
    async def wait_ready(self, name: str, timeout: float = 10.0,
                         poll: Optional[float] = None) -> NodeClaim:
        """Block until the NodeClaim's Ready root condition is True.
        ``poll`` fixes the polling interval — fleet-scale callers (bench)
        pass ~0.25s so a thousand waiters don't open at 100 Hz each."""
        return await self._wait(name, lambda nc: nc.status_conditions.is_true(
            CONDITION_READY), timeout, "Ready", poll=poll)

    async def wait_gone(self, name: str, timeout: float = 10.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            try:
                await self.client.get(NodeClaim, name)
            except Exception:
                return
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"nodeclaim {name} still present after {timeout}s")
            await asyncio.sleep(0.01)

    async def _wait(self, name: str, predicate, timeout: float, what: str,
                    poll: Optional[float] = None) -> NodeClaim:
        deadline = asyncio.get_event_loop().time() + timeout
        last = None
        interval = poll or 0.01  # fast for unit-test latencies, backs off at
        while True:              # fleet scale (hundreds of waiters × 100 Hz
            last = await self.client.get(NodeClaim, name)  # was real load)
            if predicate(last):
                return last
            if asyncio.get_event_loop().time() > deadline:
                conds = {c.type: f"{c.status}/{c.reason}"
                         for c in last.status.conditions}
                raise TimeoutError(
                    f"nodeclaim {name} not {what} after {timeout}s; conditions: {conds}")
            await asyncio.sleep(interval)
            interval = min(interval * 1.3, 0.25)


class RestartableEnv:
    """Crash-restart harness: the durable half (kube store + fake cloud)
    outlives the operator incarnations built on top of it.

    ``start()`` boots an incarnation — fresh provider caches, informers,
    controllers, eviction queue, everything in-memory — against the SAME
    store and cloud. ``crash()`` tears the running incarnation down the way
    process death would: every operator task cancelled, every cache
    dropped, nothing released gracefully. Cloud and kube state persist,
    including in-flight LROs, which the fake cloud keeps driving
    server-side (``FakeNodePoolsAPI._settle``) exactly as GKE would for an
    operator that died mid-poll.

    The usual shape, with a ``chaos.CrashPoints`` schedule in
    ``options.crashes``::

        renv = RestartableEnv(opts)
        await renv.start()
        ...create claims...
        await renv.opts.crashes.crashed.wait()   # armed point fired
        await renv.restart()                     # fresh incarnation
        await renv.wait_ready("claim0")          # must converge

    For leader-failover soaks, ``start(fence=...)`` threads a per-
    incarnation fencing token, and a *zombie* incarnation can be kept
    running deliberately (skip ``crash()``; boot a rival via a second
    ``Env(opts, client=renv.client, cloud=renv.cloud, fence=...)``) to
    prove fenced workers stop mutating the cloud.
    """

    def __init__(self, options: Optional[EnvtestOptions] = None):
        self.opts = options or EnvtestOptions()
        self.client = InMemoryClient()
        self.client.store.add_index(Node, "spec.providerID",
                                    lambda o: [o.spec.provider_id])
        self.cloud = _make_cloud(self.opts, self.client)
        self.env: Optional[Env] = None
        self.incarnations = 0

    async def start(self, fence=None) -> Env:
        if self.env is not None:
            raise RuntimeError("an incarnation is already running")
        env = Env(self.opts, client=self.client, cloud=self.cloud,
                  fence=fence)
        await env.__aenter__()
        self.env = env
        self.incarnations += 1
        return env

    async def crash(self) -> None:
        """Hard-kill the running incarnation. The graceful-vs-crash
        distinctions that matter — lease release, cloud-side rollback —
        live above this layer: nothing here releases anything."""
        env, self.env = self.env, None
        if env is not None:
            await env.__aexit__()

    async def restart(self, fence=None) -> Env:
        await self.crash()
        return await self.start(fence=fence)

    async def __aenter__(self) -> "RestartableEnv":
        await self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.crash()

    # current-incarnation passthroughs (the helpers only touch the durable
    # client, so they survive a crash that happens mid-wait)
    async def wait_ready(self, name: str, timeout: float = 10.0,
                         poll: Optional[float] = None) -> NodeClaim:
        return await self.env.wait_ready(name, timeout, poll)

    async def wait_gone(self, name: str, timeout: float = 10.0) -> None:
        return await self.env.wait_gone(name, timeout)
