"""Typed cloud-provider error taxonomy (top-level to stay import-cycle-free;
re-exported via ``cloudprovider.errors``).

Re-creates the error contract the controllers branch on (reference:
vendor/sigs.k8s.io/karpenter/pkg/cloudprovider/errors.go): NodeClaimNotFound
drives GC and termination short-circuits; InsufficientCapacity and
NodeClassNotReady make the launch reconciler delete the NodeClaim instead of
retrying (launch.go:84-109); CreateError carries a condition reason.
"""

from __future__ import annotations

from typing import Optional


class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    """The instance backing a NodeClaim no longer exists in the cloud."""


class InsufficientCapacityError(CloudProviderError):
    """The requested shape cannot be fulfilled (stockout, quota).

    TPU note: Cloud TPU stockouts surface as RESOURCE_EXHAUSTED on node-pool
    create or a SUSPENDED/FAILED queued resource; both map here so the launch
    path can terminate the NodeClaim and let KAITO retry with a different
    shape.
    """


class NodeClassNotReadyError(CloudProviderError):
    """The referenced NodeClass is not ready (bad config, missing perms)."""


class CreateError(CloudProviderError):
    """Create failed in a way that should surface as a Launched=False reason."""

    def __init__(self, message: str, reason: str = "LaunchFailed"):
        super().__init__(message)
        self.reason = reason


def is_nodeclaim_not_found(err: Optional[BaseException]) -> bool:
    return isinstance(err, NodeClaimNotFoundError)


def ignore_nodeclaim_not_found(err: Optional[BaseException]) -> None:
    """Re-raise anything that isn't a NodeClaimNotFoundError."""
    if err is not None and not is_nodeclaim_not_found(err):
        raise err
