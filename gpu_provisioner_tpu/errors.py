"""Typed cloud-provider error taxonomy (top-level to stay import-cycle-free;
re-exported via ``cloudprovider.errors``).

Re-creates the error contract the controllers branch on (reference:
vendor/sigs.k8s.io/karpenter/pkg/cloudprovider/errors.go): NodeClaimNotFound
drives GC and termination short-circuits; InsufficientCapacity and
NodeClassNotReady make the launch reconciler delete the NodeClaim instead of
retrying (launch.go:84-109); CreateError carries a condition reason.
"""

from __future__ import annotations

from typing import Optional

# ------------------------------------------------------------ reason enum
# The single home for every ``CreateError.reason`` value (and the tracker's
# TrackedOperation.reason strings that feed them). Terminal-vs-retryable
# classification comes from THIS table — never from string literals at call
# sites (provlint PL013): a reason spelled inline drifts from the
# classification below and silently flips a terminal fault into an
# infinite-retry loop (or vice versa).

REASON_LAUNCH_FAILED = "LaunchFailed"
REASON_CREATE_IN_PROGRESS = "CreateInProgress"
REASON_INVALID_NAME = "InvalidName"
REASON_UNRESOLVABLE_SHAPE = "UnresolvableShape"
REASON_INVALID_STORAGE_REQUEST = "InvalidStorageRequest"
REASON_QUEUED_PROVISIONING = "QueuedProvisioning"
REASON_DEGRADED_POOL = "DegradedPool"
REASON_NODES_NOT_READY = "NodesNotReady"
REASON_SUPERSEDED = "Superseded"
REASON_DISCARDED = "Discarded"
REASON_DELETE_TIMEOUT = "DeleteTimeout"
REASON_DELETED = "Deleted"
REASON_CREATED = "Created"
# Capacity exhausted across EVERY placement candidate (zone × generation ×
# tier): the claim can never launch as specified — terminal, like
# InsufficientCapacityError, but carrying the walk's verdict as a reason.
REASON_STOCKOUT = "Stockout"
# Every remaining candidate is memo-suppressed (a live stockout-TTL verdict,
# no fresh probe spent) AND the provider is configured to park rather than
# terminate (``ProviderConfig.stockout_park``): retryable — the WakeHub
# re-wakes the claim when the earliest memo expires, and the requeue ladder
# is the safety net. Default-off config keeps the pinned terminal semantics.
REASON_STOCKOUT_SUPPRESSED = "StockoutSuppressed"

# Reasons that mean "this claim can never converge as specified": the
# lifecycle launch reconciler deletes the NodeClaim (KAITO retries with a
# different shape) instead of requeueing. Invalid-input reasons
# (InvalidName/UnresolvableShape/InvalidStorageRequest) stay on the
# retry-then-liveness path — they surface a Launched=False condition the
# operator can read, and the launch deadline reaps them (the taxonomy table
# in docs/FAILURE_MODES.md).
TERMINAL_REASONS = frozenset({
    REASON_STOCKOUT,
})


def reason_is_terminal(reason: str) -> bool:
    """True when a CreateError with this reason should terminate the claim
    rather than requeue it."""
    return reason in TERMINAL_REASONS


# The full vocabulary, for tooling: provlint PL013 flags any of these values
# spelled as a literal in a CreateError() call or a ``.reason`` comparison.
KNOWN_REASONS = frozenset({
    REASON_LAUNCH_FAILED, REASON_CREATE_IN_PROGRESS, REASON_INVALID_NAME,
    REASON_UNRESOLVABLE_SHAPE, REASON_INVALID_STORAGE_REQUEST,
    REASON_QUEUED_PROVISIONING, REASON_DEGRADED_POOL, REASON_NODES_NOT_READY,
    REASON_SUPERSEDED, REASON_DISCARDED, REASON_DELETE_TIMEOUT,
    REASON_DELETED, REASON_CREATED, REASON_STOCKOUT,
    REASON_STOCKOUT_SUPPRESSED,
})


class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    """The instance backing a NodeClaim no longer exists in the cloud."""


class InsufficientCapacityError(CloudProviderError):
    """The requested shape cannot be fulfilled (stockout, quota).

    TPU note: Cloud TPU stockouts surface as RESOURCE_EXHAUSTED on node-pool
    create or a SUSPENDED/FAILED queued resource; both map here so the launch
    path can terminate the NodeClaim and let KAITO retry with a different
    shape.
    """


class NodeClassNotReadyError(CloudProviderError):
    """The referenced NodeClass is not ready (bad config, missing perms)."""


class CreateError(CloudProviderError):
    """Create failed in a way that should surface as a Launched=False reason."""

    def __init__(self, message: str, reason: str = REASON_LAUNCH_FAILED):
        super().__init__(message)
        self.reason = reason


def is_nodeclaim_not_found(err: Optional[BaseException]) -> bool:
    return isinstance(err, NodeClaimNotFoundError)


def ignore_nodeclaim_not_found(err: Optional[BaseException]) -> None:
    """Re-raise anything that isn't a NodeClaimNotFoundError."""
    if err is not None and not is_nodeclaim_not_found(err):
        raise err
