"""Fault-injecting fakes: the multi-node-without-a-cluster answer.

The reference tests its distributed behavior entirely through programmable
fakes — mock ARM clients with scriptable LRO pollers and a hand-rolled k8s
client that fabricates Ready nodes (pkg/fake/, SURVEY.md §4.2). Here the
in-memory store already plays the apiserver, so the fakes simulate the
**cloud**: node pools that become RUNNING after a latency, kubelet-joins that
materialize Node objects per host, queued resources that drain on a schedule,
and N-times error injection on any method (fake/types.go:82 BeginError
analog).
"""

from .cloud import (  # noqa: F401
    FakeCloud, FakeNodePoolsAPI, FakeQueuedResourcesAPI, TimedOperation,
)
from .builders import make_nodeclaim, make_node  # noqa: F401
