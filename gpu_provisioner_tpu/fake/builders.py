"""Object builders for tests and simulations (pkg/fake/nodeclaim.go analog:
GetNodeClaimObj pre-labels kaito.sh/workspace + nodepool kaito)."""

from __future__ import annotations

from typing import Optional

from ..apis import labels as wk
from ..apis import karpenter as kv1
from ..apis.core import Node, NodeSpec
from ..apis.meta import Condition, ObjectMeta
from ..apis.serde import now


def make_nodeclaim(name: str = "ws0", shape: str = "tpu-v5e-8",
                   workspace: str = "ws", storage: str = "",
                   labels: Optional[dict[str, str]] = None,
                   annotations: Optional[dict[str, str]] = None) -> kv1.NodeClaim:
    meta_labels = {
        wk.KAITO_WORKSPACE_LABEL: workspace,
        wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME,
        # every built claim is discoverable for e2e cleanup, like the
        # reference's test.NodeClaim builder stamping DiscoveryLabel
        # (vendor/.../pkg/test/nodeclaim.go:32, metadata.go:33)
        wk.DISCOVERY_LABEL: wk.DISCOVERY_VALUE,
        **(labels or {}),
    }
    requests = {wk.TPU_RESOURCE_NAME: "1"}
    if storage:
        requests["storage"] = storage
    return kv1.NodeClaim(
        metadata=ObjectMeta(name=name, labels=meta_labels,
                            annotations=annotations or {}),
        spec=kv1.NodeClaimSpec(
            requirements=[kv1.NodeSelectorRequirement(
                key=wk.INSTANCE_TYPE_LABEL, operator=kv1.IN, values=[shape])],
            resources=kv1.ResourceRequirements(requests=requests),
            node_class_ref=kv1.NodeClassRef(group="kaito.sh", kind="KaitoNodeClass",
                                            name="default"),
        ),
    )


def make_node(name: str, provider_id: str = "", pool: str = "",
              ready: bool = True, labels: Optional[dict[str, str]] = None) -> Node:
    n = Node(metadata=ObjectMeta(name=name, labels=labels or {}),
             spec=NodeSpec(provider_id=provider_id))
    if pool:
        n.metadata.labels.setdefault(wk.GKE_NODEPOOL_LABEL, pool)
    n.status.conditions.append(Condition(
        type="Ready", status="True" if ready else "False",
        reason="KubeletReady" if ready else "KubeletNotReady",
        last_transition_time=now()))
    return n


# ------------------------------------------------ node condition helpers
# The node-fault injector (chaos/nodefaults.py) and health tests drive Node
# state through these so every fault writes conditions the way a kubelet
# would: lastTransitionTime bumps ONLY when the status value flips, and the
# heartbeat refreshes independently of the status.

def set_node_condition(node: Node, ctype: str, status: str,
                       reason: str = "", message: str = "") -> bool:
    """Set (or create) a Node status condition; returns True when the status
    value actually flipped (and stamps a fresh lastTransitionTime)."""
    cond = next((c for c in node.status.conditions if c.type == ctype), None)
    if cond is None:
        cond = Condition(type=ctype)
        node.status.conditions.append(cond)
        changed = True
    else:
        changed = cond.status != status
    if changed:
        cond.last_transition_time = now()
    cond.status = status
    cond.reason = reason or ctype
    cond.message = message
    return changed


def set_node_ready(node: Node, ready: bool, reason: str = "") -> bool:
    """Flip the kubelet Ready condition; transition time bumps on change."""
    return set_node_condition(
        node, "Ready", "True" if ready else "False",
        reason or ("KubeletReady" if ready else "KubeletNotReady"))


def heartbeat_node(node: Node, at=None) -> bool:
    """Refresh the Ready condition's lastHeartbeatTime — what a live kubelet
    does every status-report interval regardless of the status value.
    ``at=None`` stamps a FULL-resolution timestamp (not the second-truncated
    ``now()``): envtest compresses heartbeat intervals below a second, where
    truncation would alias consecutive beats."""
    cond = node.ready_condition()
    if cond is None:
        return False
    from datetime import datetime, timezone
    cond.last_heartbeat_time = at or datetime.now(timezone.utc)
    return True
