"""Object builders for tests and simulations (pkg/fake/nodeclaim.go analog:
GetNodeClaimObj pre-labels kaito.sh/workspace + nodepool kaito)."""

from __future__ import annotations

from typing import Optional

from ..apis import labels as wk
from ..apis import karpenter as kv1
from ..apis.core import Node, NodeSpec
from ..apis.meta import Condition, ObjectMeta
from ..apis.serde import now


def make_nodeclaim(name: str = "ws0", shape: str = "tpu-v5e-8",
                   workspace: str = "ws", storage: str = "",
                   labels: Optional[dict[str, str]] = None,
                   annotations: Optional[dict[str, str]] = None) -> kv1.NodeClaim:
    meta_labels = {
        wk.KAITO_WORKSPACE_LABEL: workspace,
        wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME,
        # every built claim is discoverable for e2e cleanup, like the
        # reference's test.NodeClaim builder stamping DiscoveryLabel
        # (vendor/.../pkg/test/nodeclaim.go:32, metadata.go:33)
        wk.DISCOVERY_LABEL: wk.DISCOVERY_VALUE,
        **(labels or {}),
    }
    requests = {wk.TPU_RESOURCE_NAME: "1"}
    if storage:
        requests["storage"] = storage
    return kv1.NodeClaim(
        metadata=ObjectMeta(name=name, labels=meta_labels,
                            annotations=annotations or {}),
        spec=kv1.NodeClaimSpec(
            requirements=[kv1.NodeSelectorRequirement(
                key=wk.INSTANCE_TYPE_LABEL, operator=kv1.IN, values=[shape])],
            resources=kv1.ResourceRequirements(requests=requests),
            node_class_ref=kv1.NodeClassRef(group="kaito.sh", kind="KaitoNodeClass",
                                            name="default"),
        ),
    )


def make_node(name: str, provider_id: str = "", pool: str = "",
              ready: bool = True, labels: Optional[dict[str, str]] = None) -> Node:
    n = Node(metadata=ObjectMeta(name=name, labels=labels or {}),
             spec=NodeSpec(provider_id=provider_id))
    if pool:
        n.metadata.labels.setdefault(wk.GKE_NODEPOOL_LABEL, pool)
    n.status.conditions.append(Condition(
        type="Ready", status="True" if ready else "False",
        reason="KubeletReady" if ready else "KubeletNotReady",
        last_transition_time=now()))
    return n
