"""Fake GKE/Cloud-TPU backend with latency and fault injection.

Plays the role the scripted LRO pollers + MockedLRO fakes play in the
reference (pkg/fake/types.go:26-173, pollingHandler.go): deterministic,
programmable cloud behavior — but as one coherent simulator: a created node
pool transitions PROVISIONING→RUNNING after ``create_latency``, then each
host's kubelet "joins" by materializing a Node object (unready → Ready after
``node_ready_delay``) with GKE + tpu.kaito.sh labels, the way
fake/k8sClient.go:210-241 fabricates Ready nodes with agentpool labels and
VMSS providerIDs. Error injection is two-layer: scripted one-shot faults
mirroring AtomicError/MaxCalls (fake/atomic.go) via ``fail(method, error,
times)``, and policy-driven chaos (``chaos.ChaosPolicy``) for probabilistic
errors, latency/hangs, and partial-failure modes — pools whose nodes never
join, queued resources wedged mid-ladder, LROs whose ``result()`` raises
after ``done()``.
"""

from __future__ import annotations

import asyncio
import time
from collections import defaultdict
from typing import Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..catalog import lookup as catalog_lookup
from ..providers.gcp import (
    APIError, NodePool, QueuedResource,
    NP_ERROR, NP_PROVISIONING, NP_RUNNING, NP_STOPPING,
    QR_ACCEPTED, QR_ACTIVE, QR_CREATING, QR_WAITING,
)
from ..providers.instance import instance_name, provider_id
from ..runtime.client import Client, NotFoundError
from .builders import make_node, set_node_condition


class TimedOperation:
    """LRO that completes ``latency`` seconds after creation; optionally runs
    ``on_done`` (async) once, then returns ``result`` or raises ``error``.
    ``on_poll`` (sync) fires on every ``done()`` check — the accounting hook
    for client-side LRO polling, which against the real API is one
    ``operations.get`` HTTP round-trip per check."""

    def __init__(self, latency: float = 0.0, result=None,
                 error: Optional[Exception] = None, on_done=None,
                 on_poll=None):
        self._deadline = time.monotonic() + latency
        self._result = result
        self._error = error
        self._on_done = on_done
        self._on_poll = on_poll
        self._fired = False

    async def done(self) -> bool:
        if self._on_poll is not None:
            self._on_poll()
        if time.monotonic() < self._deadline:
            return False
        if not self._fired:
            self._fired = True
            if self._on_done is not None:
                await self._on_done()
        return True

    async def result(self):
        while not await self.done():
            await asyncio.sleep(0.001)
        if self._error is not None:
            raise self._error
        return self._result


class _FaultInjector:
    """Scripted one-shot faults + policy-driven chaos, shared by both fake
    APIs. ``scope`` namespaces this API's methods in chaos rule matching
    (``nodepools.begin_create`` etc.)."""

    scope = "fake"

    def __init__(self):
        self._faults: dict[str, list[tuple[Exception, int]]] = defaultdict(list)
        self.calls: dict[str, int] = defaultdict(int)
        self.chaos = None  # Optional[chaos.ChaosPolicy], set via FakeCloud

    def fail(self, method: str, error: Exception, times: int = 1) -> None:
        self._faults[method].append((error, times))

    def _check(self, method: str) -> None:
        self.calls[method] += 1
        faults = self._faults[method]
        if faults:
            error, times = faults[0]
            if times <= 1:
                faults.pop(0)
            else:
                faults[0] = (error, times - 1)
            raise error

    async def _acheck(self, method: str) -> None:
        """Scripted faults first (tests that program an exact failure keep
        exact semantics), then the chaos policy's probabilistic layer."""
        self._check(method)
        if self.chaos is not None:
            await self.chaos.before_call(self.scope, method)


class FakeNodePoolsAPI(_FaultInjector):
    scope = "nodepools"

    def __init__(self, cloud: "FakeCloud"):
        super().__init__()
        self.cloud = cloud
        self.pools: dict[str, NodePool] = {}
        # Capacity ledger: pool name -> (zone, generation, chips) reserved
        # against the cloud's per-zone inventory at begin_create admission;
        # released when the pool's delete (or create-error) settles.
        self._reserved: dict[str, tuple[str, str, int]] = {}
        # Spot-reclaim bookkeeping: creation stamps for pool ages, and the
        # pools already served a preemption notice (one notice per pool).
        self._created_at: dict[str, float] = {}
        self._preempted: set[str] = set()
        # Server-side LRO ledger: name -> (deadline, kind, pool-at-issue).
        # Real clouds keep executing an issued operation whether or not the
        # client that issued it is still alive; the old fake only advanced
        # state from the returned operation's done() poll, so an operator
        # crash mid-create stranded the pool PROVISIONING forever. Every API
        # entry point settles overdue operations first (crash-restart
        # realism: a pool stranded by a dead incarnation still turns
        # RUNNING, a STOPPING pool still disappears).
        self._pending: dict[str, tuple[float, str, NodePool]] = {}

    async def _settle(self, name: str) -> None:
        pend = self._pending.get(name)
        if pend is None or time.monotonic() < pend[0]:
            return
        deadline, kind, target = pend
        self._pending.pop(name, None)
        pool = self.pools.get(name)
        if pool is not target:
            return  # replaced since the op was issued — the op is moot
        if kind == "create":
            pool.status = NP_RUNNING
            await self.cloud.join_nodes(pool)
        elif kind == "create-error":
            pool.status = NP_ERROR
            pool.status_message = "chaos: create operation failed"
            self._release(name)  # a failed create holds no capacity
        elif kind == "delete":
            self.pools.pop(name, None)
            self._release(name)
            self._preempted.discard(name)
            if not self.cloud.leave_orphan_nodes:
                await self.cloud.remove_nodes(name)

    async def _settle_all(self) -> None:
        for name in list(self._pending):
            await self._settle(name)
        await self._sweep_spot()

    # ----------------------------------------------------- capacity model
    def _pool_zone(self, pool: NodePool) -> str:
        return pool.config.labels.get(wk.ZONE_LABEL, self.cloud.zone)

    def _check_capacity(self, pool: NodePool, zone: str) -> None:
        """Admission-time capacity verdict (real clouds answer
        RESOURCE_EXHAUSTED synchronously at node-pool create): a scripted
        chaos dry window dries the zone outright; otherwise the pool's chip
        bill is reserved against the zone × generation inventory. Without a
        ``zones=`` inventory the cloud keeps its legacy unlimited capacity
        (the dry window still applies)."""
        if self.chaos is not None and self.chaos.zone_dry(zone):
            raise APIError(
                f"chaos: zone {zone} out of TPU capacity", code=429)
        inv = self.cloud.inventory
        if not inv:
            return
        gen = pool.config.labels.get(wk.TPU_ACCELERATOR_LABEL, "")
        chips = int(pool.config.labels.get(wk.TPU_CHIPS_LABEL, "0") or 0)
        zone_inv = inv.get(zone)
        if zone_inv is None:
            raise APIError(f"zone {zone} has no TPU capacity pool", code=429)
        have = zone_inv.get(gen, 0)
        if have < chips:
            raise APIError(
                f"zone {zone} out of {gen} capacity "
                f"({have} chips left, {chips} needed)", code=429)
        zone_inv[gen] = have - chips
        self._reserved[pool.name] = (zone, gen, chips)

    def _release(self, name: str) -> None:
        """Return a pool's reserved chips to its zone pool. Pop-guarded so
        the create-error and delete settle paths can both call it without
        double-crediting."""
        res = self._reserved.pop(name, None)
        if res is None:
            return
        zone, gen, chips = res
        zone_inv = self.cloud.inventory.get(zone)
        if zone_inv is not None:
            zone_inv[gen] = zone_inv.get(gen, 0) + chips

    async def _sweep_spot(self) -> None:
        """Spot preemption, driven from API entry (no background task — the
        envtest task-leak gate stays meaningful): a RUNNING spot pool the
        chaos policy verdicts preempted gets its nodes stamped with a
        SpotPreempted=True condition (the preemption notice) and a reclaim
        delete scheduled after ``cloud.spot_reclaim_grace`` — repair
        usually wins the race by replacing the claim first, but the chips
        come back either way when the reclaim settles."""
        if self.chaos is None:
            return
        now = time.monotonic()
        for name, pool in list(self.pools.items()):
            if (not pool.config.spot or pool.status != NP_RUNNING
                    or name in self._preempted or name in self._pending):
                continue
            age = now - self._created_at.get(name, now)
            if not self.chaos.spot_preempt(name, age):
                continue
            self._preempted.add(name)
            await self.cloud.stamp_spot_preempted(name)
            self._pending[name] = (
                now + self.cloud.spot_reclaim_grace, "delete", pool)

    def _count_op_poll(self) -> None:
        # one client-side done() check == one operations.get round-trip
        # against the real API; the non-blocking tracker never issues these
        self.calls["operation_poll"] += 1

    async def begin_create(self, pool: NodePool):
        await self._settle_all()
        await self._acheck("begin_create")
        existing = self.pools.get(pool.name)
        if existing is not None and existing.status != NP_ERROR:
            # GKE 409s any live pool (PROVISIONING, RUNNING, STOPPING);
            # only an ERROR carcass may be re-created in place — the
            # delete+recreate collapsed, which is the op-error soak's
            # replace-never-duplicate contract.
            raise APIError(f"nodepool {pool.name} already exists "
                           f"({existing.status})", code=409)
        # Capacity admission. The zone-keyed probe counter is what the
        # stockout soaks assert on (≤ 1 probe of a dry zone per memo TTL);
        # conflicts above are adoption, not placement probes, so they are
        # deliberately not counted here.
        zone = self._pool_zone(pool)
        self.calls[f"begin_create:{zone}"] += 1
        self._release(pool.name)  # replacing an ERROR carcass frees its bill
        self._check_capacity(pool, zone)
        stored = NodePool.from_dict(pool.to_dict())
        stored.status = NP_PROVISIONING
        self.pools[pool.name] = stored
        self._created_at[pool.name] = time.monotonic()
        self._preempted.discard(pool.name)  # same-name replacement is fresh

        # Chaos partial mode: the LRO "completes" but result() raises and the
        # pool is a dead ERROR carcass with no nodes — the caller's retry
        # must replace it, not duplicate it.
        kind, error = "create", None
        if self.chaos is not None and self.chaos.should(
                "op_error", pool.name, per_attempt=True):
            kind = "create-error"
            error = APIError(f"chaos: operation on {pool.name} failed",
                             code=500)
        self._pending[pool.name] = (
            time.monotonic() + self.cloud.create_latency, kind, stored)

        async def on_done():
            await self._settle(pool.name)

        return TimedOperation(self.cloud.create_latency, result=stored,
                              on_done=on_done, error=error,
                              on_poll=self._count_op_poll)

    async def get(self, name: str) -> NodePool:
        await self._settle_all()
        await self._acheck("get")
        pool = self.pools.get(name)
        if pool is None:
            raise APIError(f"nodepool {name} not found", code=404)
        return NodePool.from_dict(pool.to_dict())

    async def begin_delete(self, name: str):
        await self._settle_all()
        await self._acheck("begin_delete")
        pool = self.pools.get(name)
        if pool is None:
            raise APIError(f"nodepool {name} not found", code=404)
        pool.status = NP_STOPPING
        # supersedes any pending create for the name: delete wins
        self._pending[name] = (
            time.monotonic() + self.cloud.delete_latency, "delete", pool)

        async def on_done():
            await self._settle(name)

        return TimedOperation(self.cloud.delete_latency, on_done=on_done,
                              on_poll=self._count_op_poll)

    async def list(self) -> list[NodePool]:
        await self._settle_all()
        await self._acheck("list")
        return [NodePool.from_dict(p.to_dict()) for p in self.pools.values()]


class FakeQueuedResourcesAPI(_FaultInjector):
    """Queued resources drain ACCEPTED→WAITING→CREATING→ACTIVE, one state per
    ``advance()`` or automatically every ``cloud.qr_step_latency`` seconds."""

    _LADDER = [QR_ACCEPTED, QR_WAITING, QR_CREATING, QR_ACTIVE]

    scope = "queuedresources"

    def __init__(self, cloud: "FakeCloud"):
        super().__init__()
        self.cloud = cloud
        self.resources: dict[str, QueuedResource] = {}
        self._created_at: dict[str, float] = {}

    async def create(self, qr: QueuedResource) -> QueuedResource:
        await self._acheck("create")
        if qr.name in self.resources:
            raise APIError(f"queued resource {qr.name} exists", code=409)
        self.resources[qr.name] = qr
        self._created_at[qr.name] = time.monotonic()
        return qr

    async def get(self, name: str) -> QueuedResource:
        await self._acheck("get")
        qr = self.resources.get(name)
        if qr is None:
            raise APIError(f"queued resource {name} not found", code=404)
        self._auto_advance(qr)
        return qr

    async def delete(self, name: str) -> None:
        await self._acheck("delete")
        if self.resources.pop(name, None) is None:
            raise APIError(f"queued resource {name} not found", code=404)
        self._created_at.pop(name, None)

    async def list(self) -> list[QueuedResource]:
        await self._acheck("list")
        for qr in self.resources.values():
            self._auto_advance(qr)
        return list(self.resources.values())

    def _auto_advance(self, qr: QueuedResource) -> None:
        if qr.state not in self._LADDER:
            return  # SUSPENDED/FAILED are terminal until test flips them
        # Chaos partial mode: wedged mid-ladder — reaches CREATING and stays
        # there forever (the Cloud TPU stuck-PROVISIONING pathology).
        ceiling = len(self._LADDER) - 1
        if self.chaos is not None and self.chaos.should("qr_stuck", qr.name):
            ceiling = self._LADDER.index(QR_CREATING)
        elapsed = time.monotonic() - self._created_at.get(qr.name, 0)
        steps = int(elapsed / self.cloud.qr_step_latency) if self.cloud.qr_step_latency else len(self._LADDER)
        idx = min(self._LADDER.index(QR_ACCEPTED) + steps, ceiling)
        current = self._LADDER.index(qr.state)
        qr.state = self._LADDER[max(idx, current)]

    def suspend(self, name: str, message: str = "stockout") -> None:
        qr = self.resources[name]
        qr.state = "SUSPENDED"
        qr.state_message = message


class FakeCloud:
    """The coherent simulator tying the fake APIs to the kube store."""

    def __init__(self, kube: Client, project: str = "test-project",
                 zone: str = "us-central2-b", cluster: str = "kaito",
                 create_latency: float = 0.05, delete_latency: float = 0.02,
                 node_join_delay: float = 0.0, node_ready_delay: float = 0.0,
                 qr_step_latency: float = 0.02,
                 leave_orphan_nodes: bool = False,
                 chaos=None,
                 zones: Optional[dict[str, dict[str, int]]] = None,
                 spot_reclaim_grace: float = 0.25):
        self.kube = kube
        self.project, self.zone, self.cluster = project, zone, cluster
        self.create_latency = create_latency
        self.delete_latency = delete_latency
        self.node_join_delay = node_join_delay
        self.node_ready_delay = node_ready_delay
        self.qr_step_latency = qr_step_latency
        self.leave_orphan_nodes = leave_orphan_nodes
        # Per-zone × per-generation chip inventory, e.g.
        # ``zones={"us-central2-a": {"v5e": 64}, "us-central2-b": {"v5e": 0}}``
        # — begin_create reserves a pool's chip bill against its zone (the
        # zone read from the pool's topology label, falling back to the
        # cloud's home zone) and verdicts RESOURCE_EXHAUSTED when the pool
        # is short; deletes return the chips. ``None``/empty keeps the
        # legacy unlimited-capacity behavior.
        self.inventory: dict[str, dict[str, int]] = {
            z: dict(gens) for z, gens in (zones or {}).items()}
        # Notice window between the SpotPreempted condition landing on a
        # pool's nodes and the cloud reclaim-deleting the pool.
        self.spot_reclaim_grace = spot_reclaim_grace
        self.nodepools = FakeNodePoolsAPI(self)
        self.queuedresources = FakeQueuedResourcesAPI(self)
        self._join_tasks: list[asyncio.Task] = []
        self.chaos = None
        if chaos is not None:
            self.set_chaos(chaos)

    def set_chaos(self, policy) -> None:
        """Attach a ``chaos.ChaosPolicy`` to every fake API at once."""
        self.chaos = policy
        self.nodepools.chaos = policy
        self.queuedresources.chaos = policy

    async def join_nodes(self, pool: NodePool) -> None:
        """Simulate each host's kubelet joining: Node objects appear with
        providerIDs + GKE/topology labels, unready first, Ready after delay."""
        if self.chaos is not None and self.chaos.should("no_join", pool.name):
            return  # chaos: pool RUNNING, kubelets never phone home
        shape = catalog_lookup(pool.config.labels.get(wk.INSTANCE_TYPE_LABEL, ""))
        capacity = (shape.per_host_capacity() if shape
                    else {wk.TPU_RESOURCE_NAME: "1", "cpu": "96", "memory": "448Gi"})
        # providerIDs carry the zone the pool actually landed in (the
        # placement verdict rides the pool's topology label; single-zone
        # pools fall back to the cloud's home zone)
        zone = self.nodepools._pool_zone(pool)
        for worker in range(pool.initial_node_count):
            name = instance_name(self.cluster, pool.name, worker)
            labels = dict(pool.config.labels)
            labels[wk.GKE_NODEPOOL_LABEL] = pool.name
            labels[wk.TPU_WORKER_INDEX_LABEL] = str(worker)
            labels[wk.HOSTNAME_LABEL] = name
            node = make_node(name, provider_id=provider_id(self.project, zone, name),
                             pool=pool.name, ready=self.node_ready_delay <= 0,
                             labels=labels)
            node.status.capacity = dict(capacity)
            node.status.allocatable = dict(capacity)
            if self.node_join_delay > 0:
                self._join_tasks.append(asyncio.create_task(
                    self._delayed_join(node, self.node_join_delay * (worker + 1))))
            else:
                await self._join(node)

    async def _delayed_join(self, node: Node, delay: float) -> None:
        await asyncio.sleep(delay)
        await self._join(node)

    async def _join(self, node: Node) -> None:
        try:
            await self.kube.create(node)
        except Exception:
            return  # already joined (crash-restart create retry)
        if self.node_ready_delay > 0:
            self._join_tasks.append(asyncio.create_task(self._become_ready(node)))

    async def _become_ready(self, node: Node) -> None:
        await asyncio.sleep(self.node_ready_delay)
        try:
            fresh = await self.kube.get(Node, node.metadata.name)
        except NotFoundError:
            return
        for c in fresh.status.conditions:
            if c.type == "Ready":
                c.status = "True"
                c.reason = "KubeletReady"
        await self.kube.update_status(fresh)

    async def stamp_spot_preempted(self, pool_name: str) -> None:
        """Deliver the preemption notice: SpotPreempted=True on every node
        of the pool, the way GKE surfaces the ACPI shutdown notice as a node
        condition. (The literal matches chaos.nodefaults.SPOT_PREEMPTED —
        importing it here would cycle through fake/__init__.)"""
        for node in await self.kube.list(
                Node, labels={wk.GKE_NODEPOOL_LABEL: pool_name}):
            set_node_condition(node, "SpotPreempted", "True",
                               reason="PreemptionNotice",
                               message="chaos: spot capacity reclaimed")
            try:
                await self.kube.update_status(node)
            except NotFoundError:
                pass

    async def remove_nodes(self, pool_name: str) -> None:
        for node in await self.kube.list(Node, labels={wk.GKE_NODEPOOL_LABEL: pool_name}):
            try:
                await self.kube.delete(Node, node.metadata.name)
            except NotFoundError:
                pass
