"""Flagship workload models for provisioned TPU slices.

The reference provisions GPU VMs for KAITO's LLM workspaces (Llama-family
pods — BASELINE.json "single-host slice: v5e-8 + Llama-7B pod"); this
package is the TPU-native equivalent of that workload: a Llama-style
decoder in pure JAX, sharded over the mesh built from the provisioner's
topology labels (parallel/topology.py).
"""

from .checkpoint import (TrainCheckpointManager, restore_train_state,
                         save_train_state)
from .decode import (KVCache, generate, init_kv_cache, prefill,
                     prefill_chunked)
from .llama import LlamaConfig, forward, init_params, param_specs
from .moe import MoEConfig, init_moe_model, moe_forward
from .moe_serve import moe_cached_forward, moe_prefill
from .speculative import speculative_generate
from .train import make_train_state, make_train_step

__all__ = [
    "LlamaConfig", "init_params", "forward", "param_specs",
    "make_train_state", "make_train_step",
    "KVCache", "init_kv_cache", "prefill", "prefill_chunked", "generate",
    "MoEConfig", "init_moe_model", "moe_forward",
    "moe_cached_forward", "moe_prefill", "speculative_generate",
    "save_train_state", "restore_train_state", "TrainCheckpointManager",
]
