"""Flagship workload models for provisioned TPU slices.

The reference provisions GPU VMs for KAITO's LLM workspaces (Llama-family
pods — BASELINE.json "single-host slice: v5e-8 + Llama-7B pod"); this
package is the TPU-native equivalent of that workload: a Llama-style
decoder in pure JAX, sharded over the mesh built from the provisioner's
topology labels (parallel/topology.py).
"""

from .llama import LlamaConfig, forward, init_params, param_specs

__all__ = ["LlamaConfig", "init_params", "forward", "param_specs"]
