"""Sharded train-state checkpointing (orbax).

The control plane is deliberately stateless (SURVEY.md §5 — all state in CR
conditions + cloud labels); the WORKLOAD is not: a slice-group training job
must survive preemption/repair, which is routine on TPU capacity (the
provisioner's auto-repair deletes and replaces unhealthy slices, §3.5). This
module gives the flagship train loop crash-consistent save/restore:

- saves are **sharding-aware and async-capable**: each host writes only its
  shards (orbax OCDBT), so multi-host slices checkpoint at ICI/DCN-disjoint
  disk bandwidth, not through one coordinator;
- restore is **mesh-flexible**: the target shardings come from the CURRENT
  mesh's param specs, so a checkpoint taken on a dp-heavy mesh restores onto
  a tp-heavy one (or a different slice count after repair) with orbax doing
  the resharding — exactly the elastic-recovery story the provisioner's
  repair loop implies;
- the on-disk tree is the logical layer order: pipeline layouts
  (to_pipeline_layout's interleave) must be applied AFTER restore, keeping
  checkpoints schedule-agnostic.
"""

from __future__ import annotations

import jax
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding

from .llama import LlamaConfig, init_params, param_specs


def save_train_state(path, params, opt_state, step: int) -> None:
    """Write {params, opt_state, step} atomically (temp dir + rename, which
    orbax does internally — a killed save never corrupts the previous one)."""
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(str(path), {"params": params, "opt_state": opt_state,
                               "step": step})


def restore_train_state(path, mesh, cfg: LlamaConfig, optimizer, specs=None):
    """(params, opt_state, step) restored ONTO ``mesh`` — target shardings
    derive from the current mesh/specs, not whatever mesh wrote the
    checkpoint, so restore doubles as reshard.

    ``optimizer`` is required, not defaulted: the abstract opt-state target
    (shapes AND dtypes) comes from it, and orbax casts stored leaves to the
    target dtype without complaint — restoring a bf16-mu checkpoint through
    an f32-mu default would silently diverge from the uninterrupted run."""
    if specs is None:
        specs = param_specs(cfg)

    # abstract target: shapes/dtypes from a shape-only init, shardings from
    # the current mesh — orbax reshards the stored arrays to match
    shapes = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    abstract_params = jax.tree.map(
        lambda sd, sp: jax.ShapeDtypeStruct(
            sd.shape, sd.dtype, sharding=NamedSharding(mesh, sp)),
        shapes, specs)
    # opt-state shardings come from compiling optimizer.init against the
    # abstract params — the same inheritance make_train_state relies on —
    # so every leaf restores placed, never via orbax's unsafe
    # sharding-from-file fallback
    compiled_init = jax.jit(optimizer.init).lower(abstract_params).compile()

    def _on_mesh(sh):
        # constants (e.g. the Adam step count) compile to a single-device
        # placement; restore them replicated over the current mesh instead
        if len(sh.device_set) == mesh.devices.size:
            return sh
        return NamedSharding(mesh, jax.sharding.PartitionSpec())

    abstract_opt = jax.tree.map(
        lambda sd, sh: jax.ShapeDtypeStruct(sd.shape, sd.dtype,
                                            sharding=_on_mesh(sh)),
        jax.eval_shape(optimizer.init, abstract_params),
        compiled_init.output_shardings)

    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(
            str(path), {"params": abstract_params,
                        "opt_state": abstract_opt, "step": 0})
    return restored["params"], restored["opt_state"], int(restored["step"])
