"""Sharded train-state checkpointing (orbax).

The control plane is deliberately stateless (SURVEY.md §5 — all state in CR
conditions + cloud labels); the WORKLOAD is not: a slice-group training job
must survive preemption/repair, which is routine on TPU capacity (the
provisioner's auto-repair deletes and replaces unhealthy slices, §3.5). This
module gives the flagship train loop crash-consistent save/restore:

- saves are **sharding-aware and async-capable**: each host writes only its
  shards (orbax OCDBT), so multi-host slices checkpoint at ICI/DCN-disjoint
  disk bandwidth, not through one coordinator;
- restore is **mesh-flexible**: the target shardings come from the CURRENT
  mesh's param specs, so a checkpoint taken on a dp-heavy mesh restores onto
  a tp-heavy one (or a different slice count after repair) with orbax doing
  the resharding — exactly the elastic-recovery story the provisioner's
  repair loop implies;
- the on-disk tree SHOULD be the logical layer order (schedule-agnostic);
  states built by make_pipeline_train_state carry interleaved blocks, so
  every checkpoint records its ``(n_stages, n_chunks)`` layout and restore
  REFUSES a layout mismatch — a silent mismatch would permute layers.
  Convert with parallel.pipeline.from_pipeline_layout /
  to_pipeline_layout when moving a checkpoint between geometries.
"""

from __future__ import annotations

import jax
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding

from .llama import LlamaConfig, init_params, param_specs


def _layout_entry(n_stages: int, n_chunks: int) -> dict:
    return {"n_stages": int(n_stages), "n_chunks": int(n_chunks)}


def save_train_state(path, params, opt_state, step: int, *,
                     n_stages: int = 1, n_chunks: int = 1) -> None:
    """Write {params, opt_state, step, layout} atomically (temp dir +
    rename, which orbax does internally — a killed save never corrupts the
    previous one).

    ``n_stages``/``n_chunks``: the pipeline storage layout of
    params["blocks"] (1/1 = logical layer order). States from
    make_pipeline_train_state are interleaved (to_pipeline_layout) and MUST
    be stamped with their geometry — restore fails loudly on mismatch
    instead of silently permuting layers."""
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(str(path), {"params": params, "opt_state": opt_state,
                               "step": step,
                               "layout": _layout_entry(n_stages, n_chunks)})


def _abstract_target(mesh, cfg: LlamaConfig, optimizer, specs=None) -> dict:
    """The restore target: shapes/dtypes from a shape-only init, shardings
    from the CURRENT mesh — orbax reshards the stored arrays to match."""
    if specs is None:
        specs = param_specs(cfg)
    shapes = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    abstract_params = jax.tree.map(
        lambda sd, sp: jax.ShapeDtypeStruct(
            sd.shape, sd.dtype, sharding=NamedSharding(mesh, sp)),
        shapes, specs)
    # opt-state shardings come from compiling optimizer.init against the
    # abstract params — the same inheritance make_train_state relies on —
    # so every leaf restores placed, never via orbax's unsafe
    # sharding-from-file fallback
    compiled_init = jax.jit(optimizer.init).lower(abstract_params).compile()

    def _on_mesh(sh):
        # constants (e.g. the Adam step count) compile to a single-device
        # placement; restore them replicated over the current mesh instead
        if len(sh.device_set) == mesh.devices.size:
            return sh
        return NamedSharding(mesh, jax.sharding.PartitionSpec())

    abstract_opt = jax.tree.map(
        lambda sd, sh: jax.ShapeDtypeStruct(sd.shape, sd.dtype,
                                            sharding=_on_mesh(sh)),
        jax.eval_shape(optimizer.init, abstract_params),
        compiled_init.output_shardings)
    return {"params": abstract_params, "opt_state": abstract_opt, "step": 0,
            "layout": _layout_entry(1, 1)}


def _check_layout(restored: dict, n_stages: int, n_chunks: int) -> None:
    got = restored.get("layout", _layout_entry(1, 1))
    want = _layout_entry(n_stages, n_chunks)
    if got != want:
        raise ValueError(
            f"checkpoint blocks are in pipeline layout {got}, but restore "
            f"expected {want} — restoring across layouts silently permutes "
            "layers. Convert with parallel.pipeline.from_pipeline_layout / "
            "to_pipeline_layout, or restore with the matching "
            "n_stages/n_chunks.")


def restore_train_state(path, mesh, cfg: LlamaConfig, optimizer, specs=None,
                        *, n_stages: int = 1, n_chunks: int = 1):
    """(params, opt_state, step) restored ONTO ``mesh`` — target shardings
    derive from the current mesh/specs, not whatever mesh wrote the
    checkpoint, so restore doubles as reshard.

    ``optimizer`` is required, not defaulted: the abstract opt-state target
    (shapes AND dtypes) comes from it, and orbax casts stored leaves to the
    target dtype without complaint — restoring a bf16-mu checkpoint through
    an f32-mu default would silently diverge from the uninterrupted run.

    ``n_stages``/``n_chunks`` must match the layout stamped at save time
    (ValueError otherwise — a mismatch would permute layers). Checkpoints
    written before layout stamping restore as logical order (1, 1)."""
    target = _abstract_target(mesh, cfg, optimizer, specs)
    with ocp.StandardCheckpointer() as ckptr:
        try:
            restored = ckptr.restore(str(path), target)
        except ValueError:
            # pre-layout checkpoint: orbax refuses a target tree with a key
            # the file lacks; retry without it (a genuinely different
            # mismatch fails again here, with the real error)
            target.pop("layout")
            restored = ckptr.restore(str(path), target)
    _check_layout(restored, n_stages, n_chunks)
    return restored["params"], restored["opt_state"], int(restored["step"])


class TrainCheckpointManager:
    """Rotating checkpoint schedule around save/restore_train_state.

    The loop-facing wrapper a long training job needs: save every
    ``save_interval_steps``, keep the newest ``max_to_keep`` (older ones
    deleted — TPU-slice-sized states fill disks fast), resume from the
    newest on restart. Orbax's CheckpointManager provides the bookkeeping;
    the sharding-aware abstract-target restore is ours (restore_train_state
    semantics: restores ONTO the current mesh, resharding as needed).
    """

    def __init__(self, directory, mesh, cfg: LlamaConfig, optimizer,
                 specs=None, max_to_keep: int = 3,
                 save_interval_steps: int = 100,
                 n_stages: int = 1, n_chunks: int = 1):
        self.directory = str(directory)
        self.mesh = mesh
        self.cfg = cfg
        self.optimizer = optimizer
        self.specs = specs
        self.n_stages = n_stages
        self.n_chunks = n_chunks
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps))

    def maybe_save(self, step: int, params, opt_state) -> bool:
        """Save iff the schedule says so; returns whether a save happened."""
        return self._mgr.save(
            step, args=ocp.args.StandardSave(
                {"params": params, "opt_state": opt_state, "step": step,
                 "layout": _layout_entry(self.n_stages, self.n_chunks)}))

    def latest_step(self):
        return self._mgr.latest_step()

    def wait_until_finished(self) -> None:
        """Block until in-flight async saves commit."""
        self._mgr.wait_until_finished()

    def restore_latest(self):
        """(params, opt_state, step) from the newest checkpoint, placed on
        the current mesh — or None when the directory is empty (fresh run).
        Waits out in-flight saves first: the manager registers a step
        before its files finish committing, so restoring immediately after
        maybe_save would otherwise read a half-written tree."""
        self._mgr.wait_until_finished()
        step = self._mgr.latest_step()
        if step is None:
            return None
        # restore THROUGH the manager (not a hand-built path — the step
        # directory layout is orbax's own convention)
        target = _abstract_target(self.mesh, self.cfg, self.optimizer,
                                  self.specs)
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))
        except ValueError:
            # pre-layout checkpoint (see restore_train_state)
            target.pop("layout")
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))
        _check_layout(restored, self.n_stages, self.n_chunks)
        return (restored["params"], restored["opt_state"],
                int(restored["step"]))

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
