"""KV-cache inference: prefill + single-token decode + generate.

The serving-side counterpart of models/train.py — KAITO provisions these
slices to serve models, so the framework ships the decode loop, TPU-first:

- **one cached forward** serves both phases: prefill runs the whole prompt
  through it (S tokens, causal within the window, writing the cache),
  decode runs it with S=1 — no separate code paths to diverge;
- **static shapes throughout**: the cache is a fixed [L, B, Hkv, max_len, Dh]
  ring of buffers updated with ``lax.dynamic_update_slice`` (head-major:
  each head's sequence is contiguous, so the flash prefill kernel views it
  as [B·Hkv, max_len, Dh] with a FREE reshape — no transposed copy of the
  cache is ever materialized); attention scores against the full cache
  width with a length mask (no data-dependent shapes, so XLA compiles
  exactly two programs: prefill and decode step);
- **generate is one ``lax.scan``** over decode steps — the whole
  autoregressive loop is a single compiled program, no host round-trips
  per token;
- **ragged batches serve left-padded** (``pad_id``): pad keys are masked
  out of attention and RoPE counts from each row's first real token, so a
  padded row generates exactly what it would alone;
- tensor parallelism needs nothing new: cache head dims carry the same
  ``model``-axis specs as the weights (``kv_cache_specs``), and GSPMD
  inserts the collectives exactly as in training.

GQA: the cache stores Hkv heads (the memory win is the point of GQA);
scoring groups queries as [B, S, Hkv, group, Dh] against the un-repeated
cache — the K/V expansion never materializes.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.topology import AXIS_MODEL
from .llama import (LlamaConfig, _mlp_half, _project_qkv, _rmsnorm,
                    resolve_attn as _resolve_attn)

NEG_INF = -1.0e30


class KVCache(NamedTuple):
    k: jax.Array        # [L, B, Hkv, max_len, Dh] (head-major — see module doc)
    v: jax.Array        # [L, B, Hkv, max_len, Dh]
    length: jax.Array   # scalar int32 — tokens written so far
    # int8 mode only (cfg.kv_cache_dtype="int8"): per-token-per-head
    # symmetric scales, [L, B, Hkv, max_len, 1] f32 — None in fp mode
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None


def _kv_int8(cfg: LlamaConfig) -> bool:
    """Validated kv_cache_dtype dispatch — unknown values raise instead of
    silently serving a full-precision cache (same loud-validation rule as
    resolve_attn: a typo must not quietly halve the promised headroom)."""
    if cfg.kv_cache_dtype not in ("auto", "int8"):
        raise ValueError(f"unknown kv_cache_dtype {cfg.kv_cache_dtype!r}; "
                         "expected 'auto'|'int8'")
    return cfg.kv_cache_dtype == "int8"


def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int) -> KVCache:
    """Zeroed cache per cfg.kv_cache_dtype: "auto" stores act_dtype;
    "int8" stores int8 values + f32 per-token-per-head scales — HALF the
    serving cache HBM at bf16 activations (the scales add 1/Dh), so double
    the batch or context per chip. Scores dequantize on the fly."""
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    if _kv_int8(cfg):
        sshape = shape[:-1] + (1,)
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       length=jnp.zeros((), jnp.int32),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
    return KVCache(k=jnp.zeros(shape, cfg.act_dtype),
                   v=jnp.zeros(shape, cfg.act_dtype),
                   length=jnp.zeros((), jnp.int32))


def kv_cache_specs(cfg: LlamaConfig) -> KVCache:
    """PartitionSpecs mirroring the attention weights' tp layout (kv heads
    over ``model``) so the cache shards with the model."""
    spec = P(None, None, AXIS_MODEL, None, None)
    if _kv_int8(cfg):
        return KVCache(k=spec, v=spec, length=P(),
                       k_scale=spec, v_scale=spec)
    return KVCache(k=spec, v=spec, length=P())


def _quantize_kv(x):
    """Per-token-per-head symmetric int8: [B, S, Hkv, Dh] →
    (int8 values, f32 scales [B, S, Hkv, 1]). Head-dim max keeps the
    quantization step proportional to each token's own key/value magnitude
    (RoPE'd keys are norm-preserving, so the range is stable)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scl = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scl), -127, 127).astype(jnp.int8)
    return q, scl


def _cached_attention(q, k_cache, v_cache, start, scale, impl="dense",
                      pad_lens=None, k_scale=None, v_scale=None,
                      window=None, sinks=0):
    """q: [B, S, Hq, Dh] vs the FULL cache width with a validity mask —
    a key at position p is attendable iff p <= start + query_idx (causal,
    and positions beyond the written prefix are masked by the same bound).
    GQA: queries grouped [B, S, Hkv, group, Dh]; the cache is never
    repeated/materialized at Hq width.

    ``impl="flash"``: prefill-sized S (tiles into ≥128 blocks) takes the
    cache-aware Pallas kernel (ops/flash_attention.py:flash_attention_cached)
    — blocks past the causal frontier are neither computed nor DMA'd, so
    continuing a partially-filled cache stops paying the dense S×max_len
    sweep. S=1 decode steps take the dedicated decode kernel
    (flash_attention_decode): one fetch of each kv head's live prefix
    serves all its GQA queries, and a step costs O(start) HBM traffic
    instead of the dense sweep's O(max_len) (pad_lens supported in-kernel).

    k_cache/v_cache: [B, Hkv, max_len, Dh] head-major (one layer's slice).

    ``pad_lens`` [B] (left-padded ragged batches — the standard serving
    layout): row b's cache positions [0, pad_lens[b]) hold pad tokens that
    no query may attend to. Both kernels mask pads in-kernel (S=1 via the
    decode kernel's meta, prefill via the cached kernel's) — no serving
    phase pays the dense sweep for being ragged. Pad-QUERY positions'
    outputs are unread garbage and DIFFER between impls (kernel: zero;
    dense: uniform V-average) — consume only real positions.

    ``k_scale``/``v_scale`` [B, Hkv, max_len, 1]: int8-cache dequant
    scales. The flash kernel dequantizes IN VMEM (only int8 bytes cross
    HBM); the dense path dequantizes in the read einsum.

    ``window`` (cfg.sliding_window): query p attends keys in
    (p − window, p] — both kernels bound their DMA to the window, so SWA
    serving cost is O(window) per step regardless of cached history.

    ``start`` may be a scalar (all rows at the same length — the plain
    serving loop) or a [B] vector (per-row lengths — batched speculative
    decoding, where rows accept different numbers of draft tokens per
    round). Vector start reaches the decode kernel via its per-row meta;
    other kernel paths gate on scalar start and fall back to the dense
    sweep, which masks per row."""
    B, S, Hq, Dh = q.shape
    Hkv, max_len = k_cache.shape[1], k_cache.shape[2]
    start = jnp.asarray(start)
    if impl == "flash":
        # short blocks (decode steps S=1, speculative verify S=spec_k+1,
        # tiny continuations) take the decode/verify kernel: O(start+S)
        # cache traffic instead of the dense sweep's O(max_len)
        from ..ops.flash_attention import (decode_flash_supported,
                                           flash_attention_decode)
        if decode_flash_supported(max_len, Hq, Hkv, S=S):
            return flash_attention_decode(q, k_cache, v_cache, start,
                                          scale=scale, k_scale=k_scale,
                                          v_scale=v_scale, pad_lens=pad_lens,
                                          window=window, sinks=sinks)
    if impl == "flash" and start.ndim == 0:
        from ..ops.flash_attention import (cached_flash_supported,
                                           flash_attention_cached)
        if cached_flash_supported(S, max_len, Hq, Hkv):
            return flash_attention_cached(q, k_cache, v_cache, start,
                                          scale=scale, k_scale=k_scale,
                                          v_scale=v_scale, pad_lens=pad_lens,
                                          window=window, sinks=sinks)
    kf = k_cache.astype(jnp.float32)
    vf = v_cache.astype(jnp.float32)
    if k_scale is not None:
        kf = kf * k_scale
        vf = vf * v_scale
    group = Hq // Hkv
    qg = q.reshape(B, S, Hkv, group, Dh)
    s = jnp.einsum("bqhgd,bhkd->bhgqk", qg.astype(jnp.float32),
                   kf) * scale
    key_pos = jnp.arange(max_len)                      # [K]
    # [B, S] query positions (scalar start broadcasts to every row)
    q_pos = jnp.broadcast_to(jnp.reshape(start, (-1, 1))
                             + jnp.arange(S), (B, S))
    mask = key_pos[None, None, :] <= q_pos[:, :, None]   # [B,S,K] causal
    if window is not None:
        in_win = key_pos[None, None, :] > q_pos[:, :, None] - window
        if sinks and pad_lens is None:
            # StreamingLLM: the first ``sinks`` keys stay attendable
            in_win = in_win | (key_pos[None, None, :] < sinks)
        mask = mask & in_win
    if pad_lens is not None:
        live = key_pos[None, None, :] >= pad_lens[:, None, None]  # [B, 1, K]
        mask = mask & live
        if window is not None and sinks:
            # per-row sinks: the first ``sinks`` REAL keys (after the pads)
            sink = (key_pos[None, None, :]
                    < pad_lens[:, None, None] + sinks)            # [B, 1, K]
            causal_written = key_pos[None, None, :] <= q_pos[:, :, None]
            mask = mask | (causal_written & live & sink)
    s = jnp.where(mask[:, None, None], s, NEG_INF)     # [B,1,1,S,K] bcast
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhgqk,bhkd->bqhgd", p, vf)
    return o.reshape(B, S, Hq, Dh).astype(q.dtype)


def cached_forward(params: dict, tokens, cache: KVCache, cfg: LlamaConfig,
                   pad_lens=None):
    """Forward over ``tokens`` [B, S] starting at cache.length; returns
    (logits [B, S, V], updated cache). S is the prompt for prefill, 1 for a
    decode step — same program shape either way.

    ``pad_lens`` [B] int32: left-pad counts for ragged batches (row b's
    first pad_lens[b] cache slots are dead padding — excluded from
    attention, and RoPE positions count from the first REAL token so each
    row sees positions 0,1,2,... regardless of padding).

    PRECONDITION (caller-owned): ``cache.length + S <= max_len``. The write
    index is traced, so this cannot be checked here; past the bound,
    ``dynamic_update_slice`` clamps and silently corrupts the cache.
    ``generate`` enforces it; manual decode loops must too.

    ``cache.length`` may be a scalar (the plain serving loop) or a [B]
    vector (per-row lengths — batched speculative decoding): writes then
    land at each row's own offset and attention masks per row."""
    _resolve_attn(cfg.attn_impl, cfg.sliding_window,
                  cfg.attn_sinks)  # validate loudly — the dense fallback in
    # _cached_attention is shape-driven, not a typo escape hatch
    ad = cfg.act_dtype
    B, S = tokens.shape
    start = cache.length
    per_row = jnp.ndim(start) == 1
    positions = (jnp.reshape(start, (-1, 1)) if per_row else start) \
        + jnp.arange(S, dtype=jnp.int32)
    if pad_lens is not None:
        # per-row REAL positions: pad rows clip to 0 (their k/v are masked
        # out of every attention, so their rope angle is irrelevant)
        if not per_row:
            positions = positions[None, :]
        positions = jnp.maximum(positions - pad_lens[:, None], 0)
    scale = cfg.head_dim ** -0.5

    x = params["embed"].astype(ad)[tokens]
    int8 = _kv_int8(cfg)
    if int8 != (cache.k_scale is not None):
        raise ValueError(
            f"kv_cache_dtype={cfg.kv_cache_dtype!r} but the cache was "
            f"built {'WITH' if cache.k_scale is not None else 'without'} "
            "int8 scales — cfg and init_kv_cache(cfg, ...) must agree")

    def write(buf, new):
        # new tokens arrive token-major [B, S, ., Dh']; the head-major
        # transpose is O(S) — tiny next to the cache it writes into
        nh = new.transpose(0, 2, 1, 3)
        if per_row:   # per-row offsets: a batched scatter via vmap
            return jax.vmap(
                lambda b, n, s: lax.dynamic_update_slice(b, n, (0, s, 0))
            )(buf, nh, start)
        return lax.dynamic_update_slice(buf, nh, (0, 0, start, 0))

    def body(carry, layer):
        h = carry
        if int8:
            lp, k_cache, v_cache, k_scl, v_scl = layer
        else:
            lp, k_cache, v_cache = layer
            k_scl = v_scl = None

        a = _rmsnorm(h, lp["ln_attn"], cfg.norm_eps)
        q, k, v = _project_qkv(a, lp, cfg, positions)

        if int8:
            kq, ks_ = _quantize_kv(k)
            vq, vs_ = _quantize_kv(v)
            k_cache, v_cache = write(k_cache, kq), write(v_cache, vq)
            k_scl, v_scl = write(k_scl, ks_), write(v_scl, vs_)
        else:
            k_cache, v_cache = write(k_cache, k), write(v_cache, v)

        o = _cached_attention(q, k_cache, v_cache, start, scale,
                              impl=cfg.attn_impl, pad_lens=pad_lens,
                              k_scale=k_scl, v_scale=v_scl,
                              window=cfg.sliding_window,
                              sinks=cfg.attn_sinks)
        h = h + o.reshape(B, S, cfg.n_heads * cfg.head_dim) \
            @ lp["wo"].astype(ad)
        h = _mlp_half(h, lp, cfg)
        out = ((k_cache, v_cache, k_scl, v_scl) if int8
               else (k_cache, v_cache))
        return h, out

    xs = ((params["blocks"], cache.k, cache.v, cache.k_scale, cache.v_scale)
          if int8 else (params["blocks"], cache.k, cache.v))
    x, caches = lax.scan(body, x, xs)
    x = _rmsnorm(x, params["ln_final"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)
    if int8:
        k_new, v_new, ks_new, vs_new = caches
        new_cache = KVCache(k=k_new, v=v_new, length=start + S,
                            k_scale=ks_new, v_scale=vs_new)
    else:
        k_new, v_new = caches
        new_cache = KVCache(k=k_new, v=v_new, length=start + S)
    return logits, new_cache


def _prefill_forward(params: dict, tokens, max_len: int, cfg: LlamaConfig):
    """Prefill specialization for a FRESH cache: with nothing written yet,
    attention is plain causal attention over the prompt window — S×S scores
    (flash-kernel eligible via cfg.attn_impl) instead of cached_forward's
    S×max_len masked sweep, and the cache is written once at offset 0."""
    assert cfg.sliding_window is None, \
        "fresh fast path has no window mask — prefill() routes SWA configs " \
        "to the general cached forward"
    ad = cfg.act_dtype
    B, S = tokens.shape
    positions = jnp.arange(S, dtype=jnp.int32)
    attn = _resolve_attn(cfg.attn_impl)

    x = params["embed"].astype(ad)[tokens]

    def body(h, lp):
        a = _rmsnorm(h, lp["ln_attn"], cfg.norm_eps)
        q, k, v = _project_qkv(a, lp, cfg, positions)
        o = attn(q, k, v)
        h = h + o.reshape(B, S, cfg.n_heads * cfg.head_dim) \
            @ lp["wo"].astype(ad)
        h = _mlp_half(h, lp, cfg)
        return h, (k, v)

    x, (ks, vs) = lax.scan(body, x, params["blocks"])
    x = _rmsnorm(x, params["ln_final"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)

    # scan stacks token-major [L, B, S, Hkv, Dh]; one O(S)-sized transpose
    # to head-major, then pad the sequence dim out to max_len. int8
    # quantization applies at the STORE: the prompt window above attended
    # full-precision k/v (slightly better than the general path, which
    # scores against the quantized cache) — later decode steps read the
    # quantized buffers either way.
    ks = ks.transpose(0, 1, 3, 2, 4)
    vs = vs.transpose(0, 1, 3, 2, 4)
    pad = [(0, 0), (0, 0), (0, 0), (0, max_len - S), (0, 0)]
    if _kv_int8(cfg):
        kq, kscl = _quantize_kv(ks)
        vq, vscl = _quantize_kv(vs)
        cache = KVCache(k=jnp.pad(kq, pad), v=jnp.pad(vq, pad),
                        length=jnp.asarray(S, jnp.int32),
                        k_scale=jnp.pad(kscl, pad),
                        v_scale=jnp.pad(vscl, pad))
    else:
        cache = KVCache(k=jnp.pad(ks, pad), v=jnp.pad(vs, pad),
                        length=jnp.asarray(S, jnp.int32))
    return logits, cache


def prefill(params: dict, prompt, cache: KVCache, cfg: LlamaConfig, *,
            fresh: bool = False, pad_lens=None):
    """(last-token logits [B, V], cache) after consuming the prompt.
    ``fresh=True`` (statically-known-empty cache, e.g. from generate) takes
    the S×S fast path; otherwise the general cached forward runs, correct
    for continuing a partially-filled cache. ``pad_lens`` [B] serves a
    left-padded ragged batch (see cached_forward) — incompatible with the
    fresh fast path, whose plain causal attention can't exclude pad keys.
    ``cfg.sliding_window`` likewise routes to the general path, whose
    kernels window-mask AND bound their DMA to the window."""
    if cfg.sliding_window is not None:
        fresh = False
    if fresh:
        if pad_lens is not None:
            raise ValueError("pad_lens requires fresh=False — the fresh "
                             "fast path cannot mask pad keys")
        logits, cache = _prefill_forward(params, prompt,
                                         cache.k.shape[3], cfg)
    else:
        logits, cache = cached_forward(params, prompt, cache, cfg,
                                       pad_lens=pad_lens)
    return logits[:, -1], cache


# cache donation: each chunk's update reuses the cache buffers in place —
# without it every chunk holds input+output copies of the full-size cache,
# doubling peak HBM in exactly the near-capacity regime chunking targets
_cached_forward_jit = jax.jit(cached_forward, static_argnums=(3,),
                              donate_argnums=(2,))


def prefill_chunked(params: dict, prompt, cache: KVCache, cfg: LlamaConfig,
                    *, chunk: int = 2048, pad_lens=None):
    """(last-token logits [B, V], cache) — prefill in ``chunk``-sized
    pieces so peak activation memory is O(chunk·S) instead of O(S²)-ish
    for very long prompts, while each piece still takes the cache-aware
    flash kernel (blocks tile per chunk). For the dense family this is
    numerically identical to one cached_forward over the whole prompt:
    chunk c attends to everything written before it plus its own causal
    prefix — exactly the full causal mask, evaluated piecewise. Each piece
    runs through a jitted cached_forward, so at most two programs compile
    (full chunk + remainder). Call it EAGERLY — under an outer jit the
    loop unrolls into one trace that grows with S/chunk. The input
    ``cache`` is DONATED (updated in place on device); don't reuse the
    passed-in object.

    MoE family: supported, with a routing-semantics difference — expert
    capacity is computed PER CHUNK and tokens only compete for expert
    slots within their chunk (attention is still exact). Whole-prompt
    routing competes across all S tokens; at capacities where neither
    drops, the two are identical (tests pin this)."""
    B, S = prompt.shape
    if S == 0 or chunk <= 0:
        raise ValueError(f"need a non-empty prompt (S={S}) and a positive "
                         f"chunk ({chunk})")
    step = family_step_jit(cfg)
    logits = None
    for off in range(0, S, chunk):
        piece = prompt[:, off:off + chunk]     # slice stop clamps at S
        logits, cache = step(params, piece, cache, cfg, pad_lens=pad_lens)
    return logits[:, -1], cache


def family_fns(cfg, pad_lens=None, fresh: bool = False,
               dropless_step: bool = False):
    """(prefill_fn, step_fn), each (params, tokens, cache) → (logits,
    cache), dispatched on the config's model family — THE dispatch point
    shared by generate() and speculative_generate so the two can never
    serve different code paths for the same config. ``fresh``: dense-only
    fast path for statically-empty caches (ignored for MoE, which has
    none). Pass fresh=False with pad_lens — the fast path cannot mask pad
    keys and prefill raises; sliding_window is rerouted inside prefill.
    ``dropless_step``: MoE-only — step_fn routes with capacity = its block
    width, so a multi-token step (speculative verify) cannot capacity-drop
    and its logits equal sequential single-token decoding's (dense configs
    have no cross-token FFN coupling; the flag is a no-op)."""
    from .moe import MoEConfig
    if isinstance(cfg, MoEConfig):
        from .moe_serve import moe_cached_forward, moe_prefill
        return (lambda p, t, c: moe_prefill(p, t, c, cfg,
                                            pad_lens=pad_lens),
                lambda p, t, c: moe_cached_forward(p, t, c, cfg,
                                                   pad_lens=pad_lens,
                                                   dropless=dropless_step))
    return (lambda p, t, c: prefill(p, t, c, cfg, fresh=fresh,
                                    pad_lens=pad_lens),
            lambda p, t, c: cached_forward(p, t, c, cfg,
                                           pad_lens=pad_lens))


def family_step_jit(cfg):
    """The jitted, cache-DONATING cached-forward for the config's family
    (prefill_chunked's inner step) — lives next to family_fns so family
    dispatch stays in one place."""
    from .moe import MoEConfig
    if isinstance(cfg, MoEConfig):
        from .moe_serve import _moe_cached_forward_jit
        return _moe_cached_forward_jit
    return _cached_forward_jit


def filter_logits(logits, temperature: float, top_k, top_p):
    """The serving sampling distribution in one place: temperature →
    top-k → top-p (standard order). generate() samples from it and
    speculative_generate accepts/resamples against it — the two MUST stay
    the same composition or speculative sampling stops preserving the
    serving distribution (models/speculative.py's correctness theorem)."""
    logits = logits / temperature
    if top_k is not None:
        logits = _filter_top_k(logits, top_k)
    if top_p is not None:
        logits = _filter_top_p(logits, top_p)
    return logits


def validate_sampling_args(temperature: float, top_k, top_p, key) -> None:
    """Shared loud validation for every sampling entry point."""
    if temperature > 0 and key is None:
        raise ValueError(
            "sampling (temperature>0) requires an explicit PRNG key — "
            "sampling without one would be silently deterministic")
    if top_k is not None and not 0 < top_k:
        raise ValueError(f"top_k must be positive, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def _filter_top_k(logits, top_k: int):
    """Keep the k highest logits per row; the rest → -inf."""
    vals = jax.lax.top_k(logits, top_k)[0]
    return jnp.where(logits >= vals[..., -1:], logits, NEG_INF)


def _filter_top_p(logits, top_p: float):
    """Nucleus filter: keep the smallest set of tokens whose probability
    mass reaches ``top_p`` (always ≥1 token — the exclusive cumsum keeps
    the top token even when its own mass exceeds top_p)."""
    probs = jax.nn.softmax(logits, axis=-1)
    sorted_probs = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    exclusive_csum = jnp.cumsum(sorted_probs, axis=-1) - sorted_probs
    keep = exclusive_csum < top_p
    # per-row probability threshold = smallest kept prob (2.0 > any prob)
    thresh = jnp.min(jnp.where(keep, sorted_probs, 2.0), axis=-1,
                     keepdims=True)
    return jnp.where(probs >= thresh, logits, NEG_INF)


def generate(params: dict, prompt, cfg: LlamaConfig, *, max_new_tokens: int,
             max_len: int = None, temperature: float = 0.0,
             top_k: int = None, top_p: float = None, key=None,
             pad_id: int = None, eos_id: int = None,
             return_logprobs: bool = False):
    """Autoregressive generation: prefill, then ONE lax.scan of decode
    steps. prompt: [B, S0] int32 → [B, max_new_tokens] int32.

    temperature 0 = greedy (top_k/top_p ignored). temperature > 0 samples
    — ``key`` is then REQUIRED (a silent default key would make "sampled"
    serving output deterministic across calls; same required-argument
    rationale as restore_train_state's optimizer). Filters compose in the
    standard serving order: temperature → top_k → top_p → categorical.

    Ragged batches: LEFT-pad prompts to a common S0 with ``pad_id`` (the
    standard serving layout — every row's last prompt token lands at the
    same position, so one prefill logit slice serves the whole batch).
    Pad tokens are excluded from attention and RoPE positions count from
    each row's first real token, so a padded row generates exactly what it
    would alone. Every row must contain at least one real token.

    ``eos_id``: rows that emit it are FINISHED — every later position in
    that row comes back as eos_id (the scan runs to max_new_tokens; XLA
    has no early exit, finished rows just stop contributing real tokens —
    the HF unfinished_sequences convention, so downstream truncation is a
    simple == eos_id scan).

    ``return_logprobs``: also return each emitted token's log-probability
    under the FINAL sampling distribution (post temperature/top-k/top-p —
    what the sampler actually drew from; greedy reports the unfiltered
    distribution) as a second [B, max_new_tokens] f32 array. Positions
    forced to eos by row finishing report 0.0."""
    B, S0 = prompt.shape
    if max_len is None:
        max_len = S0 + max_new_tokens
    assert S0 + max_new_tokens <= max_len, (S0, max_new_tokens, max_len)
    validate_sampling_args(temperature, top_k, top_p, key)

    pad_lens = None
    if pad_id is not None:
        # leading-pad count per row == index of the first real token
        pad_lens = jnp.argmax((prompt != pad_id).astype(jnp.int32),
                              axis=1).astype(jnp.int32)

    # family dispatch (dense vs MoE forwards) — shared with speculative
    prefill_fn, step_fn = family_fns(cfg, pad_lens=pad_lens,
                                     fresh=pad_id is None)
    cache = init_kv_cache(cfg, B, max_len)
    logits, cache = prefill_fn(params, prompt, cache)

    def pick(logits, key):
        """(token, logprob-under-the-sampling-distribution) per row."""
        if temperature > 0:
            logits = filter_logits(logits, temperature, top_k, top_p)
            tok = jax.random.categorical(key, logits,
                                         axis=-1).astype(jnp.int32)
        else:
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if not return_logprobs:      # static flag — don't pay a full-vocab
            return tok, jnp.zeros(tok.shape, jnp.float32)  # softmax in eager
        lp = jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                                 tok[:, None], axis=-1)[:, 0]
        return tok, lp

    keys = (jax.random.split(key, max_new_tokens) if temperature > 0
            else jnp.zeros((max_new_tokens,)))
    # first token comes straight from the prefill logits; the scan then does
    # forward-then-pick, so no decode forward is ever computed and discarded
    tok0, lp0 = pick(logits, keys[0])
    done0 = (tok0 == eos_id) if eos_id is not None else None

    def step(carry, key_t):
        tok, done, cache = carry
        new_logits, cache = step_fn(params, tok[:, None], cache)
        nxt, lp = pick(new_logits[:, 0], key_t)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
            lp = jnp.where(done, 0.0, lp)    # forced eos: not a model draw
            done = done | (nxt == eos_id)
        return (nxt, done, cache), (nxt, lp)

    (_, _, _), (rest, rest_lp) = lax.scan(step, (tok0, done0, cache),
                                          keys[1:])
    toks = jnp.concatenate([tok0[:, None], rest.transpose(1, 0)], axis=1)
    if not return_logprobs:
        return toks
    logprobs = jnp.concatenate([lp0[:, None], rest_lp.transpose(1, 0)],
                               axis=1)
    return toks, logprobs
