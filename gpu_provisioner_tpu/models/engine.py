"""Continuous-batching serving engine over the per-row KV-cache machinery.

Production serving traffic is a STREAM: requests arrive at arbitrary times
with varying prompt and generation lengths. Static-batch ``generate()``
couples every row to the batch's slowest member — a 16-token completion
waits for a 512-token neighbour, and no new request can start until the
whole batch drains. The engine decouples them with SLOTS (the continuous
batching of modern serving stacks, built TPU-first):

- one pre-allocated cache of ``slots`` rows at a fixed ``max_len`` budget
  (static shapes — the decode step compiles exactly once);
- every decode step advances ALL active slots together through one
  ``cached_forward`` call with a per-row length vector — the per-row-start
  decode kernel fetches each row's own live prefix, so a fresh request
  next to a long-running one costs O(its own length), not O(max_len);
- a finished slot (eos or token budget) frees immediately and the next
  queued request is admitted into it: prompts left-pad to a small set of
  BUCKET lengths (one prefill program per bucket, compiled once each) and
  prefill into a single-row cache that is then inserted into the slot —
  in-cache pads stay masked forever via the engine's per-slot pad vector,
  and RoPE counts from each row's first real token, so a slotted request
  generates exactly what it would alone (the repo's padded-row invariant);
- inactive slots ride through the shared step with their write offset
  parked in-bounds and their length restored afterwards (the same
  finished-row discipline as batched speculative decoding) — they cost
  FLOPs (static shapes) but never corrupt state.

Greedy engine output per request is EXACTLY ``generate()``'s stream for
that request (tested); sampled mode draws per-step from the same filtered
distribution. Both model families serve (dense and MoE dispatch once at
construction). MoE bucketing semantic: expert capacity for the prefill is
computed from the BUCKET length (pads claim no capacity but widen the
denominator-S capacity formula) — the same documented routing-semantics
class as chunked prefill's per-chunk capacity; the engine stream equals
generate() on the identically-padded prompt, and decode steps are
dropless either way. The host loop owns admission only — one device→host
sync per step (the emitted tokens), which admission decisions need
anyway.

Reference parity note: workload-side scope beyond the reference
(SURVEY.md §2c) — the serving stack the provisioned slices exist to run;
sits on models/decode.py:cached_forward and the per-row-start kernel
(ops/flash_attention.py:flash_attention_decode).
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .decode import (KVCache, filter_logits, init_kv_cache,
                     validate_sampling_args)
from .llama import LlamaConfig, resolve_attn as _resolve_attn

DEFAULT_BUCKETS = (64, 128, 256, 512, 1024)


@dataclass
class Request:
    req_id: int
    prompt: list[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    prefix: Optional[tuple[int, ...]] = None


@dataclass
class _Slot:
    req: Request
    emitted: list[int] = field(default_factory=list)
    lps: list[float] = field(default_factory=list)


class ServeEngine:
    """Slot-based continuous batching for one model.

    ``slots``: concurrent sequences (the static decode batch width).
    ``max_len``: per-slot cache budget; every request must satisfy
    bucket(prompt) + max_new_tokens (+ verify slack when speculating)
    <= max_len.
    ``prefill_buckets``: ascending prompt-pad lengths — one compiled
    prefill program per DISTINCT bucket actually used.
    Sampling (``temperature``/``top_k``/``top_p``/``key``) follows
    generate()'s argument contract exactly.
    ``draft_params``/``draft_cfg``/``spec_k``: SPECULATIVE serving — each
    engine step runs one spec_round (models/speculative.py) across all
    active slots: the draft proposes spec_k tokens per slot, one wide
    verify call scores them, and each slot emits its accepted prefix + 1
    (so a step emits 1..spec_k+1 tokens per slot). Greedy speculative
    slots emit exactly the plain engine's stream (MoE targets verify
    drop-free); sampled slots draw from the target's filtered
    distribution via rejection sampling. The draft prefills and slots
    alongside the target (its own cache pool, same buckets/pads).

    PREFIX CACHING (``submit(..., prefix=...)``): a shared prompt prefix
    (system prompt / few-shot header) is prefilled ONCE — left-padded to
    a bucket like any prompt, so compiles stay bounded by the bucket set
    — and its cache row LRU-reused by every request that names it.
    Admission then prefills only the per-request suffix, right-padded to
    a bucket with the extra writes ROLLED BACK via the cache-length
    invariant (entries ≥ length are dead); the slot inherits the prefix
    row's left-pad count, masked by every later step as usual. Dense
    family only: right-pad garbage rows would compete for MoE routing
    capacity, so MoE prefixes raise. Cost: one full cache row
    ([L, 1, Hkv, max_len, Dh]) of HBM per cached prefix
    (``prefix_cache_size`` bounds it).

    ``return_logprobs``: also record each emitted token's log-probability
    under the sampling distribution (generate()'s convention — greedy:
    the unfiltered distribution; sampled: the filtered one actually
    drawn from; speculative slots score under the target's verify
    distribution, speculative_generate's convention). Logprobs align 1:1
    with the emitted streams (the engine truncates AT eos, so there are
    no forced-eos fill positions) and land in ``finished_logprobs``."""

    def __init__(self, params, cfg: LlamaConfig, *, slots: int = 8,
                 max_len: int = 2048,
                 prefill_buckets: tuple[int, ...] = DEFAULT_BUCKETS,
                 temperature: float = 0.0, top_k: int = None,
                 top_p: int = None, key=None,
                 draft_params=None, draft_cfg: LlamaConfig = None,
                 spec_k: int = 4, prefix_cache_size: int = 8,
                 return_logprobs: bool = False):
        _resolve_attn(cfg.attn_impl, cfg.sliding_window,
                      cfg.attn_sinks)        # loud validation, as everywhere
        validate_sampling_args(temperature, top_k, top_p, key)
        if slots < 1:
            raise ValueError(f"need at least one slot, got {slots}")
        if (draft_params is None) != (draft_cfg is None):
            raise ValueError("draft_params and draft_cfg come together")
        if draft_cfg is not None and draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError("draft and target must share a vocabulary: "
                             f"{draft_cfg.vocab_size} != {cfg.vocab_size}")
        if draft_cfg is not None and spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len
        self.buckets = tuple(sorted(set(prefill_buckets)))
        self.temperature, self.top_k, self.top_p = temperature, top_k, top_p
        self._key = key
        self.draft_params, self.draft_cfg = draft_params, draft_cfg
        self.spec_k = spec_k
        # speculative slots need verify slack past the budget: a round may
        # write spec_k+1 entries at the row's current length
        self._slack = (spec_k + 1) if draft_cfg is not None else 0

        from .decode import family_fns

        def _step(params, tok, cache, pads, active, key):
            # inactive slots: park the write offset in-bounds (their write
            # is discarded) and restore the length afterwards — the
            # finished-row discipline from speculative_generate.
            # family_fns is THE family dispatch point (dense vs MoE) —
            # the engine serves the same code path as generate()
            parked = jnp.minimum(cache.length, max_len - 1)
            safe = jnp.where(active, cache.length, parked)
            cache = cache._replace(length=safe)
            logits, cache = family_fns(cfg, pad_lens=pads)[1](params, tok,
                                                              cache)
            cache = cache._replace(
                length=jnp.where(active, cache.length, safe))
            lg = logits[:, 0]
            if temperature > 0:
                dist = filter_logits(lg, temperature, top_k, top_p)
                nxt = jax.random.categorical(key, dist,
                                             axis=-1).astype(jnp.int32)
            else:
                dist = lg     # greedy reports the unfiltered distribution
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            if return_logprobs:
                lp = jnp.take_along_axis(
                    jax.nn.log_softmax(dist, axis=-1), nxt[:, None],
                    axis=-1)[:, 0]
            else:               # static flag: don't pay the full-vocab
                lp = jnp.zeros(nxt.shape)          # softmax when off
            return nxt, lp, cache

        self._step = jax.jit(_step, donate_argnums=(2,))

        def _prefill_for(pcfg):
            # B=1 general cached forward at offset 0 (left-padded bucket)
            # — ONE factory serves target and draft so their prefill
            # paths cannot diverge
            def _prefill(params, prompt, cache1, pads1):
                logits, cache1 = family_fns(pcfg, pad_lens=pads1)[1](
                    params, prompt, cache1)
                return logits[:, -1], cache1
            return jax.jit(_prefill)         # compiles per bucket length

        self._prefill = _prefill_for(cfg)

        def _suffix_for(pcfg):
            # prefix caching's suffix continuation: rides at the prefix
            # row's offset, RIGHT-padded to its bucket; the padded tail's
            # writes roll back via the length (entries ≥ length are dead
            # by the cache invariant) and the real last token's logits
            # come from position r−1. cache1 is the LRU row — never
            # donated, so the cached prefix row survives every reuse.
            def _suffix(params, suffix, cache1, pads1, r):
                # pads1: the PREFIX row's left-pad count (prefixes bucket
                # through the same left-pad path as prompts, bounding
                # compiles to the bucket set) — suffix positions and key
                # masking must keep honoring it
                logits, cache1 = family_fns(pcfg, pad_lens=pads1)[1](
                    params, suffix, cache1)
                lg = jnp.take(logits, r - 1, axis=1)         # [1, V]
                cache1 = cache1._replace(
                    length=cache1.length - (suffix.shape[1] - r))
                return lg, cache1
            return jax.jit(_suffix)

        self._suffix_prefill = _suffix_for(cfg)

        def _insert(big: KVCache, small: KVCache, slot, length):
            def put(b, s):
                return jax.lax.dynamic_update_slice(
                    b, s, (0, slot, 0, 0, 0)) if b is not None else None
            return KVCache(k=put(big.k, small.k), v=put(big.v, small.v),
                           length=big.length.at[slot].set(length),
                           k_scale=put(big.k_scale, small.k_scale),
                           v_scale=put(big.v_scale, small.v_scale))

        self._insert = jax.jit(_insert, donate_argnums=(0,))

        if draft_cfg is not None:
            from .speculative import spec_round

            def _spec_step(params, dparams, last, done, cache_t, cache_d,
                           pads, key):
                # family_fns is THE dispatch point (dense vs MoE, and the
                # MoE dropless-verify rule) — the engine must serve the
                # same code path as speculative_generate
                step_t = family_fns(cfg, pad_lens=pads,
                                    dropless_step=True)[1]
                step_d = family_fns(draft_cfg, pad_lens=pads)[1]
                (emit_vec, _keep, emit_n, new_last, cache_t, cache_d,
                 verify_logits) = spec_round(
                    step_t, step_d, params, dparams, last, done, cache_t,
                    cache_d, key, spec_k=spec_k,
                    draft_vocab=draft_cfg.vocab_size, max_len=max_len,
                    sampled=temperature > 0, temperature=temperature,
                    top_k=top_k, top_p=top_p)
                # pack the two host-bound outputs into ONE transfer and
                # drop the [slots, k+1, V] verify logits on device — jit
                # outputs cannot be DCE'd, so returning them would write
                # MBs of never-read HBM per step. Logprobs, when on, ride
                # as a tiny [slots, k+1] f32 (not the V-wide logits).
                packed = jnp.concatenate([emit_vec, emit_n[:, None]],
                                         axis=1)          # [slots, k+2]
                if return_logprobs:
                    wlp = jnp.take_along_axis(
                        jax.nn.log_softmax(verify_logits, axis=-1),
                        emit_vec[..., None], axis=-1)[..., 0]
                else:
                    wlp = jnp.zeros(emit_vec.shape)
                return packed, wlp, new_last, cache_t, cache_d

            self._spec_step = jax.jit(_spec_step, donate_argnums=(4, 5))

            self._dprefill = _prefill_for(draft_cfg)
            self._suffix_prefill_d = _suffix_for(draft_cfg)
            self.draft_cache = init_kv_cache(draft_cfg, slots, max_len)
            self.draft_cache = self.draft_cache._replace(
                length=jnp.zeros((slots,), jnp.int32))

        self.cache = init_kv_cache(cfg, slots, max_len)
        self.cache = self.cache._replace(
            length=jnp.zeros((slots,), jnp.int32))
        self._pads = jnp.zeros((slots,), jnp.int32)
        self._last = jnp.zeros((slots,), jnp.int32)
        self._slot: list[Optional[_Slot]] = [None] * slots
        self._queue: deque[Request] = deque()
        self._next_id = 0
        self.finished: dict[int, list[int]] = {}
        self.prefix_cache_size = prefix_cache_size
        self._prefix_lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.prefix_misses = 0               # observability + tests
        self.prefix_hits = 0
        self.return_logprobs = return_logprobs
        self.finished_logprobs: dict[int, list[float]] = {}

        # Fleet observability: the weak-value registry bridges stats() into
        # the tpu_provisioner_engine_* gauges (controllers/metrics.py) —
        # the input signal the demand autoscaler watches. Lazy import so a
        # stubbed/absent observability tree never blocks engine bring-up.
        try:
            from ..observability.fleet import register_engine
            register_engine(self)
        except Exception:  # noqa: BLE001 — registration is best-effort
            pass

    # --- request lifecycle --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               eos_id: Optional[int] = None, prefix=None) -> int:
        """Queue a request; returns its id. Raises if it cannot ever fit.
        ``prefix``: shared leading tokens (system prompt) prefilled once
        and LRU-reused across requests — ``prompt`` continues AFTER it."""
        prompt = list(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens} (admission always emits "
                             "the prefill token)")
        p = 0
        if prefix is not None:
            prefix = tuple(int(t) for t in prefix)
            if not prefix:
                raise ValueError("empty prefix — omit it instead")
            from .moe import MoEConfig
            if isinstance(self.cfg, MoEConfig) or \
                    isinstance(self.draft_cfg, MoEConfig):
                raise ValueError(
                    "prefix caching serves the dense family only — the "
                    "right-padded suffix rows would compete for MoE "
                    "routing capacity")
            p = self._bucket(len(prefix))   # prefixes bucket like prompts
        b = self._bucket(len(prompt))
        if p + b + max_new_tokens + self._slack > self.max_len:
            # speculative engines add verify slack: a round may write
            # spec_k+1 entries at the row's current length
            raise ValueError(
                f"request needs "
                + (f"prefix {p} + " if p else "")
                + f"bucket {b} + {max_new_tokens} new tokens "
                + (f"+ {self._slack} verify slack " if self._slack else "")
                + f"> max_len {self.max_len}")
        rid = self._next_id
        self._next_id += 1
        self._queue.append(Request(rid, prompt, max_new_tokens, eos_id,
                                   prefix))
        return rid

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    def _admit(self, emitted: dict[int, list[int]]) -> None:
        """Fill free slots from the queue; admission itself emits each
        request's FIRST token (from the prefill logits) into ``emitted``."""
        for s in range(self.slots):
            if not self._queue:
                return
            if self._slot[s] is not None:
                continue
            req = self._queue.popleft()
            if req.prefix is not None:
                lg, cache1, dcache1, pad, length = self._prefix_admit(req)
            else:
                b = self._bucket(len(req.prompt))
                pad = b - len(req.prompt)
                length = b
                prompt = jnp.asarray([[0] * pad + req.prompt], jnp.int32)
                cache1 = init_kv_cache(self.cfg, 1, self.max_len)
                lg, cache1 = self._prefill(self.params, prompt, cache1,
                                           jnp.asarray([pad], jnp.int32))
                dcache1 = None
                if self.draft_cfg is not None:
                    dcache1 = init_kv_cache(self.draft_cfg, 1,
                                            self.max_len)
                    _, dcache1 = self._dprefill(
                        self.draft_params, prompt, dcache1,
                        jnp.asarray([pad], jnp.int32))
            if self.temperature > 0:
                self._key, k0 = jax.random.split(self._key)
                dist = filter_logits(lg, self.temperature, self.top_k,
                                     self.top_p)
                tok0 = jax.random.categorical(k0, dist, axis=-1)
            else:
                dist = lg
                tok0 = jnp.argmax(lg, axis=-1)
            tok0 = int(tok0[0])
            lp0 = 0.0
            if self.return_logprobs:
                lp0 = float(jax.nn.log_softmax(dist, axis=-1)[0, tok0])
            self.cache = self._insert(self.cache, cache1,
                                      jnp.asarray(s, jnp.int32),
                                      jnp.asarray(length, jnp.int32))
            if dcache1 is not None:
                self.draft_cache = self._insert(
                    self.draft_cache, dcache1, jnp.asarray(s, jnp.int32),
                    jnp.asarray(length, jnp.int32))
            self._pads = self._pads.at[s].set(pad)
            self._last = self._last.at[s].set(tok0)
            self._slot[s] = _Slot(req, [tok0], [lp0])
            emitted.setdefault(req.req_id, []).append(tok0)
            self._maybe_finish(s)

    def _prefix_row(self, prefix: tuple[int, ...]):
        """(target row cache, draft row cache | None, pad count) prefilled
        over the LEFT-pad-bucketed prefix, LRU-cached — the prefill cost
        is paid once per distinct prefix, every later request reuses the
        row, and bucketing keeps the compile count bounded by the bucket
        set (an exact-length prefill would compile per distinct length)."""
        hit = self._prefix_lru.get(prefix)
        if hit is not None:
            self.prefix_hits += 1
            self._prefix_lru.move_to_end(prefix)
            return hit
        self.prefix_misses += 1
        pb = self._bucket(len(prefix))
        pad = pb - len(prefix)
        toks = jnp.asarray([[0] * pad + list(prefix)], jnp.int32)
        pads1 = jnp.asarray([pad], jnp.int32)
        c = init_kv_cache(self.cfg, 1, self.max_len)
        _, c = self._prefill(self.params, toks, c, pads1)
        d = None
        if self.draft_cfg is not None:
            d = init_kv_cache(self.draft_cfg, 1, self.max_len)
            _, d = self._dprefill(self.draft_params, toks, d, pads1)
        self._prefix_lru[prefix] = (c, d, pad)
        while len(self._prefix_lru) > self.prefix_cache_size:
            self._prefix_lru.popitem(last=False)
        return c, d, pad

    def _prefix_admit(self, req: Request):
        """Admission via a cached prefix row: only the per-request suffix
        is prefilled, RIGHT-padded to a bucket — the padded tail's writes
        roll back via the length. The slot inherits the prefix row's
        LEFT-pad count, which every later step keeps masking."""
        b = self._bucket(len(req.prompt))
        suffix = jnp.asarray(
            [req.prompt + [0] * (b - len(req.prompt))], jnp.int32)
        r = jnp.asarray(len(req.prompt), jnp.int32)
        pc, pd, pad = self._prefix_row(req.prefix)
        pads1 = jnp.asarray([pad], jnp.int32)
        lg, cache1 = self._suffix_prefill(self.params, suffix, pc, pads1,
                                          r)
        dcache1 = None
        if self.draft_cfg is not None:
            _, dcache1 = self._suffix_prefill_d(self.draft_params, suffix,
                                                pd, pads1, r)
        length = self._bucket(len(req.prefix)) + len(req.prompt)
        return lg, cache1, dcache1, pad, length

    def _maybe_finish(self, s: int) -> None:
        slot = self._slot[s]
        req = slot.req
        done = len(slot.emitted) >= req.max_new_tokens or (
            req.eos_id is not None and slot.emitted[-1] == req.eos_id)
        if done:
            self.finished[req.req_id] = slot.emitted
            if self.return_logprobs:
                self.finished_logprobs[req.req_id] = slot.lps
            self._slot[s] = None
            self.cache = self.cache._replace(
                length=self.cache.length.at[s].set(0))
            if self.draft_cfg is not None:
                self.draft_cache = self.draft_cache._replace(
                    length=self.draft_cache.length.at[s].set(0))

    # --- the serving loop ---------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(s is not None for s in self._slot)

    def stats(self) -> dict:
        """Serving observability counters (the engine analog of the
        control plane's Prometheus surface): slot occupancy, queue depth,
        totals, prefix-cache effectiveness."""
        emitted = sum(len(v) for v in self.finished.values()) + sum(
            len(s.emitted) for s in self._slot if s is not None)
        return {
            "slots": self.slots,
            "slots_active": sum(s is not None for s in self._slot),
            "queue_depth": len(self._queue),
            "requests_submitted": self._next_id,
            "requests_finished": len(self.finished),
            "tokens_emitted": emitted,
            "prefix_cache_entries": len(self._prefix_lru),
            "prefix_cache_hits": self.prefix_hits,
            "prefix_cache_misses": self.prefix_misses,
        }

    def step(self) -> dict[int, list[int]]:
        """Admit what fits, then advance every active slot one token.
        Returns {req_id: [tokens]} for EVERY token emitted this step — a
        newly-admitted request contributes its first token (from the
        prefill logits) plus, if still active, this step's decode token;
        a request that finishes during admission thus still surfaces
        here."""
        out: dict[int, list[int]] = {}
        self._admit(out)
        active_slots = [i for i, s in enumerate(self._slot) if s is not None]
        if not active_slots:
            return out
        active = jnp.asarray([s is not None for s in self._slot])
        if self.temperature > 0:
            self._key, kt = jax.random.split(self._key)
        else:
            kt = jax.random.key(0)
        if self.draft_cfg is not None:
            return self._spec_advance(out, active_slots, active, kt)
        nxt, lp, self.cache = self._step(self.params, self._last[:, None],
                                         self.cache, self._pads, active,
                                         kt)
        self._last = nxt
        toks = np.asarray(nxt)               # the one host sync per step
        lps = np.asarray(lp) if self.return_logprobs else None
        for s in active_slots:
            t = int(toks[s])
            slot = self._slot[s]
            slot.emitted.append(t)
            if lps is not None:
                slot.lps.append(float(lps[s]))
            out.setdefault(slot.req.req_id, []).append(t)
            self._maybe_finish(s)
        return out

    def _spec_advance(self, out, active_slots, active, kt):
        """One speculative round for every active slot: 1..spec_k+1 tokens
        per slot per step. Quota/eos truncation happens host-side — a
        truncated slot always FINISHES, so its device state (which ran
        ahead by the truncated tokens) is discarded with the slot."""
        (packed, wlp, new_last, self.cache,
         self.draft_cache) = self._spec_step(
            self.params, self.draft_params, self._last, ~active,
            self.cache, self.draft_cache, self._pads, kt)
        self._last = new_last
        host = np.asarray(packed)            # the one host sync per step
        ev, en = host[:, :-1], host[:, -1]
        lps = np.asarray(wlp) if self.return_logprobs else None
        for s in active_slots:
            slot = self._slot[s]
            req = slot.req
            new = [int(t) for t in ev[s][:int(en[s])]]
            new = new[:req.max_new_tokens - len(slot.emitted)]
            if req.eos_id is not None and req.eos_id in new:
                new = new[:new.index(req.eos_id) + 1]
            slot.emitted.extend(new)
            if lps is not None:              # logprobs align 1:1 with the
                slot.lps.extend(             # truncated token window
                    float(x) for x in lps[s][:len(new)])
            if new:
                out.setdefault(req.req_id, []).extend(new)
            self._maybe_finish(s)
        return out

    def run(self, max_steps: int = 100_000) -> dict[int, list[int]]:
        """Drive until every submitted request finishes; returns
        {req_id: emitted tokens}."""
        steps = 0
        while self.pending:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} "
                                   f"steps ({self.pending} pending)")
        return self.finished


__all__ = ["ServeEngine", "Request", "DEFAULT_BUCKETS"]
