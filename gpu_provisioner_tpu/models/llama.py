"""Llama-family decoder in pure JAX, designed for the MXU.

TPU-first choices (not a torch translation):
- layers stored **stacked** ([L, ...] leading dim) and executed with
  ``lax.scan`` — XLA compiles ONE block and reuses it, keeping compile time
  flat in depth and letting the scheduler pipeline HBM prefetch;
- bf16 matmuls (MXU-native), fp32 for norms/softmax/logits accumulation;
- static shapes throughout; causal masking via positions, no dynamic slicing;
- attention is injected (``attn_fn``) so the same forward runs dense
  single-chip (ops/attention), ring sequence-parallel (parallel/ring.py
  under shard_map), or a pallas flash kernel — the sharding lives outside
  the math;
- tensor parallelism is expressed only as PartitionSpecs (``param_specs``);
  XLA/GSPMD inserts the collectives (scaling-book recipe), nothing manual.

Model shapes follow the public Llama family (7B: dim 4096 / 32 layers /
32 heads / GQA optional); presets sized for bring-up are in PRESETS.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.ring import dense_attention
from ..parallel.topology import AXIS_MODEL


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8            # GQA; == n_heads → MHA
    hidden_dim: int = 11008        # SwiGLU inner width
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"        # activation / matmul dtype
    param_dtype: str = "float32"   # master weights
    remat: bool = False            # jax.checkpoint each block (HBM ↔ FLOPs)
    seq_schedule: str = "ring"     # "ring" | "zigzag" (balanced causal ring)
    attn_impl: str = "dense"       # "dense" | "flash" (pallas kernel; falls
                                   # back to dense off-TPU / non-tiling shapes)
    kv_cache_dtype: str = "auto"   # "auto" (= act dtype) | "int8" (quantized
                                   # serving cache: half the HBM, on-the-fly
                                   # dequant — models/decode.py)
    sliding_window: Optional[int] = None
                                   # Mistral-style sliding-window attention:
                                   # query p attends (p-window, p]. Serving
                                   # takes the windowed Pallas kernels
                                   # (O(window) cache DMA); the full forward
                                   # masks densely. None = full causal.
    attn_sinks: int = 0            # StreamingLLM attention sinks: with a
                                   # sliding window, the first attn_sinks
                                   # REAL tokens stay attendable forever
                                   # (ragged rows: the first real tokens
                                   # after the pads) — long generations
                                   # keep the softmax's sink mass instead
                                   # of falling off a quality cliff.
                                   # Requires sliding_window; serving
                                   # kernels fetch the sink blocks + the
                                   # window, still O(window) DMA.

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def act_dtype(self):
        return jnp.dtype(self.dtype)


PRESETS = {
    "llama-7b": LlamaConfig(),
    "llama-1b": LlamaConfig(dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
                            hidden_dim=5504),
    # Mistral-7B-v0.1-shaped: GQA 32/8 + 4k sliding window (the release
    # that USES the window; theta stays 1e4 to match its checkpoints)
    "mistral-7b-ish": LlamaConfig(vocab_size=32000, dim=4096, n_layers=32,
                                  n_heads=32, n_kv_heads=8, hidden_dim=14336,
                                  max_seq_len=32768, sliding_window=4096),
    "tiny": LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=128),
}


def resolve_attn(impl: str, window: Optional[int] = None,
                 sinks: int = 0) -> Callable:
    """cfg.attn_impl → attention callable (the one dispatch point — forward,
    the pipelined stage body, and serving prefill all resolve through here).
    Unknown values raise instead of silently running dense.

    ``window`` (cfg.sliding_window): impl="flash" takes the windowed
    Pallas kernels — forward AND recompute backward prune to the window
    band (loop bounds, live gates, and kv index-map clamps), so
    Mistral-style long-context training is O(S·window) compute and
    O(S·D) memory where the dense mask cannot even compile at 32k.
    impl="dense" masks densely. ``sinks`` (cfg.attn_sinks) stays on the
    dense path for self-attention — sinks matter in long GENERATION,
    which runs the serving kernels; a windowed+sinks full forward is
    rare enough that correct-but-dense is the right cost."""
    if impl not in ("flash", "dense"):
        raise ValueError(
            f"unknown attn_impl {impl!r}; expected 'dense'|'flash'")
    if sinks and window is None:
        raise ValueError(
            f"attn_sinks={sinks} requires sliding_window — without a "
            "window every key is already attendable")
    if sinks < 0:
        raise ValueError(f"attn_sinks must be >= 0, got {sinks}")
    if window is not None:
        if window <= 0:
            # window=0 would all-NEG_INF every score row and the impls
            # would silently disagree on the garbage (dense: uniform
            # V-average; kernels: zeros) — same loud-validation rule as
            # the impl check above
            raise ValueError(
                f"sliding_window must be positive, got {window} "
                "(use None to disable)")
        if impl == "flash" and not sinks:
            from ..ops.flash_attention import flash_attention
            return partial(flash_attention, window=window)
        return partial(dense_attention, window=window, sinks=sinks)
    if impl == "flash":
        from ..ops.flash_attention import flash_attention
        return flash_attention
    return dense_attention


def init_params(key, cfg: LlamaConfig) -> dict:
    """Stacked-layer parameter pytree. Truncated-normal-ish scaled init."""
    pd = jnp.dtype(cfg.param_dtype)
    L, D, F = cfg.n_layers, cfg.dim, cfg.hidden_dim
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ks = jax.random.split(key, 9)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape, pd) * (fan_in ** -0.5)).astype(pd)

    return {
        "embed": norm(ks[0], (cfg.vocab_size, D), D),
        "blocks": {
            "wq": norm(ks[1], (L, D, Hq * Dh), D),
            "wk": norm(ks[2], (L, D, Hkv * Dh), D),
            "wv": norm(ks[3], (L, D, Hkv * Dh), D),
            "wo": norm(ks[4], (L, Hq * Dh, D), Hq * Dh),
            "w_gate": norm(ks[5], (L, D, F), D),
            "w_up": norm(ks[6], (L, D, F), D),
            "w_down": norm(ks[7], (L, F, D), F),
            "ln_attn": jnp.ones((L, D), pd),
            "ln_mlp": jnp.ones((L, D), pd),
        },
        "ln_final": jnp.ones((D,), pd),
        "lm_head": norm(ks[8], (D, cfg.vocab_size), D),
    }


def param_specs(cfg: LlamaConfig) -> dict:
    """PartitionSpecs for tensor parallelism over the ``model`` mesh axis.

    Megatron layout expressed declaratively: QKV/gate/up column-parallel,
    wo/down row-parallel, embedding/lm_head vocab-parallel. The stacked
    layer dim L is never sharded.
    """
    M = AXIS_MODEL
    return {
        "embed": P(M, None),
        "blocks": {
            "wq": P(None, None, M), "wk": P(None, None, M),
            "wv": P(None, None, M), "wo": P(None, M, None),
            "w_gate": P(None, None, M), "w_up": P(None, None, M),
            "w_down": P(None, M, None),
            "ln_attn": P(None, None), "ln_mlp": P(None, None),
        },
        "ln_final": P(None),
        "lm_head": P(None, M),
    }


def _rmsnorm(x, scale, eps):
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(x.dtype) * scale.astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding. x: [B, S, H, D], positions: [B, S] or [S]."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D, 2, dtype=jnp.float32) / D)
    if positions.ndim == 1:
        positions = positions[None, :]
    ang = positions[..., None].astype(jnp.float32) * freqs        # [B, S, D/2]
    cos, sin = jnp.cos(ang)[:, :, None, :], jnp.sin(ang)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _project_qkv(h, lp, cfg: LlamaConfig, positions):
    """Normed input → roped (q, k, v). Shared by the training block and the
    KV-cache decode path (models/decode.py) so the projection/rope math has
    exactly one home."""
    B, S, _ = h.shape
    Hq, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    ad = cfg.act_dtype
    q = (h @ lp["wq"].astype(ad)).reshape(B, S, Hq, Dh)
    k = (h @ lp["wk"].astype(ad)).reshape(B, S, Hkv, Dh)
    v = (h @ lp["wv"].astype(ad)).reshape(B, S, Hkv, Dh)
    return (_rope(q, positions, cfg.rope_theta),
            _rope(k, positions, cfg.rope_theta), v)


def _mlp_half(x, lp, cfg: LlamaConfig):
    """Norm → SwiGLU → residual (shared with models/decode.py)."""
    ad = cfg.act_dtype
    h = _rmsnorm(x, lp["ln_mlp"], cfg.norm_eps)
    gated = jax.nn.silu(h @ lp["w_gate"].astype(ad)) * (h @ lp["w_up"].astype(ad))
    return x + gated @ lp["w_down"].astype(ad)


def _block_attention_half(x, lp, cfg: LlamaConfig, positions, attn_fn):
    """Norm → QKV → rope → attention → residual (shared with models/moe.py,
    which swaps only the FFN half)."""
    B, S, D = x.shape
    ad = cfg.act_dtype
    h = _rmsnorm(x, lp["ln_attn"], cfg.norm_eps)
    q, k, v = _project_qkv(h, lp, cfg, positions)
    o = attn_fn(q, k, v).reshape(B, S, cfg.n_heads * cfg.head_dim)
    return x + o @ lp["wo"].astype(ad)


def _block(x, lp, cfg: LlamaConfig, positions, attn_fn):
    """One decoder block. x: [B, S, D], lp: this layer's param slice."""
    x = _block_attention_half(x, lp, cfg, positions, attn_fn)
    return _mlp_half(x, lp, cfg)


def forward(params: dict, tokens, cfg: LlamaConfig,
            attn_fn: Optional[Callable] = None,
            positions=None):
    """Logits for next-token prediction. tokens: [B, S] int32 → [B, S, V].

    ``attn_fn(q, k, v) -> o`` defaults to dense causal attention; the
    sequence-parallel train step passes the shard_map-wrapped ring kernel.
    ``positions`` defaults to arange(S) — pass global positions when the
    sequence axis is sharded.
    """
    if attn_fn is None:
        attn_fn = resolve_attn(cfg.attn_impl, cfg.sliding_window,
                               cfg.attn_sinks)
    ad = cfg.act_dtype
    B, S = tokens.shape
    if positions is None:
        positions = jnp.arange(S, dtype=jnp.int32)

    x = params["embed"].astype(ad)[tokens]                 # [B, S, D]

    blk = partial(_block, cfg=cfg, positions=positions, attn_fn=attn_fn)
    if cfg.remat:
        blk = jax.checkpoint(blk)

    def scan_body(x, layer_params):
        return blk(x, layer_params), None

    x, _ = lax.scan(scan_body, x, params["blocks"])

    x = _rmsnorm(x, params["ln_final"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)
    return logits
