"""Mixtral-style sparse Mixture-of-Experts with expert parallelism.

TPU-first formulation (GShard/Switch dispatch — the canonical XLA MoE):
no gather/scatter or dynamic shapes. Routing builds a dense one-hot
dispatch tensor [B, S, E, C] (capacity C per expert) and the whole layer is
three einsums — dispatch, expert FFN, combine — so GSPMD inserts the
all-to-alls when tokens are sharded over (slice, data) and expert weights
over the ``expert`` mesh axis (PartitionSpec("expert", None, "model"):
ep × tp compose). Overflow tokens beyond capacity are dropped (standard
Switch behavior); the residual stream carries them unchanged.

Reference parity note: the reference provisions capacity for KAITO model
workspaces; Mixtral-class MoE is in that family. Nothing in the reference
to cite — this is workload-side scope the TPU build adds (SURVEY.md §2c).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.topology import AXIS_EXPERT, AXIS_MODEL
from .llama import LlamaConfig, _rmsnorm


@dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2     # top-k routing (Mixtral: 2)
    capacity_factor: float = 1.25  # C = factor · k · S / E
    router_z_loss: float = 1e-3    # stabilizes router logits (ST-MoE)


PRESETS_MOE = {
    "tiny-moe": MoEConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, hidden_dim=128, max_seq_len=128,
                          n_experts=4, experts_per_token=2),
    "mixtral-ish": MoEConfig(dim=2048, n_layers=16, n_heads=16, n_kv_heads=8,
                             hidden_dim=5504, n_experts=8),
}


def init_moe_params(key, cfg: MoEConfig) -> dict:
    """Per-layer MoE FFN params, stacked [L, ...] like the dense blocks."""
    pd = jnp.dtype(cfg.param_dtype)
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.hidden_dim, cfg.n_experts
    ks = jax.random.split(key, 4)

    def norm(k, shape, fan_in):
        return (jax.random.normal(k, shape, pd) * (fan_in ** -0.5)).astype(pd)

    return {
        "router": norm(ks[0], (L, D, E), D),
        "w_gate": norm(ks[1], (L, E, D, F), D),
        "w_up": norm(ks[2], (L, E, D, F), D),
        "w_down": norm(ks[3], (L, E, F, D), F),
    }


def moe_param_specs() -> dict:
    """Experts over ``expert``, inner width over ``model`` (ep × tp)."""
    E, M = AXIS_EXPERT, AXIS_MODEL
    return {
        "router": P(None, None, None),
        "w_gate": P(None, E, None, M),
        "w_up": P(None, E, None, M),
        "w_down": P(None, E, M, None),
    }


def capacity(cfg: MoEConfig, seq_len: int) -> int:
    c = int(cfg.capacity_factor * cfg.experts_per_token * seq_len
            / cfg.n_experts)
    return max(1, c)


def route(logits, k: int, cap: int, token_mask=None):
    """Top-k routing → (dispatch [B,S,E,C] one-hot, combine [B,S,E,C]).

    Position-in-expert via cumulative sum over the flattened (s, k) choice
    order — deterministic, shape-static, XLA-friendly. Tokens past an
    expert's capacity are dropped.

    ``token_mask`` [B, S] bool: False tokens route NOWHERE — they claim no
    capacity slot and receive zero FFN output. Serving uses this for
    left-pad positions, which sit FIRST in the cumsum claim order and
    would otherwise evict real tokens from full experts (the dense MLP has
    no such cross-token coupling; capacity does).
    """
    B, S, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [B,S,E]
    gate_vals, gate_idx = lax.top_k(probs, k)                     # [B,S,k]
    # renormalize the k gates so combine weights sum to 1 per token
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)       # [B,S,k,E]
    if token_mask is not None:
        onehot = onehot * token_mask[:, :, None, None].astype(onehot.dtype)
    # choice order: (s, k) flattened → earlier tokens/choices claim slots first
    flat = onehot.reshape(B, S * k, E)
    pos = jnp.cumsum(flat, axis=1) - flat                          # [B,S*k,E]
    pos = pos.reshape(B, S, k, E)
    within = (pos < cap) & (onehot > 0)                            # [B,S,k,E]
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                            dtype=jnp.float32) * within[..., None]
    # [B,S,k,E,C] → fold the k choices
    dispatch = jnp.sum(pos_oh, axis=2)                             # [B,S,E,C]
    combine = jnp.sum(pos_oh * gate_vals[..., None, None]
                      * onehot[..., None], axis=2)                 # [B,S,E,C]
    return dispatch, combine


def moe_ffn(x, lp: dict, cfg: MoEConfig, token_mask=None,
            cap_override: int = None):
    """One MoE FFN layer. x: [B, S, D] → [B, S, D] (+ aux losses dict).
    ``token_mask`` [B, S]: see route() — masked tokens get zero output and
    claim no expert capacity (serving's left-pad positions).

    ``cap_override``: expert capacity to use instead of capacity(cfg, S).
    ``cap_override=S`` makes the layer DROP-FREE (an expert can receive at
    most S tokens — top-k picks k distinct experts per token), under which
    each token's output is exactly its per-token routing Σ gateᵢ·expertᵢ(x)
    — position-in-slot cancels in the combine sum. Speculative decoding's
    verify block uses this for exact MoE-target equality with plain
    per-token decode (models/speculative.py)."""
    B, S, D = x.shape
    ad = cfg.act_dtype
    cap = cap_override if cap_override is not None else capacity(cfg, S)
    logits = x.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
    dispatch, combine = route(logits, cfg.experts_per_token, cap,
                              token_mask=token_mask)

    # dispatch → [E, B, C, D]: GSPMD turns this into the all-to-all when
    # x is batch-sharded and the expert dim is mesh-sharded
    expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch.astype(ad), x)
    h = jax.nn.silu(jnp.einsum("ebcd,edf->ebcf", expert_in,
                               lp["w_gate"].astype(ad)))
    h = h * jnp.einsum("ebcd,edf->ebcf", expert_in, lp["w_up"].astype(ad))
    expert_out = jnp.einsum("ebcf,efd->ebcd", h, lp["w_down"].astype(ad))
    out = jnp.einsum("bsec,ebcd->bsd", combine.astype(ad), expert_out)

    # load-balance aux loss (Switch §2.2) + router z-loss (ST-MoE)
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = jnp.mean(dispatch.sum(-1), axis=(0, 1))          # [E]
    frac_probs = jnp.mean(probs, axis=(0, 1))                      # [E]
    lb_loss = cfg.n_experts * jnp.sum(frac_tokens * frac_probs)
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return out, {"load_balance": lb_loss, "router_z": z_loss}


def moe_block(x, lp_dense: dict, lp_moe: dict, cfg: MoEConfig, positions,
              attn_fn):
    """Decoder block with the dense FFN swapped for the MoE FFN."""
    from .llama import _block_attention_half

    x = _block_attention_half(x, lp_dense, cfg, positions, attn_fn)
    h = _rmsnorm(x, lp_dense["ln_mlp"], cfg.norm_eps)
    ffn_out, aux = moe_ffn(h, lp_moe, cfg)
    return x + ffn_out, aux


# --- full model ------------------------------------------------------------

def init_moe_model(key, cfg: MoEConfig) -> dict:
    """Backbone (embed/attention/norms — no dense FFN) + MoE FFN params."""
    from .llama import init_params

    k1, k2 = jax.random.split(key)
    dense = init_params(k1, cfg)
    for w in ("w_gate", "w_up", "w_down"):   # replaced by experts
        del dense["blocks"][w]
    return {"backbone": dense, "moe": init_moe_params(k2, cfg)}


def moe_model_specs(cfg: MoEConfig) -> dict:
    from .llama import param_specs

    dense = param_specs(cfg)
    for w in ("w_gate", "w_up", "w_down"):
        del dense["blocks"][w]
    return {"backbone": dense, "moe": moe_param_specs()}


def moe_forward(params: dict, tokens, cfg: MoEConfig, attn_fn=None):
    """Logits + mean aux losses. tokens: [B, S] → ([B, S, V], aux dict)."""
    from .llama import _rope, resolve_attn  # noqa: F401  (rope in the block)

    if attn_fn is None:
        attn_fn = resolve_attn("dense", cfg.sliding_window, cfg.attn_sinks)
    ad = cfg.act_dtype
    B, S = tokens.shape
    positions = jnp.arange(S, dtype=jnp.int32)
    backbone = params["backbone"]
    x = backbone["embed"].astype(ad)[tokens]

    blk = partial(moe_block, cfg=cfg, positions=positions, attn_fn=attn_fn)
    if cfg.remat:
        blk = jax.checkpoint(blk)

    def scan_body(x, layer):
        lp_dense, lp_moe = layer
        x, aux = blk(x, lp_dense, lp_moe)
        return x, aux

    x, aux_stacked = lax.scan(scan_body, x,
                              (backbone["blocks"], params["moe"]))
    aux = jax.tree.map(jnp.mean, aux_stacked)

    x = _rmsnorm(x, backbone["ln_final"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ backbone["lm_head"].astype(jnp.float32)
    return logits, aux


def moe_loss_fn(params, inputs, targets, cfg: MoEConfig, attn_fn=None,
                lb_coeff: float = 1e-2):
    logits, aux = moe_forward(params, inputs, cfg, attn_fn=attn_fn)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce = jnp.mean(logz - gold)
    return (ce + lb_coeff * aux["load_balance"]
            + cfg.router_z_loss * aux["router_z"])


def make_moe_train_step(mesh, cfg: MoEConfig, optimizer=None):
    """jitted MoE train step over the (slice, data, seq, expert, model) mesh."""
    import optax

    from .train import default_optimizer, make_attn_fn

    if optimizer is None:
        optimizer = default_optimizer()
    attn_fn = make_attn_fn(mesh, impl=cfg.attn_impl,
                           seq_schedule=cfg.seq_schedule,
                           window=cfg.sliding_window,
                           sinks=cfg.attn_sinks)

    def step(params, opt_state, inputs, targets):
        loss, grads = jax.value_and_grad(moe_loss_fn)(
            params, inputs, targets, cfg, attn_fn)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def make_moe_train_state(key, cfg: MoEConfig, mesh, optimizer=None):
    from .train import default_optimizer, shard_params

    if optimizer is None:
        optimizer = default_optimizer()
    params = shard_params(init_moe_model(key, cfg), mesh,
                          specs=moe_model_specs(cfg))
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state, optimizer
