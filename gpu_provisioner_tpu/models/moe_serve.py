"""KV-cache serving for the MoE family — models/decode.py's twin over
models/moe.py.

The attention half is byte-identical to dense-model serving (same KVCache,
same head-major layout, same _cached_attention dispatch incl. the flash
prefill/decode kernels and the int8 cache); only the FFN half differs:
each layer routes through its experts via moe_ffn.

Routing semantics at serving time, deliberately:

- **prefill** routes exactly like training's moe_forward over the same
  tokens (capacity computed from the prompt length, earlier tokens claim
  expert slots first) — prefill logits equal the full forward's logits.
- **decode steps are dropless**: each step routes its single token with
  capacity(cfg, 1) ≥ 1 slot per expert, and top-k picks k DISTINCT
  experts, so a generated token is never capacity-dropped. Teacher-forcing
  a long sequence through moe_forward CAN drop late tokens that compete
  for full experts; a served continuation never competes with its prompt.
  (The standard serving behavior — capacity is a training-efficiency
  device, not a sampling semantic.)

Aux losses (load-balance, router-z) are computed by moe_ffn and discarded
here — serving has no optimizer to feed them to.

Reference parity note: the reference provisions nodes for KAITO which
serves MoE-class models (SURVEY.md §2c); the workload side of this repo
therefore ships the serving loop for both model families.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .decode import (KVCache, _cached_attention, _quantize_kv, _kv_int8,
                     init_kv_cache)
from .llama import _project_qkv, _rmsnorm, resolve_attn as _resolve_attn
from .moe import MoEConfig, moe_ffn


def moe_cached_forward(params: dict, tokens, cache: KVCache, cfg: MoEConfig,
                       pad_lens=None, dropless: bool = False):
    """Forward over ``tokens`` [B, S] starting at cache.length; returns
    (logits [B, S, V], updated cache). The MoE twin of
    decode.cached_forward — same cache contract (caller guarantees
    cache.length + S <= max_len), same pad_lens semantics, params in
    init_moe_model's layout: {"backbone": ..., "moe": per-layer experts}.

    ``dropless=True``: route with capacity = S so no token in this call can
    be capacity-dropped, making an S-token block's logits exactly equal S
    sequential single-token calls' (see moe_ffn). Speculative decoding's
    verify block requires this; plain decode steps (S=1) are dropless
    already, and prefill deliberately keeps training's capacity semantics.
    """
    _resolve_attn(cfg.attn_impl, cfg.sliding_window,
                  cfg.attn_sinks)  # loud validation
    ad = cfg.act_dtype
    B, S = tokens.shape
    start = cache.length
    per_row = jnp.ndim(start) == 1    # per-row lengths (batched spec)
    positions = (jnp.reshape(start, (-1, 1)) if per_row else start) \
        + jnp.arange(S, dtype=jnp.int32)
    token_mask = None
    if pad_lens is not None:
        # cache position of token i is start+i; row b's pads fill [0, pad_b)
        if not per_row:
            positions = positions[None, :]
        token_mask = positions >= pad_lens[:, None]                # [B, S]
        positions = jnp.maximum(positions - pad_lens[:, None], 0)
    scale = cfg.head_dim ** -0.5

    backbone = params["backbone"]
    x = backbone["embed"].astype(ad)[tokens]
    int8 = _kv_int8(cfg)
    if int8 != (cache.k_scale is not None):
        raise ValueError(
            f"kv_cache_dtype={cfg.kv_cache_dtype!r} but the cache was "
            f"built {'WITH' if cache.k_scale is not None else 'without'} "
            "int8 scales — cfg and init_kv_cache(cfg, ...) must agree")

    def write(buf, new):
        nh = new.transpose(0, 2, 1, 3)
        if per_row:   # per-row offsets: a batched scatter via vmap
            return jax.vmap(
                lambda b, n, s: lax.dynamic_update_slice(b, n, (0, s, 0))
            )(buf, nh, start)
        return lax.dynamic_update_slice(buf, nh, (0, 0, start, 0))

    def body(carry, layer):
        h = carry
        if int8:
            lp, lp_moe, k_cache, v_cache, k_scl, v_scl = layer
        else:
            lp, lp_moe, k_cache, v_cache = layer
            k_scl = v_scl = None

        a = _rmsnorm(h, lp["ln_attn"], cfg.norm_eps)
        q, k, v = _project_qkv(a, lp, cfg, positions)

        if int8:
            kq, ks_ = _quantize_kv(k)
            vq, vs_ = _quantize_kv(v)
            k_cache, v_cache = write(k_cache, kq), write(v_cache, vq)
            k_scl, v_scl = write(k_scl, ks_), write(v_scl, vs_)
        else:
            k_cache, v_cache = write(k_cache, k), write(v_cache, v)

        o = _cached_attention(q, k_cache, v_cache, start, scale,
                              impl=cfg.attn_impl, pad_lens=pad_lens,
                              k_scale=k_scl, v_scale=v_scl,
                              window=cfg.sliding_window,
                              sinks=cfg.attn_sinks)
        h = h + o.reshape(B, S, cfg.n_heads * cfg.head_dim) \
            @ lp["wo"].astype(ad)
        m = _rmsnorm(h, lp["ln_mlp"], cfg.norm_eps)
        # pad positions must not claim expert capacity (they sit FIRST in
        # the claim order and would evict real tokens) nor emit output
        ffn_out, _aux = moe_ffn(m, lp_moe, cfg, token_mask=token_mask,
                                cap_override=S if dropless else None)
        h = h + ffn_out
        out = ((k_cache, v_cache, k_scl, v_scl) if int8
               else (k_cache, v_cache))
        return h, out

    xs = ((backbone["blocks"], params["moe"], cache.k, cache.v,
           cache.k_scale, cache.v_scale) if int8
          else (backbone["blocks"], params["moe"], cache.k, cache.v))
    x, caches = lax.scan(body, x, xs)
    x = _rmsnorm(x, backbone["ln_final"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ backbone["lm_head"].astype(jnp.float32)
    if int8:
        k_new, v_new, ks_new, vs_new = caches
        new_cache = KVCache(k=k_new, v=v_new, length=start + S,
                            k_scale=ks_new, v_scale=vs_new)
    else:
        k_new, v_new = caches
        new_cache = KVCache(k=k_new, v=v_new, length=start + S)
    return logits, new_cache


def moe_prefill(params: dict, prompt, cache: KVCache, cfg: MoEConfig, *,
                pad_lens=None):
    """(last-token logits [B, V], cache) after consuming the prompt.
    Always the general cached forward — the MoE family has no fresh-cache
    S×S fast path (the expert dispatch dominates prefill cost, not the
    attention masking the fast path optimizes away)."""
    logits, cache = moe_cached_forward(params, prompt, cache, cfg,
                                       pad_lens=pad_lens)
    return logits[:, -1], cache


# chunked-prefill step (decode.prefill_chunked dispatches here for MoE
# configs): donated cache, same rationale as decode._cached_forward_jit
_moe_cached_forward_jit = jax.jit(moe_cached_forward, static_argnums=(3,),
                                  donate_argnums=(2,))

__all__ = ["moe_cached_forward", "moe_prefill", "init_kv_cache",
           "MoEConfig"]
