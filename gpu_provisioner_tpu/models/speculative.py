"""Speculative decoding: a small draft model proposes, the target verifies.

Greedy speculative decoding (the Leviathan/Chen scheme's deterministic
special case): the draft autoregresses ``spec_k`` cheap tokens, the target
scores all of them in ONE cached forward (a [1, k+1] prefill-shaped call
instead of k+1 serial decode steps), and the longest prefix where the
draft's choices equal the target's argmax is accepted, plus one "bonus"
token from the target's own distribution at the first disagreement.

Output-equality guarantee: greedy speculative decoding emits EXACTLY the
token stream of plain greedy decoding with the target model — acceptance
only ever keeps tokens the target itself would have picked. The speedup is
latency only: ceil(max_new / (accepted+1)) target forwards instead of
max_new, bought with draft FLOPs (cheap by construction) and wider target
calls (nearly free: a decode step is HBM-bandwidth-bound on the weights,
and a [1, k+1] call reads the weights ONCE for k+1 positions — the same
economics that make batched decode cheap).

TPU shape discipline: everything is static-shape inside one
``lax.while_loop`` — per-iteration acceptance length is data-dependent,
so the loop carries (output buffer, emit count, caches) and writes
fixed-width windows with masking; rollback after partial acceptance is
just the traced cache ``length`` scalar (keys beyond it are masked out of
every later attention and overwritten by later writes, so no buffer
cleanup is needed — the same invariant cached_forward already relies on).

Scope: batch 1 (speculation is a latency tool; per-row acceptance lengths
would need per-row cache lengths), greedy only, dense/Llama family for
both models (same vocab required; MoE targets raise until
moe_cached_forward grows a speculative harness).

Reference parity note: workload-side scope beyond the reference
(SURVEY.md §2c) — the serving stack KAITO provisions for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .decode import cached_forward, init_kv_cache, prefill
from .llama import LlamaConfig


def speculative_generate(params, draft_params, prompt, cfg: LlamaConfig,
                         draft_cfg: LlamaConfig, *, max_new_tokens: int,
                         spec_k: int = 4, max_len: int = None):
    """Greedy generation of ``max_new_tokens`` tokens from the TARGET
    model, accelerated by the draft. prompt: [1, S0] int32 →
    (tokens [1, max_new_tokens], stats dict with ``target_calls`` — the
    number of target forwards actually executed, vs max_new_tokens for
    plain decoding).

    ``spec_k``: draft tokens proposed per round. Each round emits between
    1 and spec_k+1 tokens. Both models must share the vocabulary."""
    from .moe import MoEConfig
    if isinstance(cfg, MoEConfig) or isinstance(draft_cfg, MoEConfig):
        raise NotImplementedError(
            "speculative decoding drives cached_forward directly; the MoE "
            "family needs the moe_cached_forward harness")
    B, S0 = prompt.shape
    if B != 1:
        raise ValueError(
            f"speculative decoding is batch-1 (latency tool); got B={B} — "
            "per-row acceptance would need per-row cache lengths")
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary: "
                         f"{draft_cfg.vocab_size} != {cfg.vocab_size}")
    if max_len is None:
        max_len = S0 + max_new_tokens + spec_k + 1
    # the verify call may run up to spec_k+1 past the final emission
    assert S0 + max_new_tokens + spec_k + 1 <= max_len, (
        S0, max_new_tokens, spec_k, max_len)

    cache_t = init_kv_cache(cfg, 1, max_len)
    cache_d = init_kv_cache(draft_cfg, 1, max_len)
    # prefill both; the target's last-position logits give the first token
    logits_t, cache_t = prefill(params, prompt, cache_t, cfg, fresh=True)
    _, cache_d = prefill(draft_params, prompt, cache_d, draft_cfg,
                         fresh=True)
    tok0 = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)     # [1]

    BUF = max_new_tokens + spec_k + 1          # slack for the last window
    out0 = jnp.zeros((1, BUF), jnp.int32)
    out0 = out0.at[:, 0].set(tok0)

    def cond(carry):
        _, n, _, _, _, _ = carry
        return n < max_new_tokens

    def body(carry):
        out, n, last, cache_t, cache_d, calls = carry

        # --- draft phase: k+1 serial cheap steps -----------------------
        # step i consumes token i of [last, d1..dk]; the (k+1)-th write
        # puts d_k's kv in the draft cache so a fully-accepted round
        # leaves the draft consistent without a special case
        def draft_step(c, tok):
            cache_d = c
            lg, cache_d = cached_forward(draft_params, tok[None],
                                         cache_d, draft_cfg)
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            return cache_d, nxt

        def draft_scan(c, _):
            cache_d, tok = c
            cache_d, nxt = draft_step(cache_d, tok)
            return (cache_d, nxt), nxt

        (cache_d, _), drafts = lax.scan(
            draft_scan, (cache_d, last), None, length=spec_k + 1)
        drafts = drafts.transpose(1, 0)                 # [1, k+1]
        proposal = drafts[:, :spec_k]                   # d_1..d_k

        # --- target phase: ONE wide verify call ------------------------
        block = jnp.concatenate([last[:, None], proposal], axis=1)
        lg, cache_t = cached_forward(params, block, cache_t, cfg)
        preds = jnp.argmax(lg, axis=-1).astype(jnp.int32)   # [1, k+1]
        calls = calls + 1

        # longest agreeing prefix: m = #{i : d_i == p_i, all j<i agree}
        agree = (proposal == preds[:, :spec_k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)[0]  # scalar
        emit_n = m + 1                                      # + bonus token

        # emitted tokens = p_1..p_m (== d_1..d_m) then bonus p_{m+1}:
        # exactly preds[:, :m+1] — write the full fixed window, masked so
        # positions ≥ emit_n keep their old buffer contents
        window = lax.dynamic_slice(out, (0, n), (1, spec_k + 1))
        keep = jnp.arange(spec_k + 1)[None, :] < emit_n
        out = lax.dynamic_update_slice(
            out, jnp.where(keep, preds, window), (0, n))

        # --- rollback to the accepted state ----------------------------
        # target wrote k+1 entries ([last, d1..dk]); accepted needs
        # [.., last, d1..dm] → drop (k - m). draft wrote k+1 entries
        # ([last, d1..dk]) and the next round feeds new_last=p_{m+1}, so
        # it also keeps [.., last, d1..dm] → drop (k - m).
        cache_t = cache_t._replace(
            length=cache_t.length - (spec_k - m))
        cache_d = cache_d._replace(
            length=cache_d.length - (spec_k - m))

        new_last = preds[jnp.arange(1), m]                  # p_{m+1}, [1]
        return out, n + emit_n, new_last, cache_t, cache_d, calls

    out, n, _, _, _, calls = lax.while_loop(
        cond, body, (out0, jnp.asarray(1, jnp.int32), tok0,
                     cache_t, cache_d, jnp.asarray(1, jnp.int32)))
    return out[:, :max_new_tokens], {"target_calls": calls,
                                     "tokens": jnp.minimum(n, max_new_tokens)}
