"""Speculative decoding: a small draft model proposes, the target verifies.

Greedy speculative decoding (the Leviathan/Chen scheme's deterministic
special case): the draft autoregresses ``spec_k`` cheap tokens, the target
scores all of them in ONE cached forward (a [1, k+1] prefill-shaped call
instead of k+1 serial decode steps), and the longest prefix where the
draft's choices equal the target's argmax is accepted, plus one "bonus"
token from the target's own distribution at the first disagreement.

Output-equality guarantee: greedy speculative decoding emits EXACTLY the
token stream of plain greedy decoding with the target model — acceptance
only ever keeps tokens the target itself would have picked. The speedup is
latency only: ceil(max_new / (accepted+1)) target forwards instead of
max_new, bought with draft FLOPs (cheap by construction) and wider target
calls (nearly free: a decode step is HBM-bandwidth-bound on the weights,
and a [1, k+1] call reads the weights ONCE for k+1 positions — the same
economics that make batched decode cheap).

TPU shape discipline: everything is static-shape inside one
``lax.while_loop`` — per-iteration acceptance length is data-dependent,
so the loop carries (output buffer, emit count, caches) and writes
fixed-width windows with masking; rollback after partial acceptance is
just the traced cache ``length`` scalar (keys beyond it are masked out of
every later attention and overwritten by later writes, so no buffer
cleanup is needed — the same invariant cached_forward already relies on).

Sampled mode (temperature > 0): the draft samples its proposals from its
filtered distribution and the Leviathan/Chen rejection step (_spec_accept)
accepts proposal i with probability min(1, p_target/p_draft), resampling
from the normalized residual on rejection — every emitted token's law is
exactly the target's filtered distribution (statistically verified in
tests/test_speculative.py), though not token-identical to plain sampled
generate for a given key (RNG consumption differs).

Scope: batch 1 (speculation is a latency tool; per-row acceptance lengths
would need per-row cache lengths). Both model families serve: dense and
MoE configs each dispatch to their own cached forward (draft and target
independently — a dense draft speculating for an MoE target is the
natural production pairing). Same vocabulary required. MoE-target caveat:
the wide verify call routes its spec_k+1 tokens with the block's own
capacity (competition WITHIN the block), while plain decode routes each
token alone (dropless). Exactness for an MoE target therefore requires
the verify block to be drop-free in the worst case — capacity(cfg,
spec_k+1) ≥ spec_k+1, i.e. roughly capacity_factor · experts_per_token
≥ n_experts. Mixtral-style cf≈1.25 · 2 < 8 does NOT satisfy it: if
several verify-block tokens pick the same expert, a drop makes the
verify logits diverge from plain per-token decoding and speculative
output can differ from plain greedy. Raise capacity_factor for serving
(capacity is a training-efficiency device) or accept approximate
equality. Dense targets have no such coupling.

Reference parity note: workload-side scope beyond the reference
(SURVEY.md §2c) — the serving stack KAITO provisions for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .decode import (family_fns, filter_logits, init_kv_cache,
                     validate_sampling_args)
from .llama import LlamaConfig


def _spec_accept(key, proposal, p_d, p_t):
    """Leviathan/Chen rejection step, factored pure for direct statistical
    testing: proposal [k] drawn sequentially from the draft distributions
    p_d [k, V]; p_t [k+1, V] are the target's distributions at the same
    positions. Returns (m, bonus): accept proposal[i] while
    u_i < p_t[i, d_i] / p_d[i, d_i]; at the first rejection (position m)
    the bonus token is drawn from the normalized residual
    max(p_t[m] − p_d[m], 0) — and from p_t[k] itself when everything was
    accepted. This makes every emitted token's law EXACTLY the target's
    (the scheme's correctness theorem), regardless of draft quality."""
    k = proposal.shape[0]
    ku, kb = jax.random.split(key)
    u = jax.random.uniform(ku, (k,))
    q = jnp.take_along_axis(p_d, proposal[:, None], axis=1)[:, 0]   # q_i(d_i)
    p = jnp.take_along_axis(p_t[:k], proposal[:, None], axis=1)[:, 0]
    accept = u < jnp.minimum(1.0, p / jnp.maximum(q, 1e-20))
    m = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))              # scalar
    # residual at the rejected position; p_t[k] when fully accepted
    pt_m = jnp.take(p_t, m, axis=0)                                 # [V]
    pd_m = jnp.take(jnp.concatenate([p_d, jnp.zeros_like(p_d[:1])]),
                    m, axis=0)                                      # [V]
    resid = jnp.maximum(pt_m - pd_m, 0.0)
    s = jnp.sum(resid)
    probs = jnp.where(s > 0, resid / jnp.maximum(s, 1e-20), pt_m)
    bonus = jax.random.categorical(kb, jnp.log(jnp.maximum(probs, 1e-30)))
    return m, bonus.astype(jnp.int32)


def speculative_generate(params, draft_params, prompt, cfg: LlamaConfig,
                         draft_cfg: LlamaConfig, *, max_new_tokens: int,
                         spec_k: int = 4, max_len: int = None,
                         temperature: float = 0.0, top_k: int = None,
                         top_p: float = None, key=None, eos_id: int = None,
                         return_logprobs: bool = False):
    """Generation of ``max_new_tokens`` tokens from the TARGET model,
    accelerated by the draft. prompt: [1, S0] int32 →
    (tokens [1, max_new_tokens], stats dict with ``target_calls`` — the
    number of target forwards actually executed, vs max_new_tokens for
    plain decoding).

    ``temperature`` 0 (default) = greedy: output is EXACTLY plain greedy's
    stream. ``temperature`` > 0 (``key`` REQUIRED, same rule as generate):
    the draft SAMPLES its proposals from its filtered distribution and the
    rejection step (_spec_accept) keeps each emitted token's law exactly
    the target's filtered distribution — distribution-identical to plain
    sampled generate, though not token-identical for a given key (the RNG
    is consumed differently).

    ``spec_k``: draft tokens proposed per round. Each round emits between
    1 and spec_k+1 tokens. Both models must share the vocabulary.

    ``eos_id``: generate()'s finish semantics — every position after the
    first emitted eos comes back as eos_id, and the loop STOPS speculating
    once eos lands (plain decoding must scan to max_new_tokens; early
    exit is a bonus speculation gets from its host-side while_loop).

    ``return_logprobs``: also return each emitted token's log-probability
    under the TARGET's distribution at that position (greedy: unfiltered,
    matching generate(); sampled: the filtered distribution the scheme
    provably emits from — for a bonus token that is its marginal law's
    source distribution, not the residual it was mechanically drawn from)
    as a second [1, max_new_tokens] f32 array. Post-eos positions report
    0.0, like generate()."""
    B, S0 = prompt.shape
    if B != 1:
        raise ValueError(
            f"speculative decoding is batch-1 (latency tool); got B={B} — "
            "per-row acceptance would need per-row cache lengths")
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary: "
                         f"{draft_cfg.vocab_size} != {cfg.vocab_size}")
    validate_sampling_args(temperature, top_k, top_p, key)
    sampled = temperature > 0
    if not sampled:
        key = jax.random.key(0)          # threaded but never consumed
    if max_len is None:
        max_len = S0 + max_new_tokens + spec_k + 1
    # the verify call may run up to spec_k+1 past the final emission
    assert S0 + max_new_tokens + spec_k + 1 <= max_len, (
        S0, max_new_tokens, spec_k, max_len)

    prefill_t, step_t = family_fns(cfg, fresh=True)
    prefill_d, step_d = family_fns(draft_cfg, fresh=True)
    cache_t = init_kv_cache(cfg, 1, max_len)
    cache_d = init_kv_cache(draft_cfg, 1, max_len)
    # prefill both; the target's last-position logits give the first token
    logits_t, cache_t = prefill_t(params, prompt, cache_t)
    _, cache_d = prefill_d(draft_params, prompt, cache_d)
    def emit_dist(logits):
        """log of the distribution emitted tokens are reported under —
        generate()'s convention: unfiltered for greedy, filtered for
        sampling."""
        if sampled:
            logits = filter_logits(logits, temperature, top_k, top_p)
        return jax.nn.log_softmax(logits, axis=-1)

    if sampled:
        key, k0 = jax.random.split(key)
        tok0 = jax.random.categorical(
            k0, filter_logits(logits_t, temperature, top_k, top_p),
            axis=-1).astype(jnp.int32)                         # [1]
    else:
        tok0 = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)

    BUF = max_new_tokens + spec_k + 1          # slack for the last window
    out0 = jnp.zeros((1, BUF), jnp.int32)
    out0 = out0.at[:, 0].set(tok0)
    lp0 = jnp.zeros((1, BUF), jnp.float32)
    if return_logprobs:
        lp0 = lp0.at[:, 0].set(
            jnp.take_along_axis(emit_dist(logits_t), tok0[:, None],
                                axis=-1)[:, 0])

    def cond(carry):
        out, n = carry[0], carry[2]
        go = n < max_new_tokens
        if eos_id is not None:
            # stop speculating once eos landed anywhere emitted so far
            emitted = jnp.arange(out.shape[1]) < n
            go = go & ~jnp.any(emitted & (out[0] == eos_id))
        return go

    def body(carry):
        out, lp, n, last, cache_t, cache_d, calls, key = carry
        key, kd, ka = jax.random.split(key, 3)

        # --- draft phase: k+1 serial cheap steps -----------------------
        # step i consumes token i of [last, d1..dk]; the (k+1)-th write
        # puts d_k's kv in the draft cache so a fully-accepted round
        # leaves the draft consistent without a special case
        def draft_scan(c, kt):
            cache_d, tok = c
            lg, cache_d = step_d(draft_params, tok[None], cache_d)
            if sampled:
                fl = filter_logits(lg[:, 0], temperature, top_k, top_p)
                probs = jax.nn.softmax(fl, axis=-1)[0]          # [V]
                nxt = jax.random.categorical(kt, fl,
                                             axis=-1).astype(jnp.int32)
            else:
                probs = jnp.zeros((draft_cfg.vocab_size,))      # unused
                nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            return (cache_d, nxt), (nxt, probs)

        (cache_d, _), (drafts, draft_probs) = lax.scan(
            draft_scan, (cache_d, last), jax.random.split(kd, spec_k + 1))
        drafts = drafts.transpose(1, 0)                 # [1, k+1]
        proposal = drafts[:, :spec_k]                   # d_1..d_k

        # --- target phase: ONE wide verify call ------------------------
        block = jnp.concatenate([last[:, None], proposal], axis=1)
        lg, cache_t = step_t(params, block, cache_t)
        calls = calls + 1

        if sampled:
            fl_t = filter_logits(lg[0], temperature, top_k, top_p)
            p_t = jax.nn.softmax(fl_t, axis=-1)
            m, bonus = _spec_accept(ka, proposal[0],
                                    draft_probs[:spec_k], p_t)
            # emitted = accepted draft tokens then the bonus draw
            prop_pad = jnp.concatenate(
                [proposal[0], jnp.zeros((1,), jnp.int32)])
            emit_vec = jnp.where(jnp.arange(spec_k + 1) < m,
                                 prop_pad, bonus)[None, :]
            new_last = jnp.full((1,), bonus, jnp.int32)
        else:
            preds = jnp.argmax(lg, axis=-1).astype(jnp.int32)   # [1, k+1]
            # longest agreeing prefix: m = #{i : d_i == p_i, all j<i agree}
            agree = (proposal == preds[:, :spec_k]).astype(jnp.int32)
            m = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)[0]
            # emitted tokens = p_1..p_m (== d_1..d_m) then bonus p_{m+1}
            emit_vec = preds
            new_last = preds[jnp.arange(1), m]                  # p_{m+1}
        emit_n = m + 1                                          # + bonus

        # write the full fixed window, masked so positions ≥ emit_n keep
        # their old buffer contents
        window = lax.dynamic_slice(out, (0, n), (1, spec_k + 1))
        keep = jnp.arange(spec_k + 1)[None, :] < emit_n
        out = lax.dynamic_update_slice(
            out, jnp.where(keep, emit_vec, window), (0, n))
        if return_logprobs:
            # each emitted token scored under the target's distribution
            # at its own position (lg[0, i] is the dist after prefix+d_<i);
            # sampled mode reuses the already-filtered logits
            ld = (jax.nn.log_softmax(fl_t, axis=-1) if sampled
                  else jax.nn.log_softmax(lg[0], axis=-1))   # [k+1, V]
            wlp = jnp.take_along_axis(ld, emit_vec[0][:, None],
                                      axis=-1)[None, :, 0]   # [1, k+1]
            lwin = lax.dynamic_slice(lp, (0, n), (1, spec_k + 1))
            lp = lax.dynamic_update_slice(
                lp, jnp.where(keep, wlp, lwin), (0, n))

        # --- rollback to the accepted state ----------------------------
        # target wrote k+1 entries ([last, d1..dk]); accepted needs
        # [.., last, d1..dm] → drop (k - m). draft wrote k+1 entries
        # ([last, d1..dk]) and the next round feeds new_last, so it also
        # keeps [.., last, d1..dm] → drop (k - m).
        cache_t = cache_t._replace(
            length=cache_t.length - (spec_k - m))
        cache_d = cache_d._replace(
            length=cache_d.length - (spec_k - m))
        return (out, lp, n + emit_n, new_last, cache_t, cache_d, calls,
                key)

    out, lp, n, _, _, _, calls, _ = lax.while_loop(
        cond, body, (out0, lp0, jnp.asarray(1, jnp.int32), tok0,
                     cache_t, cache_d, jnp.asarray(1, jnp.int32), key))
    toks = out[:, :max_new_tokens]
    lps = lp[:, :max_new_tokens]
    n_tokens = jnp.minimum(n, max_new_tokens)
    if eos_id is not None:
        # HF unfinished_sequences convention (generate() parity): every
        # position AFTER the first eos reads back as eos_id. This single
        # mask also covers the last window's post-eos tail and any
        # never-filled buffer slots from the early exit (both sit after
        # the first eos).
        is_eos = toks == eos_id
        seen = jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
        after = (seen - is_eos.astype(jnp.int32)) > 0
        toks = jnp.where(after, eos_id, toks)
        lps = jnp.where(after, 0.0, lps)     # forced eos: not a model draw
        # finished length = through the first eos (n counts buffer writes,
        # which include the final window's post-eos tail)
        n_tokens = jnp.where(
            jnp.any(is_eos),
            jnp.argmax(is_eos[0]) + 1, n_tokens).astype(jnp.int32)
    stats = {"target_calls": calls, "tokens": n_tokens}
    if return_logprobs:
        return toks, lps, stats
    return toks, stats
