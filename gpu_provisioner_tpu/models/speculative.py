"""Speculative decoding: a small draft model proposes, the target verifies.

Greedy speculative decoding (the Leviathan/Chen scheme's deterministic
special case): the draft autoregresses ``spec_k`` cheap tokens, the target
scores all of them in ONE cached forward (a [1, k+1] prefill-shaped call
instead of k+1 serial decode steps), and the longest prefix where the
draft's choices equal the target's argmax is accepted, plus one "bonus"
token from the target's own distribution at the first disagreement.

Output-equality guarantee: greedy speculative decoding emits EXACTLY the
token stream of plain greedy decoding with the target model — acceptance
only ever keeps tokens the target itself would have picked. The speedup is
latency only: ceil(max_new / (accepted+1)) target forwards instead of
max_new, bought with draft FLOPs (cheap by construction) and wider target
calls (nearly free: a decode step is HBM-bandwidth-bound on the weights,
and a [1, k+1] call reads the weights ONCE for k+1 positions — the same
economics that make batched decode cheap).

TPU shape discipline: everything is static-shape inside one
``lax.while_loop`` — per-iteration acceptance length is data-dependent,
so the loop carries (output buffer, emit count, caches) and writes
fixed-width windows with masking; rollback after partial acceptance is
just the traced cache ``length`` scalar (keys beyond it are masked out of
every later attention and overwritten by later writes, so no buffer
cleanup is needed — the same invariant cached_forward already relies on).

Sampled mode (temperature > 0): the draft samples its proposals from its
filtered distribution and the Leviathan/Chen rejection step (_spec_accept)
accepts proposal i with probability min(1, p_target/p_draft), resampling
from the normalized residual on rejection — every emitted token's law is
exactly the target's filtered distribution (statistically verified in
tests/test_speculative.py), though not token-identical to plain sampled
generate for a given key (RNG consumption differs).

Batching: any B. Rows accept different numbers of draft tokens per round,
so the loop carries PER-ROW cache lengths (``KVCache.length`` as a [B]
vector — cached_forward writes at per-row offsets and the decode kernel
takes per-row starts through its scalar-prefetch meta) and a per-row
emit count; a finished row (quota or eos) rolls back everything its round
wrote (m = −1) so its caches stop advancing while the batch runs on.
Greedy batched speculation emits row-for-row exactly plain greedy
generate()'s stream. Both model families serve: dense and
MoE configs each dispatch to their own cached forward (draft and target
independently — a dense draft speculating for an MoE target is the
natural production pairing). Same vocabulary required. MoE targets: the
wide verify call routes with a DROP-FREE capacity override (capacity =
spec_k+1 for its own block — family_fns(dropless_step=True)), so no
verify token can be capacity-dropped and the verify logits equal plain
per-token decoding's exactly, even at Mixtral-style capacity factors
(cf≈1.25 · k=2 < E=8) where the training capacity WOULD drop. The
override exists because capacity is a training-efficiency device, not a
sampling semantic; the extra verify FLOPs are O(spec_k²/E) expert slots —
noise. Dense targets have no cross-token FFN coupling to begin with.

Reference parity note: workload-side scope beyond the reference
(SURVEY.md §2c) — the serving stack KAITO provisions for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .decode import (family_fns, filter_logits, init_kv_cache,
                     validate_sampling_args)
from .llama import LlamaConfig


def _spec_accept(key, proposal, p_d, p_t):
    """Leviathan/Chen rejection step, factored pure for direct statistical
    testing: proposal [k] drawn sequentially from the draft distributions
    p_d [k, V]; p_t [k+1, V] are the target's distributions at the same
    positions. Returns (m, bonus): accept proposal[i] while
    u_i < p_t[i, d_i] / p_d[i, d_i]; at the first rejection (position m)
    the bonus token is drawn from the normalized residual
    max(p_t[m] − p_d[m], 0) — and from p_t[k] itself when everything was
    accepted. This makes every emitted token's law EXACTLY the target's
    (the scheme's correctness theorem), regardless of draft quality."""
    k = proposal.shape[0]
    ku, kb = jax.random.split(key)
    u = jax.random.uniform(ku, (k,))
    q = jnp.take_along_axis(p_d, proposal[:, None], axis=1)[:, 0]   # q_i(d_i)
    p = jnp.take_along_axis(p_t[:k], proposal[:, None], axis=1)[:, 0]
    accept = u < jnp.minimum(1.0, p / jnp.maximum(q, 1e-20))
    m = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))              # scalar
    # residual at the rejected position; p_t[k] when fully accepted
    pt_m = jnp.take(p_t, m, axis=0)                                 # [V]
    pd_m = jnp.take(jnp.concatenate([p_d, jnp.zeros_like(p_d[:1])]),
                    m, axis=0)                                      # [V]
    resid = jnp.maximum(pt_m - pd_m, 0.0)
    s = jnp.sum(resid)
    probs = jnp.where(s > 0, resid / jnp.maximum(s, 1e-20), pt_m)
    bonus = jax.random.categorical(kb, jnp.log(jnp.maximum(probs, 1e-30)))
    return m, bonus.astype(jnp.int32)


def spec_round(step_t, step_d, params, draft_params, last, done,
               cache_t, cache_d, key, *, spec_k: int, draft_vocab: int,
               max_len: int, sampled: bool, temperature: float = 0.0,
               top_k=None, top_p=None):
    """ONE speculative round for a batch of rows — the shared core of
    ``speculative_generate``'s loop body and the serving engine's
    speculative step. ``last`` [B]: each row's previous token; ``done``
    [B]: rows that must emit nothing (their round rolls back in full and
    their caches never advance). Returns (emit_vec [B, spec_k+1], keep
    [B, spec_k+1] bool — True at emitted positions, emit_n [B], new_last
    [B], cache_t, cache_d, verify_logits [B, spec_k+1, V] — the target's
    logits at each block position, FILTERED when sampled, for logprob
    scoring)."""
    B = last.shape[0]
    kd, ka = jax.random.split(key)

    # A FINISHED row still flows through the round's k+1 writes (static
    # shapes), and its frozen length can sit as high as
    # S0+max_new+spec_k — writing k+1 entries there would escape max_len
    # (dynamic_update_slice would clamp and silently overwrite the live
    # tail). Clamp finished rows' write offset into bounds: everything a
    # finished row writes is discarded (it is never queried again), so
    # parking its writes at the bound keeps cached_forward's precondition
    # intact for every row. Active rows are in-bounds by callers' max_len
    # budgeting.
    bound = max_len - (spec_k + 1)
    cache_t = cache_t._replace(
        length=jnp.where(done, jnp.minimum(cache_t.length, bound),
                         cache_t.length))
    cache_d = cache_d._replace(
        length=jnp.where(done, jnp.minimum(cache_d.length, bound),
                         cache_d.length))

    # --- draft phase: k+1 serial cheap steps -------------------------------
    # step i consumes token i of [last, d1..dk]; the (k+1)-th write puts
    # d_k's kv in the draft cache so a fully-accepted round leaves the
    # draft consistent without a special case
    def draft_scan(c, kt):
        cache_d, tok = c
        lg, cache_d = step_d(draft_params, tok[:, None], cache_d)
        if sampled:
            fl = filter_logits(lg[:, 0], temperature, top_k, top_p)
            probs = jax.nn.softmax(fl, axis=-1)             # [B, V]
            nxt = jax.random.categorical(kt, fl,
                                         axis=-1).astype(jnp.int32)
        else:
            probs = jnp.zeros((B, draft_vocab))             # unused
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
        return (cache_d, nxt), (nxt, probs)

    (cache_d, _), (drafts, draft_probs) = lax.scan(
        draft_scan, (cache_d, last), jax.random.split(kd, spec_k + 1))
    drafts = drafts.transpose(1, 0)                 # [B, k+1]
    proposal = drafts[:, :spec_k]                   # d_1..d_k

    # --- target phase: ONE wide verify call --------------------------------
    block = jnp.concatenate([last[:, None], proposal], axis=1)
    lg, cache_t = step_t(params, block, cache_t)    # [B, k+1, V]

    if sampled:
        fl_t = filter_logits(lg, temperature, top_k, top_p)
        p_t = jax.nn.softmax(fl_t, axis=-1)         # [B, k+1, V]
        dp = draft_probs.transpose(1, 0, 2)[:, :spec_k]  # [B, k, V]
        m, bonus = jax.vmap(_spec_accept)(
            jax.random.split(ka, B), proposal, dp, p_t)  # [B], [B]
        # emitted = accepted draft tokens then the bonus draw
        prop_pad = jnp.concatenate(
            [proposal, jnp.zeros((B, 1), jnp.int32)], axis=1)
        emit_vec = jnp.where(jnp.arange(spec_k + 1)[None] < m[:, None],
                             prop_pad, bonus[:, None])   # [B, k+1]
        new_last = bonus
        verify_logits = fl_t
    else:
        preds = jnp.argmax(lg, axis=-1).astype(jnp.int32)   # [B, k+1]
        # longest agreeing prefix: m = #{i : d_i == p_i, all j<i agree}
        agree = (proposal == preds[:, :spec_k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(agree, axis=1), axis=1)     # [B]
        # emitted tokens = p_1..p_m (== d_1..d_m) then bonus p_{m+1}
        emit_vec = preds
        new_last = preds[jnp.arange(B), m]                  # p_{m+1}
        verify_logits = lg
    # finished rows emit NOTHING this round (m = −1 ⇒ emit_n = 0 and the
    # rollback below drops every entry the round wrote)
    m = jnp.where(done, -1, m)
    emit_n = m + 1                                          # [B]
    new_last = jnp.where(done, last, new_last)
    keep = jnp.arange(spec_k + 1)[None] < emit_n[:, None]   # [B, k+1]

    # --- rollback to the accepted state ------------------------------------
    # target wrote k+1 entries ([last, d1..dk]) at each row's offset;
    # accepted needs [.., last, d1..dm] → drop (k - m). draft wrote k+1
    # entries and the next round feeds new_last, so it also keeps
    # [.., last, d1..dm] → drop (k - m). (done rows: m = −1 drops all
    # k+1 — their caches never advance.)
    cache_t = cache_t._replace(length=cache_t.length - (spec_k - m))
    cache_d = cache_d._replace(length=cache_d.length - (spec_k - m))
    return emit_vec, keep, emit_n, new_last, cache_t, cache_d, verify_logits


def speculative_generate(params, draft_params, prompt, cfg: LlamaConfig,
                         draft_cfg: LlamaConfig, *, max_new_tokens: int,
                         spec_k: int = 4, max_len: int = None,
                         temperature: float = 0.0, top_k: int = None,
                         top_p: float = None, key=None, eos_id: int = None,
                         pad_id: int = None, return_logprobs: bool = False):
    """Generation of ``max_new_tokens`` tokens from the TARGET model,
    accelerated by the draft. prompt: [B, S0] int32 →
    (tokens [B, max_new_tokens], stats dict with ``target_calls`` — the
    number of wide target forwards actually executed (rounds), vs
    max_new_tokens for plain decoding, and per-row ``tokens``).

    ``temperature`` 0 (default) = greedy: output is EXACTLY plain greedy's
    stream. ``temperature`` > 0 (``key`` REQUIRED, same rule as generate):
    the draft SAMPLES its proposals from its filtered distribution and the
    rejection step (_spec_accept) keeps each emitted token's law exactly
    the target's filtered distribution — distribution-identical to plain
    sampled generate, though not token-identical for a given key (the RNG
    is consumed differently).

    ``spec_k``: draft tokens proposed per round. Each round emits between
    1 and spec_k+1 tokens. Both models must share the vocabulary.

    ``eos_id``: generate()'s finish semantics — every position after the
    first emitted eos comes back as eos_id, and a finished ROW stops
    contributing draft/verify work (its round rolls back in full); the
    loop exits once every row is finished (plain decoding must scan to
    max_new_tokens; early exit is a bonus speculation gets from its
    host-side while_loop).

    ``pad_id``: generate()'s ragged-batch convention — LEFT-pad prompts
    to a common S0; pad keys are masked out of every attention and RoPE
    counts from each row's first real token.

    ``return_logprobs``: also return each emitted token's log-probability
    under the TARGET's distribution at that position (greedy: unfiltered,
    matching generate(); sampled: the filtered distribution the scheme
    provably emits from — for a bonus token that is its marginal law's
    source distribution, not the residual it was mechanically drawn from)
    as a second [B, max_new_tokens] f32 array. Post-eos positions report
    0.0, like generate()."""
    B, S0 = prompt.shape
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    if cfg.vocab_size != draft_cfg.vocab_size:
        raise ValueError("draft and target must share a vocabulary: "
                         f"{draft_cfg.vocab_size} != {cfg.vocab_size}")
    validate_sampling_args(temperature, top_k, top_p, key)
    sampled = temperature > 0
    if not sampled:
        key = jax.random.key(0)          # threaded but never consumed
    if max_len is None:
        max_len = S0 + max_new_tokens + spec_k + 1
    # the verify call may run up to spec_k+1 past the final emission;
    # ValueError (not assert — stripped under -O) because violation
    # silently corrupts the cache via dynamic_update_slice clamping
    if S0 + max_new_tokens + spec_k + 1 > max_len:
        raise ValueError(
            f"max_len={max_len} cannot hold prompt ({S0}) + "
            f"max_new_tokens ({max_new_tokens}) + verify slack "
            f"(spec_k+1 = {spec_k + 1})")

    pad_lens = None
    if pad_id is not None:
        # leading-pad count per row == index of the first real token
        pad_lens = jnp.argmax((prompt != pad_id).astype(jnp.int32),
                              axis=1).astype(jnp.int32)

    # dropless_step: the verify block must not capacity-drop (MoE targets)
    # — see the module docstring; no-op for dense configs
    prefill_t, step_t = family_fns(cfg, pad_lens=pad_lens,
                                   fresh=pad_id is None, dropless_step=True)
    prefill_d, step_d = family_fns(draft_cfg, pad_lens=pad_lens,
                                   fresh=pad_id is None)
    cache_t = init_kv_cache(cfg, B, max_len)
    cache_d = init_kv_cache(draft_cfg, B, max_len)
    # prefill both; the target's last-position logits give the first token
    logits_t, cache_t = prefill_t(params, prompt, cache_t)
    _, cache_d = prefill_d(draft_params, prompt, cache_d)
    # per-row cache lengths from here on: rows accept different numbers of
    # draft tokens per round, so their caches advance at different rates
    row_len = jnp.full((B,), S0, jnp.int32)
    cache_t = cache_t._replace(length=row_len)
    cache_d = cache_d._replace(length=row_len)
    def emit_dist(logits):
        """log of the distribution emitted tokens are reported under —
        generate()'s convention: unfiltered for greedy, filtered for
        sampling."""
        if sampled:
            logits = filter_logits(logits, temperature, top_k, top_p)
        return jax.nn.log_softmax(logits, axis=-1)

    if sampled:
        key, k0 = jax.random.split(key)
        tok0 = jax.random.categorical(
            k0, filter_logits(logits_t, temperature, top_k, top_p),
            axis=-1).astype(jnp.int32)                         # [1]
    else:
        tok0 = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)

    BUF = max_new_tokens + spec_k + 1          # slack for the last window
    out0 = jnp.zeros((B, BUF), jnp.int32)
    out0 = out0.at[:, 0].set(tok0)
    lp0 = jnp.zeros((B, BUF), jnp.float32)
    if return_logprobs:
        lp0 = lp0.at[:, 0].set(
            jnp.take_along_axis(emit_dist(logits_t), tok0[:, None],
                                axis=-1)[:, 0])
    n0 = jnp.ones((B,), jnp.int32)
    done0 = n0 >= max_new_tokens
    if eos_id is not None:
        done0 = done0 | (tok0 == eos_id)

    def cond(carry):
        return jnp.any(~carry[4])              # any row still generating

    def body(carry):
        out, lp, n, last, done, cache_t, cache_d, calls, key = carry
        key, kr = jax.random.split(key)
        (emit_vec, keep, emit_n, new_last, cache_t, cache_d,
         verify_logits) = spec_round(
            step_t, step_d, params, draft_params, last, done, cache_t,
            cache_d, kr, spec_k=spec_k, draft_vocab=draft_cfg.vocab_size,
            max_len=max_len, sampled=sampled, temperature=temperature,
            top_k=top_k, top_p=top_p)
        calls = calls + 1

        # write the full fixed window PER ROW at its own offset, masked so
        # positions ≥ emit_n keep their old buffer contents
        def row_update(buf_row, n_b, new_b, keep_b):
            window = lax.dynamic_slice(buf_row, (n_b,), (spec_k + 1,))
            return lax.dynamic_update_slice(
                buf_row, jnp.where(keep_b, new_b, window), (n_b,))

        out = jax.vmap(row_update)(out, n, emit_vec, keep)
        if return_logprobs:
            # each emitted token scored under the target's distribution at
            # its own position (verify_logits[b, i] is the dist after
            # prefix+d_<i; already filtered in sampled mode)
            ld = jax.nn.log_softmax(verify_logits, axis=-1)  # [B, k+1, V]
            wlp = jnp.take_along_axis(ld, emit_vec[..., None],
                                      axis=-1)[..., 0]    # [B, k+1]
            lp = jax.vmap(row_update)(lp, n, wlp, keep)

        n = n + emit_n
        done = done | (n >= max_new_tokens)
        if eos_id is not None:
            done = done | jnp.any(keep & (emit_vec == eos_id), axis=1)
        return (out, lp, n, new_last, done, cache_t, cache_d, calls, key)

    out, lp, n, _, _, _, _, calls, _ = lax.while_loop(
        cond, body, (out0, lp0, n0, tok0, done0,
                     cache_t, cache_d, jnp.asarray(1, jnp.int32), key))
    toks = out[:, :max_new_tokens]
    lps = lp[:, :max_new_tokens]
    n_tokens = jnp.minimum(n, max_new_tokens)
    if eos_id is not None:
        # HF unfinished_sequences convention (generate() parity): every
        # position AFTER the first eos reads back as eos_id. This single
        # mask also covers the last window's post-eos tail and any
        # never-filled buffer slots from the early exit (both sit after
        # the first eos).
        is_eos = toks == eos_id
        seen = jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
        after = (seen - is_eos.astype(jnp.int32)) > 0
        toks = jnp.where(after, eos_id, toks)
        lps = jnp.where(after, 0.0, lps)     # forced eos: not a model draw
        # finished length = through the first eos (n counts buffer writes,
        # which include the final window's post-eos tail)
        n_tokens = jnp.where(
            jnp.any(is_eos, axis=1),
            jnp.argmax(is_eos, axis=1) + 1, n_tokens).astype(jnp.int32)
    stats = {"target_calls": calls, "tokens": n_tokens}
    if return_logprobs:
        return toks, lps, stats
    return toks, stats
