"""Sharded training step over the provisioner-derived mesh.

The GSPMD recipe (scaling-book): params carry PartitionSpecs
(models/llama.py param_specs — tensor parallel over ``model``), the batch is
sharded over (slice, data) × ``seq``, attention runs as a shard_map'd ring
kernel over ``seq``, and XLA inserts every collective (psum for row-parallel
matmuls, all-gathers for the embedding, reduce-scatter in the backward) —
nothing is hand-scheduled. ``slice`` is the DCN axis: gradients sync across
slices exactly like data parallelism, which is the multi-slice
"4× v5e-16 DCN data-parallel" configuration in BASELINE.json.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.ring import ring_attention
from ..parallel.topology import AXIS_DATA, AXIS_MODEL, AXIS_SEQ, AXIS_SLICE
from .llama import (LlamaConfig, forward, init_params, param_specs,
                    resolve_attn)

BATCH_SPEC = P((AXIS_SLICE, AXIS_DATA), AXIS_SEQ)


def default_optimizer(mu_dtype=None):
    """The one default — make_train_state and make_train_step must agree or
    opt_state layout and update rules silently diverge. ``mu_dtype=bfloat16``
    halves first-moment memory for HBM-bound single-chip runs."""
    return optax.adamw(3e-4, weight_decay=0.1, mu_dtype=mu_dtype)


def make_attn_fn(mesh, impl: str = "dense",
                 seq_schedule: str = "ring",
                 window: int = None, sinks: int = 0) -> Callable:
    """Attention for the mesh: ring over ``seq`` when that axis is sharded;
    otherwise the pallas flash kernel (impl="flash") or dense, shard_mapped
    so each device runs the kernel on its local (batch, head) shard.
    ``seq_schedule="zigzag"`` load-balances the causal ring (every shard
    holds an early+late chunk pair; see parallel/ring.py) at the cost of a
    seq permutation outside the shard_map — GSPMD lowers the gathers to
    all-to-alls on ICI, negligible next to the O(S²/n) attention saved.

    ``window`` (cfg.sliding_window): impl="flash" takes the windowed
    Pallas kernels (O(S·window) — see resolve_attn); composing SWA with a
    seq-sharded ring schedule is not implemented — raise rather than
    silently train full-causal."""
    attn = resolve_attn(impl, window, sinks)  # validates every branch
    qkv_spec = P((AXIS_SLICE, AXIS_DATA), AXIS_SEQ, AXIS_MODEL, None)
    if mesh.shape[AXIS_SEQ] > 1:
        if window is not None:
            raise NotImplementedError(
                "sliding_window × sequence-parallel ring attention is not "
                "implemented; train SWA models with sp=1")
        if seq_schedule == "zigzag":
            from ..parallel.ring import zigzag_order, zigzag_ring_attention

            n = mesh.shape[AXIS_SEQ]
            ring = jax.shard_map(
                partial(zigzag_ring_attention, axis_name=AXIS_SEQ, impl=impl),
                mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
                out_specs=qkv_spec, check_vma=False)

            def attn(q, k, v):
                perm, inv = zigzag_order(q.shape[1], n)
                return ring(q[:, perm], k[:, perm], v[:, perm])[:, inv]
            return attn
        return jax.shard_map(
            partial(ring_attention, axis_name=AXIS_SEQ, impl=impl),
            mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec, check_vma=False)
    if impl == "flash":
        return jax.shard_map(
            attn, mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec, check_vma=False)
    return attn


def loss_fn(params, inputs, targets, cfg: LlamaConfig, attn_fn=None,
            positions=None):
    """Next-token cross entropy. inputs/targets: [B, S] int32 (pre-shifted —
    both shard cleanly over ``seq``, unlike a fused [B, S+1] array).
    ``positions`` carries each token's true global position when the caller
    feeds a permuted sequence (the zigzag schedule); the mean is
    permutation-invariant so the loss needs no unpermute."""
    logits = forward(params, inputs, cfg, attn_fn=attn_fn,
                     positions=positions)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def shard_params(params, mesh, cfg: Optional[LlamaConfig] = None, specs=None):
    """Place a parameter pytree onto the mesh, per ``specs`` when given,
    else per param_specs(cfg)."""
    if specs is None:
        specs = param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def make_train_state(key, cfg: LlamaConfig, mesh, optimizer=None):
    """(params, opt_state, optimizer) initialized and sharded on the mesh."""
    if optimizer is None:
        optimizer = default_optimizer()
    params = shard_params(init_params(key, cfg), mesh, cfg)
    opt_state = jax.jit(optimizer.init)(params)  # inherits param shardings
    return params, opt_state, optimizer


def make_train_step(mesh, cfg: LlamaConfig, optimizer=None):
    """jitted (params, opt_state, inputs, targets) → (params, opt_state, loss).

    inputs/targets: [B, S] int32, sharded BATCH_SPEC. Donates
    params/opt_state so the update is in-place in HBM.

    zigzag schedule: the TOKEN batch is permuted once per step (true global
    positions travel to rope via ``positions``; the loss mean is
    permutation-invariant), so the attention itself runs zigzag-layout with
    zero per-layer gathers — make_attn_fn's per-call permute wrapper is for
    standalone attention use, not this path.
    """
    from functools import partial as _partial

    if optimizer is None:
        optimizer = default_optimizer()
    zigzag = (cfg.seq_schedule == "zigzag" and mesh.shape[AXIS_SEQ] > 1)
    if zigzag:
        if cfg.sliding_window is not None:
            raise NotImplementedError(
                "sliding_window × sequence-parallel ring attention is not "
                "implemented; train SWA models with sp=1")
        from ..parallel.ring import zigzag_order, zigzag_ring_attention

        qkv_spec = P((AXIS_SLICE, AXIS_DATA), AXIS_SEQ, AXIS_MODEL, None)
        attn_fn = jax.shard_map(
            _partial(zigzag_ring_attention, axis_name=AXIS_SEQ,
                     impl=cfg.attn_impl),
            mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec, check_vma=False)
    else:
        attn_fn = make_attn_fn(mesh, impl=cfg.attn_impl,
                               seq_schedule=cfg.seq_schedule,
                               window=cfg.sliding_window,
                               sinks=cfg.attn_sinks)

    def step(params, opt_state, inputs, targets):
        positions = None
        if zigzag:
            perm, _ = zigzag_order(inputs.shape[1], mesh.shape[AXIS_SEQ])
            inputs, targets, positions = \
                inputs[:, perm], targets[:, perm], perm.astype(jnp.int32)
        loss, grads = jax.value_and_grad(loss_fn)(
            params, inputs, targets, cfg, attn_fn, positions)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def make_forward(cfg: LlamaConfig):
    """jittable single-device forward (the __graft_entry__ surface)."""

    def fn(params, tokens):
        return forward(params, tokens, cfg)

    return jax.jit(fn)


def make_pipeline_train_step(mesh, cfg: LlamaConfig, n_micro: int = 4,
                             n_chunks: int = 1, optimizer=None):
    """Train step with the decoder blocks pipelined over ``pipe``
    (parallel/pipeline.py): embed/head outside the pipeline with their tp
    specs, blocks layer-sharded over ``pipe`` AND tensor-parallel over
    ``model`` within each stage (partial-manual shard_map — GSPMD inserts
    the tp collectives inside stages). Composes with (slice, data) batch
    sharding and with ``seq`` sharding.

    Attention inside a stage follows ``cfg.attn_impl``: "flash" calls the
    Pallas kernel straight from the stage body (it runs under auto_axes, so
    GSPMD gathers the non-pipe shards around the unpartitionable
    pallas_call — free at pp>1's usual tp-light configs and exactly local
    on a single chip, and long-context training per stage stops paying the
    O(S²) dense score matrix); "dense" keeps the all-gathered dense path
    (ring attention's manual overlap stays exclusive to the non-pipelined
    path — nesting a second manual region inside the pipe region buys
    nothing at stage-local sequence lengths). ``n_chunks>1`` switches the
    schedule to Megatron-interleaved, shrinking the pipeline bubble and
    ramp waste by that factor."""
    from ..parallel.pipeline import pipelined_blocks
    from .llama import _block, _rmsnorm

    if optimizer is None:
        optimizer = default_optimizer()
    state_spec = P((AXIS_SLICE, AXIS_DATA), AXIS_SEQ)
    stage_attn = resolve_attn(cfg.attn_impl, cfg.sliding_window,
                              cfg.attn_sinks)

    def pipelined_forward(params, tokens):
        ad = cfg.act_dtype
        B, S = tokens.shape
        positions = jnp.arange(S, dtype=jnp.int32)
        x = params["embed"].astype(ad)[tokens]
        block_fn = lambda lp, h: _block(h, lp, cfg, positions, stage_attn)
        apply = pipelined_blocks(block_fn, mesh, cfg.n_layers, n_micro,
                                 n_chunks=n_chunks, state_spec=state_spec)
        x = apply(params["blocks"], x)
        x = _rmsnorm(x, params["ln_final"], cfg.norm_eps)
        return x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)

    def loss(params, inputs, targets):
        logits = pipelined_forward(params, inputs)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(logz - gold)

    def step(params, opt_state, inputs, targets):
        l, grads = jax.value_and_grad(loss)(params, inputs, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, l

    return jax.jit(step, donate_argnums=(0, 1))


def pipeline_param_specs(cfg: LlamaConfig) -> dict:
    """Pipeline layout COMPOSED with tensor parallelism: blocks get
    P(pipe, *megatron_dims) — layer dim over ``pipe``, weight dims keeping
    their ``model`` shards from param_specs; embed/head keep their
    vocab-parallel specs (they run outside the pipeline)."""
    from ..parallel.topology import AXIS_PIPE

    specs = param_specs(cfg)
    specs["blocks"] = jax.tree.map(
        lambda s: P(AXIS_PIPE, *s[1:]), specs["blocks"])
    return specs


def make_pipeline_train_state(key, cfg: LlamaConfig, mesh, optimizer=None,
                              n_chunks: int = 1):
    """(params, opt_state, optimizer) laid out per pipeline_param_specs,
    with the stacked layer dim permuted into the interleaved storage order
    the schedule expects (identity for n_chunks=1)."""
    from ..parallel.pipeline import to_pipeline_layout
    from ..parallel.topology import AXIS_PIPE

    if optimizer is None:
        optimizer = default_optimizer()
    params = init_params(key, cfg)
    params["blocks"] = to_pipeline_layout(
        params["blocks"], cfg.n_layers, mesh.shape[AXIS_PIPE], n_chunks)
    params = shard_params(params, mesh, specs=pipeline_param_specs(cfg))
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state, optimizer
