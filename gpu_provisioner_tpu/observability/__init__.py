"""claimtrace — per-claim lifecycle tracing with critical-path attribution.

The package stitches the repo's other observability surfaces (metrics, JSON
logs, Events, profiles) together by claim: one trace per claim UID, spans
opened at the existing seams (reconcile, provider state-machine steps, LRO
resolution, node wait), trace/span IDs injected into log records and Events
while a span is active, and a critical-path analyzer that decomposes a
wave's ready-wall into named phases (docs/OBSERVABILITY.md).

fleetscope (PR 14) builds the fleet layer on top: a streaming SLO engine
folding every ready claim into fixed-bucket percentile digests with
multi-window burn-rate alerts (``fleet``), and an anomaly-triggered flight
recorder of semantic control-plane events (``flightrecorder``).
"""

from .critical_path import (analyze_trace, render_attribution,
                            wave_attribution)
from .fleet import (FleetAggregator, LatencyDigest, SLOObjective,
                    SLOTracker, engine_stats, register_engine)
from .flightrecorder import FlightRecorder, wire_default_sources
from .tracing import (Span, Trace, TraceEvent, Tracer, TraceStore,
                      current_ids, install_log_record_factory,
                      render_waterfall)

__all__ = [
    "Span", "Trace", "TraceEvent", "Tracer", "TraceStore", "current_ids",
    "install_log_record_factory", "render_waterfall",
    "analyze_trace", "wave_attribution", "render_attribution",
    "FleetAggregator", "LatencyDigest", "SLOObjective", "SLOTracker",
    "engine_stats", "register_engine",
    "FlightRecorder", "wire_default_sources",
]
