"""Critical-path attribution: decompose a ready-wall into named phases.

BENCH_pr02 reconstructed "where does the wave's wall-clock go" by hand from
endpoint counters; this module does it mechanically from a claim's trace. A
claim's timeline [wave-start, ready] is partitioned by a priority sweep over
its span intervals:

    status-write > qr-wait > cloud-call > placement > node-wait > lro
        > queue-wait > reconcile

Time covered by nothing is the **requeue-idle-gap** — the claim existed and
nobody was working on it (parked on ``Result(requeue_after=...)``, or
waiting for its watch event to be pumped). Time covered *only* by a
reconcile span (controller body work with no named sub-phase) is
**reconcile-exec** and counts as unattributed: the attribution gate in the
bench asserts the named phases + idle-gap explain ≥ 95% of the wall, which
is only meaningful if "in a reconcile doing something we didn't name" can
fail it.

``node-wait`` is usually *derived*: in the non-blocking provisioning path no
code sits in a node-wait loop, so the phase is the interval from the create
LRO's resolution to the claim's ``registered`` annotation.
"""

from __future__ import annotations

import bisect
from typing import Iterable, Optional

from .tracing import Trace

# Higher priority wins where intervals overlap (a status-write inside a
# reconcile inside the claim's LRO window is status-write time). The
# placement span covers the whole candidate walk and CONTAINS its
# begin-create attempts — cloud-call outranks it so only the walk's own
# overhead (memo checks, stockout bookkeeping between probes) lands on
# the placement line.
_PRIORITY = {
    "status-write": 8,
    "qr-wait": 7,
    "cloud-call": 6,
    "placement": 5,
    "node-wait": 4,
    "lro": 3,
    "queue-wait": 2,
    "reconcile": 1,
}

IDLE = "requeue-idle-gap"
# Idle split by what ENDED the gap (the wake-source attr the workqueue
# stamps on the queue-wait span that follows): "woken" = an event source
# (watch/node/lro/stockout/status-flush) ended it early, "timer" = the
# requeue_after safety net had to fire — residual polling, the thing the
# wake graph exists to eliminate. Gaps nothing ended (the tail before
# ready when ready precedes the next dequeue) stay plain IDLE.
IDLE_WOKEN = "idle-gap:woken"
IDLE_TIMER = "idle-gap:timer"
UNATTRIBUTED = "reconcile-exec"

# Phases that count toward the attribution gate. The idle flavors are
# named — "the claim sat parked until X woke it" is an answer, and the
# one the wake-graph work gates on. UNATTRIBUTED is deliberately not.
NAMED_PHASES = ("queue-wait", "lro", "node-wait", "placement", "qr-wait",
                "cloud-call", "status-write", IDLE, IDLE_WOKEN, IDLE_TIMER)


def classify(span_name: str) -> Optional[str]:
    """Span name → phase, or None for spans the sweep ignores."""
    base = span_name.split(":", 1)[0]
    if base in ("queue-wait", "qr-wait", "status-write", "node-wait", "lro",
                "placement"):
        return base
    if base in ("begin-create", "begin-delete", "delete-queued"):
        return "cloud-call"
    if base == "reconcile":
        return "reconcile"
    return None


def _intervals(trace: Trace) -> list[tuple[float, float, str]]:
    out: list[tuple[float, float, str]] = []
    lro_ends: list[float] = []
    for s in trace.spans:
        phase = classify(s.name)
        if phase is None or s.end <= s.start:
            continue
        out.append((s.start, s.end, phase))
        if phase == "lro" and "create" in s.name:
            lro_ends.append(s.end)
    # Derived node-wait: create-LRO resolution → registered annotation.
    registered = [e.at for e in trace.events if e.name == "registered"]
    if lro_ends and registered:
        start, end = max(lro_ends), max(registered)
        if end > start:
            out.append((start, end, "node-wait"))
    return out


def analyze_trace(trace: Trace, t0: Optional[float] = None,
                  until_event: str = "ready") -> Optional[dict]:
    """Decompose one claim's [t0, ready] window. Returns None when the
    trace never reached ``until_event``."""
    finishes = [e.at for e in trace.events if e.name == until_event]
    if not finishes:
        return None
    ready = max(finishes)
    if t0 is None:
        t0 = trace.t0()
    if t0 is None or ready <= t0:
        return None

    ivals = [(max(s, t0), min(e, ready), p)
             for s, e, p in _intervals(trace) if e > t0 and s < ready]
    points = sorted({t0, ready, *(p for iv in ivals for p in iv[:2])})
    # Wake points: span starts carrying a ``wake`` attr (the queue-wait
    # span for a normal dequeue; the reconcile span when queue-wait was
    # zero). An idle segment whose END coincides with a wake point was
    # terminated by that wake — classify it by the wake's kind.
    wakes = sorted((max(s.start, t0),
                    "timer" if s.attrs.get("wake") == "timer" else "woken")
                   for s in trace.spans
                   if s.attrs.get("wake") and t0 < s.start <= ready + 1e-9)
    wake_times = [w[0] for w in wakes]
    phases: dict[str, float] = {}
    for lo, hi in zip(points, points[1:]):
        mid = (lo + hi) / 2
        best, best_pri = IDLE, 0
        for s, e, p in ivals:
            if s <= mid < e and _PRIORITY[p] > best_pri:
                best, best_pri = p, _PRIORITY[p]
        if best == "reconcile":
            best = UNATTRIBUTED
        elif best == IDLE:
            i = bisect.bisect_left(wake_times, hi - 1e-9)
            if i < len(wake_times) and wake_times[i] <= hi + 1e-9:
                best = IDLE_TIMER if wakes[i][1] == "timer" else IDLE_WOKEN
        phases[best] = phases.get(best, 0.0) + (hi - lo)

    wall = ready - t0
    attributed = sum(phases.get(p, 0.0) for p in NAMED_PHASES)
    return {
        "claim": trace.claim,
        "wall": wall,
        "ready_at": ready,
        "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
        "attributed_fraction": attributed / wall,
    }


def wave_attribution(traces: Iterable[Trace], t0: float,
                     until_event: str = "ready") -> Optional[dict]:
    """Wave-level view: the ready-wall is set by the last claim to go
    Ready, so the headline decomposition is that *critical* claim's
    timeline over [wave-start, last-ready]. Aggregate per-phase means over
    every finished claim ride along for the non-critical picture."""
    per_claim = [r for r in (analyze_trace(tr, t0=t0, until_event=until_event)
                             for tr in traces) if r is not None]
    if not per_claim:
        return None
    critical = max(per_claim, key=lambda r: r["ready_at"])
    n = len(per_claim)
    agg: dict[str, float] = {}
    for r in per_claim:
        for k, v in r["phases"].items():
            agg[k] = agg.get(k, 0.0) + v
    return {
        "claims": n,
        "wall": round(critical["wall"], 6),
        "critical_claim": critical["claim"],
        "phases": critical["phases"],
        "attributed_fraction": round(critical["attributed_fraction"], 6),
        "mean_phases": {k: round(v / n, 6) for k, v in sorted(agg.items())},
    }


def render_attribution(result: dict) -> str:
    """The ``make trace`` summary table."""
    wall = result["wall"]
    rows = [f"critical-path attribution: {result['claims']} claim(s), "
            f"wall {wall:.3f}s, critical claim {result['critical_claim']}"]
    for name, secs in sorted(result["phases"].items(),
                             key=lambda kv: -kv[1]):
        rows.append(f"  {name:<18} {secs:8.3f}s  {100 * secs / wall:5.1f}%")
    rows.append(f"  {'attributed':<18} {'':8}  "
                f"{100 * result['attributed_fraction']:5.1f}%")
    return "\n".join(rows)
