"""fleetscope SLO engine: streaming fleet-level latency digests + burn rate.

claimtrace (PR 9) answers "where did THIS claim's time go" from a 512-trace
ring; at mega-wave scale the ring wraps long before the wave ends, so the
ring cannot be the source of *fleet* statistics. This module subscribes to
trace annotations (``Tracer.add_listener``) and folds every claim that goes
Ready into **fixed-bucket percentile digests** the moment it completes —
O(buckets) memory per series, so 10k claims cost exactly what 100 do and
eviction stops mattering.

Three layers, all passive (no background tasks, loop-clock timestamps):

- :class:`LatencyDigest` — a geometric bucket ladder (1 ms … ~21 min,
  ×1.25). ``record`` is a bisect + increment; ``quantile`` walks the
  cumulative counts and clamps to the observed min/max.
- :class:`SLOTracker` — one declared objective ("time-to-ready p{q} ≤
  target") with the classic multi-window error-budget burn rate: a fast
  and a slow event-time window must BOTH burn above the threshold before
  the fast-burn alert fires (a lone fast-window spike is noise; a slow
  window alone alerts hours late).
- :class:`FleetAggregator` — the Tracer listener. On ``ready`` it runs the
  critical-path analyzer over the finished trace, folds wall time into the
  per-{zone, generation, tier, shard} digest (keys come off the trace attrs
  the placement walk stamps) and per-phase digests, and feeds every
  objective. Crossing into fast-burn fires ``on_fast_burn`` — the flight
  recorder's SLO anomaly trigger.

Counters/digests are sampled by ``controllers/metrics.py`` at scrape time
into the ``tpu_provisioner_slo_*`` families (this layer never imports
prometheus — the REPAIR_STATS convention), and ``snapshot()`` is the
``/slo`` endpoint payload. ``ENGINES`` rides along as the serving-engine
stats registry (``models/engine.py`` registers, metrics samples
``tpu_provisioner_engine_*``) — the input signal ROADMAP item 2's
autoscaler watches, rendezvousing here for the same reason REPAIR_STATS
rendezvous health and metrics.
"""

from __future__ import annotations

import weakref
from bisect import bisect_right
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from .critical_path import analyze_trace
from .tracing import Trace, _mono

# ---------------------------------------------------------------- registries

# Live aggregators, sampled by controllers/metrics.update_runtime_gauges at
# scrape time (the ops.TRACKERS idiom: weak so a dead Env's aggregator
# drops out of the scrape instead of freezing its last gauge values).
AGGREGATORS: "weakref.WeakSet[FleetAggregator]" = weakref.WeakSet()

# Serving engines by name → weakly-held engine objects exposing ``stats()``
# (models/engine.py registers itself at construction). Weak values: an
# engine garbage-collected with its test/benchmark disappears from the
# scrape rather than pinning a jax params tree alive.
ENGINES: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()


def register_engine(engine, name: Optional[str] = None) -> str:
    """Register a serving engine's ``stats()`` surface under ``name``
    (default: ``engine-N`` in registration order). Re-using a name replaces
    the previous engine — restart semantics, not an error."""
    if name is None:
        name = f"engine-{len(ENGINES)}"
    ENGINES[name] = engine
    return name


def engine_stats() -> dict[str, dict]:
    """Snapshot every live engine's counters (best-effort; a half-torn-down
    engine is skipped rather than failing the scrape)."""
    out: dict[str, dict] = {}
    for name, eng in list(ENGINES.items()):
        try:
            out[name] = eng.stats()
        except Exception:  # noqa: BLE001 — observability only
            continue
    return out


# ------------------------------------------------------------------ digests

# Geometric ladder: 1 ms × 1.25^i for 64 buckets ≈ 1 ms … 21 min, ~11%
# relative quantile error. Shared module-wide so a digest is one small list
# of ints — the "memory flat from 100 to 10k claims" property the bench
# gates (BENCH_pr14.json).
BUCKET_BOUNDS: tuple[float, ...] = tuple(
    0.001 * 1.25 ** i for i in range(64))


class LatencyDigest:
    """Fixed-bucket streaming percentile sketch. O(len(BUCKET_BOUNDS))
    memory regardless of how many observations were recorded."""

    __slots__ = ("counts", "count", "total", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.count = 0
        self.total = 0.0
        self.min = 0.0
        self.max = 0.0

    def record(self, value: float) -> None:
        v = max(0.0, float(value))
        self.counts[bisect_right(BUCKET_BOUNDS, v)] += 1
        if self.count == 0 or v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self.count += 1
        self.total += v

    def quantile(self, q: float) -> float:
        """The q-quantile's bucket upper bound, clamped to the observed
        [min, max] so a one-sample digest reports the sample itself."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target and c:
                hi = (BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS)
                      else self.max)
                return min(max(hi, self.min), self.max)
        return self.max

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def state(self) -> dict:
        """Wire form for cross-process aggregation (the multi-process shard
        supervisor ships these over the worker channel): the raw bucket
        counts plus the scalar folds. Buckets are fixed module-wide, so a
        snapshot is mergeable by element-wise add regardless of which
        worker produced it."""
        return {"counts": list(self.counts), "count": self.count,
                "total": self.total, "min": self.min, "max": self.max}

    @classmethod
    def from_state(cls, state: dict) -> "LatencyDigest":
        d = cls()
        counts = list(state.get("counts", ()))
        # tolerate a peer built against a different ladder length rather
        # than corrupting the merge — excess tail folds into the overflow
        for i, c in enumerate(counts):
            d.counts[min(i, len(d.counts) - 1)] += int(c)
        d.count = int(state.get("count", 0))
        d.total = float(state.get("total", 0.0))
        d.min = float(state.get("min", 0.0))
        d.max = float(state.get("max", 0.0))
        return d

    def merge(self, other: "LatencyDigest") -> None:
        """Fold ``other`` into this digest: element-wise bucket add plus
        scalar folds. Correct because every digest shares BUCKET_BOUNDS."""
        if other.count == 0:
            return
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        if self.count == 0:
            self.min = other.min
        else:
            self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        self.count += other.count
        self.total += other.total

    def summary(self) -> dict:
        return {
            "count": self.count,
            "mean": round(self.mean, 6),
            "p50": round(self.quantile(0.50), 6),
            "p90": round(self.quantile(0.90), 6),
            "p95": round(self.quantile(0.95), 6),
            "p99": round(self.quantile(0.99), 6),
            "max": round(self.max, 6),
        }


# --------------------------------------------------------------- objectives

@dataclass(frozen=True)
class SLOObjective:
    """A declared objective: at least ``percentile`` of claims must reach
    Ready within ``target`` seconds. The error budget is the complement
    (p95 ≤ target ⇒ 5% of claims may miss). Window lengths default to the
    production multi-window pair (5 m fast / 1 h slow); envtest passes
    second-scale windows — same math, compressed clock."""

    name: str = "time-to-ready"
    target: float = 600.0
    percentile: float = 0.95
    fast_window: float = 300.0
    slow_window: float = 3600.0
    # Both windows must burn ≥ threshold to alert. 14.4 is the canonical
    # "2% of a 30-day budget in one hour" page threshold.
    burn_threshold: float = 14.4
    # Below this many samples in the fast window the alert holds its fire —
    # one bad claim into an empty window is burn ∞, not an incident.
    min_samples: int = 10

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.percentile, 1e-9)


class BurnWindow:
    """Event-time good/bad counts over a sliding window, bucketed into
    ``slots`` fixed slots — O(slots) memory, loop-clock, no tasks."""

    __slots__ = ("window", "slots", "_gran", "_clock", "_ring")

    def __init__(self, window: float, slots: int = 15,
                 clock: Callable[[], float] = _mono):
        self.window = window
        self.slots = slots
        self._gran = max(window / slots, 1e-6)
        self._clock = clock
        self._ring: list[list] = []   # [slot_index, good, bad], ascending

    def _expire(self, now_idx: int) -> None:
        live = now_idx - self.slots
        while self._ring and self._ring[0][0] <= live:
            self._ring.pop(0)

    def note(self, ok: bool) -> None:
        idx = int(self._clock() / self._gran)
        if not self._ring or self._ring[-1][0] != idx:
            self._ring.append([idx, 0, 0])
        self._ring[-1][1 if ok else 2] += 1
        self._expire(idx)

    def counts(self) -> tuple[int, int]:
        self._expire(int(self._clock() / self._gran))
        good = sum(s[1] for s in self._ring)
        bad = sum(s[2] for s in self._ring)
        return good, bad

    def bad_fraction(self) -> float:
        good, bad = self.counts()
        total = good + bad
        return bad / total if total else 0.0


class SLOTracker:
    """One objective's live state: cumulative good/bad plus the fast/slow
    burn windows."""

    def __init__(self, objective: SLOObjective,
                 clock: Callable[[], float] = _mono):
        self.objective = objective
        self.good = 0
        self.bad = 0
        self.fast = BurnWindow(objective.fast_window, clock=clock)
        self.slow = BurnWindow(objective.slow_window, clock=clock)

    def note(self, time_to_ready: float) -> None:
        ok = time_to_ready <= self.objective.target
        if ok:
            self.good += 1
        else:
            self.bad += 1
        self.fast.note(ok)
        self.slow.note(ok)

    def burn_rates(self) -> dict[str, float]:
        budget = self.objective.error_budget
        return {"fast": self.fast.bad_fraction() / budget,
                "slow": self.slow.bad_fraction() / budget}

    def fast_burning(self) -> bool:
        """The multi-window alert condition: both windows over threshold,
        with enough fast-window evidence to mean it."""
        fg, fb = self.fast.counts()
        if fg + fb < self.objective.min_samples:
            return False
        burn = self.burn_rates()
        t = self.objective.burn_threshold
        return burn["fast"] >= t and burn["slow"] >= t

    def to_dict(self) -> dict:
        o = self.objective
        return {
            "name": o.name,
            "target_s": o.target,
            "percentile": o.percentile,
            "good": self.good,
            "violations": self.bad,
            "burn": {k: round(v, 4) for k, v in self.burn_rates().items()},
            "fast_burning": self.fast_burning(),
        }


# --------------------------------------------------------------- aggregator

# Trace attrs the placement walk stamps on the chosen candidate; absent
# (single-zone legacy world, direct provider tests) they read "none".
_KEY_ATTRS = ("zone", "generation", "tier")


class FleetAggregator:
    """The Tracer listener that turns per-claim traces into fleet SLO state.

    Passive and synchronous: ``on_trace_event`` runs inside the annotate
    call that marked the claim Ready — one ``analyze_trace`` (O(spans log
    spans) over an already-bounded trace) plus a handful of digest
    increments per claim, which the bench gates at ≤ 2% of wave wall."""

    def __init__(self, objectives: Optional[Iterable[SLOObjective]] = None,
                 shard: int = 0, clock: Callable[[], float] = _mono):
        self.shard = str(shard)
        self.fleet = LatencyDigest()
        self.digests: dict[tuple[str, str, str, str], LatencyDigest] = {}
        self.phase_digests: dict[str, LatencyDigest] = {}
        self.slos = [SLOTracker(o, clock=clock)
                     for o in (objectives
                               if objectives is not None
                               else (SLOObjective(),))]
        self.claims_observed = 0
        self.unattributed = 0     # ready traces analyze_trace couldn't place
        # fired on the transition INTO fast-burn per objective — the flight
        # recorder's slo-fast-burn trigger (re-arming when burn clears).
        self.on_fast_burn: Optional[Callable[[SLOTracker], None]] = None
        self._burning: set[str] = set()
        AGGREGATORS.add(self)

    # Tracer.add_listener signature
    def on_trace_event(self, trace: Trace, name: str) -> None:
        if name == "ready":
            self.observe(trace)

    def observe(self, trace: Trace) -> None:
        res = analyze_trace(trace)
        if res is None:
            self.unattributed += 1
            return
        wall = res["wall"]
        attrs = trace.attrs
        key = tuple(str(attrs.get(a, "none")) for a in _KEY_ATTRS) + (
            self.shard,)
        d = self.digests.get(key)
        if d is None:
            d = self.digests[key] = LatencyDigest()
        d.record(wall)
        self.fleet.record(wall)
        for phase, secs in res["phases"].items():
            pd = self.phase_digests.get(phase)
            if pd is None:
                pd = self.phase_digests[phase] = LatencyDigest()
            pd.record(secs)
        self.claims_observed += 1
        for t in self.slos:
            t.note(wall)
            name = t.objective.name
            if t.fast_burning():
                if name not in self._burning:
                    self._burning.add(name)
                    if self.on_fast_burn is not None:
                        self.on_fast_burn(t)
            else:
                self._burning.discard(name)

    def snapshot(self) -> dict:
        """The ``/slo`` endpoint payload."""
        return {
            "shard": self.shard,
            "claims_observed": self.claims_observed,
            "unattributed": self.unattributed,
            "fleet": self.fleet.summary(),
            "keys": [
                dict(zip(("zone", "generation", "tier", "shard"), key),
                     **digest.summary())
                for key, digest in sorted(self.digests.items())
            ],
            "phases": {phase: d.summary()
                       for phase, d in sorted(self.phase_digests.items())},
            "objectives": [t.to_dict() for t in self.slos],
        }


# ------------------------------------------------- cross-process aggregation

def digest_states() -> dict:
    """CUMULATIVE wire snapshot of every live aggregator in this process —
    what a shard worker ships to the supervisor over the snapshot channel.
    Keys are the joined label tuple (JSON has no tuple keys); digests from
    multiple aggregators under the same key merge element-wise."""
    digests: dict[str, LatencyDigest] = {}
    claims = 0
    for agg in list(AGGREGATORS):
        claims += agg.claims_observed
        for key, digest in list(agg.digests.items()):
            k = "|".join(key)
            if k in digests:
                digests[k].merge(digest)
            else:
                d = LatencyDigest()
                d.merge(digest)
                digests[k] = d
    return {"claims_observed": claims,
            "digests": {k: d.state() for k, d in digests.items()}}


class FleetMirror:
    """Parent-side stand-in for the workers' aggregators: registered in
    ``AGGREGATORS`` so the /metrics SLO export walks it like a local
    aggregator, but its digests are rebuilt WHOLESALE from the latest
    per-worker cumulative snapshots on every :meth:`load` — replacing, not
    folding into, prior state, so re-delivered snapshots never double-count.
    The holder (ShardSupervisor) keeps the strong reference; the weak
    registry drops the mirror with it."""

    def __init__(self) -> None:
        self.digests: dict[tuple[str, str, str, str], LatencyDigest] = {}
        self.claims_observed = 0
        # present (empty) so the /metrics AGGREGATORS walk treats a mirror
        # exactly like a local aggregator; phase/SLO state stays worker-local
        self.phase_digests: dict[str, LatencyDigest] = {}
        self.slos: tuple = ()
        AGGREGATORS.add(self)

    def load(self, worker_states) -> None:
        digests: dict[tuple[str, str, str, str], LatencyDigest] = {}
        claims = 0
        for st in worker_states:
            if not st:
                continue
            claims += int(st.get("claims_observed", 0))
            for k, ds in st.get("digests", {}).items():
                key = tuple(k.split("|"))
                nd = LatencyDigest.from_state(ds)
                if key in digests:
                    digests[key].merge(nd)
                else:
                    digests[key] = nd
        self.digests = digests
        self.claims_observed = claims
