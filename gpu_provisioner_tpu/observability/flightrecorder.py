"""Flight recorder: bounded ring of semantic control-plane events plus
anomaly-triggered diagnostic bundles.

The claimtrace ring answers "where did this claim's time go"; metrics
answer "how much of everything happened". Neither answers the incident
question — *what was the control plane doing right before it went
sideways* — once the 512-trace ring has wrapped. The recorder keeps the
last N **semantic** events (wakes, fence drops, breaker trips, placement
verdicts, repair decisions — not the hot per-reconcile chatter) in an
O(capacity) ring, and when an anomaly trigger fires (SLO fast-burn,
circuit-breaker or mass-repair-breaker trip, stall detector, recovery
adoption) it freezes a **bundle**: the ring, per-shard queue depths,
inflight cloud ops, recent trace summaries, placement memos. Bundles are
written to disk (when a directory is configured) and served at
``/debugz/bundle`` — the black box you pull after the crash.

The recorder taps the same ``runtime/probes`` seam schedfuzz arms
(PR 12), attached as a persistent *sink* so a fuzz probe and a recorder
coexist. Attachment is from outside (envtest / the operator main), never
by runtime importing this module — PG001 layering. Disabled, the probe
fast path stays a single module-global ``None`` check; tests pin that
structurally. ``probe()`` is synchronous and must stay cheap: membership
test, deque append, and — only on the rare trigger events — a bundle
snapshot.

Exactly-one-bundle-per-distinct-trigger: a zonal stockout trips the same
breaker on every reconcile tick for minutes; writing a bundle per tick
would bury the interesting first one and thrash the disk. Triggers dedupe
on (kind, key) — repeats increment ``triggers_suppressed`` and are
otherwise free.
"""

from __future__ import annotations

import json
import logging
import time
import weakref
from collections import deque
from pathlib import Path
from typing import Callable, Optional

from .tracing import _mono

log = logging.getLogger("flightrecorder")

# Live recorders, sampled by controllers/metrics.update_runtime_gauges at
# scrape (the ops.TRACKERS idiom — weak, so a torn-down Env's recorder
# drops out of the scrape).
RECORDERS: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()

# Probe events worth remembering. Deliberately NOT the hot path —
# wq-enqueue, cache-apply, handler-delivery, meta-patch, status-patch,
# fence-check, cloud-mutate and wq-timer-due fire per reconcile and would
# reduce the ring to the last few milliseconds; the semantic events below
# fire on *decisions*, so a 2048-slot ring spans minutes of real trouble.
RECORDED_EVENTS = frozenset({
    "hub-wake",            # wakehub delivered a wake (source-labelled)
    "hub-stop",            # wakehub shut down
    "wq-stale-drop",       # workqueue dropped a stale/superseded item
    "fence-drop",          # deletion fence rejected a late mutation
    "breaker-open",        # transport circuit breaker opened
    "repair-breaker-trip",  # mass-repair breaker crossed its fraction
    "repair-commit",       # health controller committed a repair
    "repair-success",      # a repaired node came back
    "recovery-adopt",      # restart recovery adopted pre-existing capacity
    "placement-verdict",   # candidate walk decided (chosen/stockout/...)
    "api-mode",            # APIHealthGovernor mode transition (all of them)
    "degraded-mode",       # governor ENTERED a non-HEALTHY mode (triggers)
})

# Probe event → trigger kind. These snapshot a bundle *in addition to*
# landing in the ring. SLO fast-burn and stall arrive via trigger()
# directly (they are not probe events).
TRIGGER_EVENTS = {
    "breaker-open": "breaker-trip",
    "repair-breaker-trip": "repair-breaker-trip",
    "recovery-adopt": "recovery-adoption",
    # one bundle per degraded-mode ENTERED (keyed by mode name, so a
    # flapping apiserver can't thrash the disk — re-entries of the same
    # mode are counted in triggers_suppressed)
    "degraded-mode": "degraded-mode",
}


def _jsonable(v):
    """Best-effort coercion for probe info values — bundles must always
    serialize, whatever a probe site passed."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    return repr(v)


class FlightRecorder:
    """Bounded semantic-event ring + trigger-deduped bundle snapshots.

    Passive: no tasks, no locks (single event loop), loop-clock stamps.
    ``sources`` are zero-arg callables contributing one section each to a
    bundle (queue depths, inflight ops, trace summaries, placement memos);
    a failing source contributes its error string instead of failing the
    snapshot — the recorder must never make an incident worse.
    """

    def __init__(self, capacity: int = 2048,
                 bundle_dir: Optional[str] = None,
                 clock: Callable[[], float] = _mono):
        self.capacity = capacity
        self.bundle_dir = Path(bundle_dir) if bundle_dir else None
        self._clock = clock
        self._ring: deque = deque(maxlen=capacity)
        self._sources: dict[str, Callable[[], object]] = {}
        self._bundles: dict[str, dict] = {}   # tkey → bundle, insert-ordered
        self._seq = 0
        self.events_recorded = 0
        self.bundles_written = 0
        self.triggers_suppressed = 0
        RECORDERS.add(self)

    # ------------------------------------------------------------- wiring

    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        """Register a bundle section provider (idempotent by name)."""
        self._sources[name] = fn

    # The probes.add_sink signature. Hot-ish path: one frozenset test for
    # everything emit() fans out, ring append only for recorded events.
    def probe(self, event: str, key, **info) -> None:
        if event not in RECORDED_EVENTS:
            return
        self._seq += 1
        self.events_recorded += 1
        self._ring.append({
            "seq": self._seq,
            "at": round(self._clock(), 6),
            "event": event,
            "key": str(key),
            **({"info": _jsonable(info)} if info else {}),
        })
        kind = TRIGGER_EVENTS.get(event)
        if kind is not None:
            # A probe site's info kwargs must never shadow trigger()'s own
            # parameters — a recorder quirk can't be allowed to raise back
            # into control-plane code through the emit fan-out.
            safe = {k: v for k, v in info.items()
                    if k not in ("kind", "key")}
            self.trigger(kind, key=str(key), **safe)

    # Breaker-listener signature (transport.add_breaker_listener) — the
    # transport layer is below runtime and has no probes import, so it
    # calls listeners directly and the recorder adapts here.
    def breaker_opened(self, name: str, **info) -> None:
        self.probe("breaker-open", name, **info)

    # Governor-listener signature (apihealth.add_degraded_listener): fired
    # on entry into any non-HEALTHY mode. Routed through probe() so the
    # entry lands in the ring AND snapshots a bundle via TRIGGER_EVENTS.
    def degraded_entered(self, mode: str, **info) -> None:
        self.probe("degraded-mode", mode, **info)

    def slo_fast_burn(self, tracker) -> None:
        """FleetAggregator.on_fast_burn adapter."""
        o = tracker.objective
        self.trigger("slo-fast-burn", key=o.name,
                     target_s=o.target, burn=tracker.burn_rates())

    def stall(self, lag: float) -> None:
        """StallDetector.on_stall adapter."""
        self.trigger("stall", key="event-loop", lag_s=round(lag, 4))

    # ----------------------------------------------------------- triggers

    def trigger(self, kind: str, key: str = "", **info) -> Optional[dict]:
        """Snapshot a bundle for (kind, key) — once. Repeats are counted
        and suppressed so a flapping breaker can't thrash the disk."""
        tkey = f"{kind}:{key}" if key else kind
        if tkey in self._bundles:
            self.triggers_suppressed += 1
            return None
        bundle = self._snapshot(kind, tkey, _jsonable(info))
        self._bundles[tkey] = bundle
        self._write(bundle)
        # Leave a marker in the ring so later bundles show earlier ones.
        self._seq += 1
        self._ring.append({"seq": self._seq,
                           "at": round(self._clock(), 6),
                           "event": "bundle-snapshot", "key": tkey})
        return bundle

    def _snapshot(self, kind: str, tkey: str, info: dict) -> dict:
        sources = {}
        for name, fn in self._sources.items():
            try:
                sources[name] = _jsonable(fn())
            except Exception as exc:  # noqa: BLE001 — never worsen incident
                sources[name] = {"error": repr(exc)}
        return {
            "trigger": {"kind": kind, "key": tkey, "info": info,
                        "at": round(self._clock(), 6),
                        "wall_time": time.time()},
            "seq": self._seq,
            "events": list(self._ring),
            "sources": sources,
        }

    def _write(self, bundle: dict) -> None:
        if self.bundle_dir is None:
            self.bundles_written += 1
            return
        try:
            self.bundle_dir.mkdir(parents=True, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-._" else "_"
                           for c in bundle["trigger"]["key"])
            path = self.bundle_dir / f"bundle-{self._seq:08d}-{safe}.json"
            path.write_text(json.dumps(bundle, indent=1, sort_keys=True))
            self.bundles_written += 1
        except OSError:
            log.warning("flight recorder could not write bundle",
                        exc_info=True)

    # ------------------------------------------------------------ reading

    def events(self) -> list[dict]:
        return list(self._ring)

    def bundles(self) -> list[dict]:
        """All bundles this run, oldest first (the /debugz/bundle list)."""
        return list(self._bundles.values())

    def bundle(self, tkey: Optional[str] = None) -> Optional[dict]:
        """One bundle: by trigger key, or the most recent."""
        if tkey is not None:
            return self._bundles.get(tkey)
        if not self._bundles:
            return None
        return next(reversed(self._bundles.values()))

    def stats(self) -> dict:
        return {
            "events_recorded": self.events_recorded,
            "ring_len": len(self._ring),
            "capacity": self.capacity,
            "bundles": len(self._bundles),
            "bundles_written": self.bundles_written,
            "triggers_suppressed": self.triggers_suppressed,
        }


def wire_default_sources(recorder: FlightRecorder, *, manager=None,
                         tracker=None, placement=None,
                         trace_store=None) -> None:
    """Attach the standard bundle sections for whatever subsystems exist.

    Everything is held weakly-by-closure on the objects the caller already
    owns; sources are snapshots, so a bundle taken mid-teardown degrades to
    error strings instead of raising.
    """
    if manager is not None:
        def queue_depths() -> dict:
            out = {}
            for c in getattr(manager, "controllers", []):
                q = getattr(c, "queue", None)
                if q is None:
                    continue
                out[c.name] = {"shard": getattr(c, "shard_index", 0),
                               "depth": q.depth(),
                               "delayed": q.delayed(),
                               "retrying": q.retrying()}
            return out
        recorder.add_source("queue_depths", queue_depths)

    if tracker is not None:
        recorder.add_source(
            "inflight_ops",
            lambda: {"inflight": tracker.inflight(),
                     "completed_total": tracker.completed_total})

    if placement is not None:
        recorder.add_source("placement_memos", placement.snapshot)

    if trace_store is not None:
        recorder.add_source(
            "recent_traces",
            lambda: [t.summary() for t in trace_store.recent(20)])
