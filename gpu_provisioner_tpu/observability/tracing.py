"""In-process span tracer: bounded TraceStore + contextvar propagation.

Design constraints, in order:

- **Passive.** No background tasks, no flush loops — recording is a list
  append under the event loop's implicit serialization, so the envtest
  task-leak gate needs no new tracked components and teardown order cannot
  deadlock on a tracer.
- **No open spans across tasks.** A span opened by one task and closed by
  another (the LRO poller resolves what ``create`` started) would leak
  contextvars between unrelated reconciles. Cross-task phases are recorded
  as *completed* spans from their known timestamps (``record_span``);
  ``span_begin``/``span_end`` pairs stay within one task and are policed by
  provlint PL012 (must be closed via context manager or try/finally).
- **Same clock as the operation tracker.** Timestamps use the running
  loop's clock (``providers.operations.loop_now`` semantics, duplicated
  here so observability imports nothing above ``logging``/stdlib) so spans
  recorded from ``TrackedOperation.started/completed_at`` line up with
  spans the tracer stamped itself.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
import uuid
from collections import OrderedDict
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Optional

# (trace_id, span_id) of the innermost active span in this task, or None.
# Read by the log-record factory and the event Recorder's trace_ids seam.
_CURRENT: ContextVar[Optional[tuple[str, str]]] = ContextVar(
    "claimtrace_current", default=None)


def current_ids() -> Optional[tuple[str, str]]:
    """The active (trace_id, span_id), or None outside any span."""
    return _CURRENT.get()


def _mono() -> float:
    """Loop clock inside async contexts, ``time.monotonic`` outside — the
    same seam as ``providers.operations.loop_now`` so tracker-sourced span
    timestamps and tracer-stamped ones share a time base."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


# One urandom read per process, then a counter: span ids need uniqueness,
# not unpredictability, and uuid4-per-span is an os.urandom syscall on the
# reconcile hot path — on a saturated single-core box that alone is a
# measurable slice of the tracing overhead budget.
_ID_PREFIX = uuid.uuid4().hex[:8]
_ID_COUNTER = itertools.count()


def _new_id() -> str:
    return f"{_ID_PREFIX}{next(_ID_COUNTER):08x}"


@dataclass(slots=True)
class Span:
    """One closed interval inside a trace. ``end`` is stamped at close; a
    span only enters ``Trace.spans`` once closed (open spans live on the
    ``_OpenSpan`` token), so readers never see a half-written interval."""

    span_id: str
    parent_id: str
    name: str
    start: float
    end: float = 0.0
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return max(0.0, self.end - self.start)


@dataclass(slots=True)
class TraceEvent:
    """Zero-duration annotation (ready, registered, adopted-on-restart)."""

    name: str
    at: float
    attrs: dict = field(default_factory=dict)


class Trace:
    """All spans + annotations for one claim, bounded to ``max_spans``."""

    def __init__(self, claim: str, max_spans: int = 256):
        self.claim = claim
        self.trace_id = _new_id()
        self.max_spans = max_spans
        self.attrs: dict = {}
        self.spans: list[Span] = []
        self.events: list[TraceEvent] = []
        self.dropped_spans = 0

    def add_span(self, span: Span) -> None:
        if len(self.spans) >= self.max_spans:
            self.dropped_spans += 1
            return
        self.spans.append(span)

    def add_event(self, ev: TraceEvent) -> None:
        if len(self.events) >= self.max_spans:
            self.dropped_spans += 1
            return
        self.events.append(ev)

    def t0(self) -> Optional[float]:
        starts = [s.start for s in self.spans] + [e.at for e in self.events]
        return min(starts) if starts else None

    def last_at(self) -> float:
        """Loop-clock timestamp of the trace's most recent activity — the
        ``/traces?since=`` cursor (clients echo the value back; monotonic
        values are opaque but orderable)."""
        ends = [s.end for s in self.spans] + [e.at for e in self.events]
        return max(ends) if ends else 0.0

    def to_dict(self) -> dict:
        """JSON shape served by ``/traces/{claim}`` — offsets are relative
        to the trace's first timestamp (monotonic values mean nothing to a
        client)."""
        t0 = self.t0() or 0.0
        return {
            "claim": self.claim,
            "trace_id": self.trace_id,
            "attrs": dict(self.attrs),
            "dropped_spans": self.dropped_spans,
            "spans": [{
                "span_id": s.span_id, "parent_id": s.parent_id,
                "name": s.name,
                "start": round(s.start - t0, 6),
                "duration": round(s.duration, 6),
                "attrs": dict(s.attrs),
            } for s in sorted(self.spans, key=lambda s: s.start)],
            "events": [{
                "name": e.name, "at": round(e.at - t0, 6),
                "attrs": dict(e.attrs),
            } for e in sorted(self.events, key=lambda e: e.at)],
        }

    def summary(self) -> dict:
        """Ring-listing shape served by ``/traces``."""
        t0 = self.t0()
        ends = [s.end for s in self.spans] + [e.at for e in self.events]
        return {
            "claim": self.claim, "trace_id": self.trace_id,
            "spans": len(self.spans), "events": len(self.events),
            "span_window": round(max(ends) - t0, 6) if t0 is not None else 0.0,
            "last_at": round(max(ends), 6) if ends else 0.0,
            "attrs": dict(self.attrs),
        }


class TraceStore:
    """Bounded ring buffer of traces keyed by claim name: inserting past
    ``max_traces`` evicts the oldest trace. Single-event-loop discipline —
    all mutation happens on the operator loop, so no lock."""

    def __init__(self, max_traces: int = 512, max_spans: int = 256):
        self.max_traces = max_traces
        self.max_spans = max_spans
        self._traces: "OrderedDict[str, Trace]" = OrderedDict()
        self.evicted_total = 0

    def get_or_create(self, claim: str) -> Trace:
        tr = self._traces.get(claim)
        if tr is None:
            tr = Trace(claim, max_spans=self.max_spans)
            self._traces[claim] = tr
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
                self.evicted_total += 1
        return tr

    def get(self, claim: str) -> Optional[Trace]:
        return self._traces.get(claim)

    def replace(self, claim: str) -> Trace:
        """Drop any existing trace for ``claim`` and start a fresh one —
        the restart re-anchor path (a new process owns a new trace_id; the
        old trace died with the old process's store anyway, but a
        RestartableEnv shares nothing either, so this is belt-and-braces
        for callers that re-adopt within one store)."""
        self._traces.pop(claim, None)
        return self.get_or_create(claim)

    def traces(self) -> list[Trace]:
        return list(self._traces.values())

    def recent(self, n: int = 50) -> list[Trace]:
        return list(self._traces.values())[-n:]

    def __len__(self) -> int:
        return len(self._traces)


class _OpenSpan:
    """Token returned by ``span_begin``; holds the contextvar reset token so
    nesting restores the parent span on close."""

    __slots__ = ("trace", "span", "cv_token")

    def __init__(self, trace: Trace, span: Span, cv_token):
        self.trace = trace
        self.span = span
        self.cv_token = cv_token


class _SpanScope:
    """Hand-rolled context manager over a ``span_begin`` token: the
    ``@contextmanager`` generator dance costs a generator frame plus three
    extra calls per span, which the hot reconcile seam pays thousands of
    times per wave. ``__exit__`` closes unconditionally, same as the old
    ``finally``."""

    __slots__ = ("_tracer", "_token")

    def __init__(self, tracer: "Tracer", token: Optional[_OpenSpan]):
        self._tracer = tracer
        self._token = token

    def __enter__(self) -> Optional[_OpenSpan]:
        return self._token

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            self._tracer.span_end(self._token)
        return False


# Shared no-op scope for every disabled-tracer span: the disabled path must
# cost a dict lookup and nothing else — the bench overhead baseline measures
# against a disabled tracer, so allocations here would poison the baseline.
_NULL_SCOPE = _SpanScope(None, None)


class Tracer:
    """The recording API threaded through controllers/providers/registry.

    Every method is a cheap no-op when the tracer is constructed with
    ``enabled=False`` (the bench overhead baseline measures against a
    *disabled* tracer as well as a ``None`` one — both paths must be free).
    """

    def __init__(self, store: Optional[TraceStore] = None,
                 enabled: bool = True):
        self.store = store if store is not None else TraceStore()
        self.enabled = enabled
        self._span_names: dict[str, str] = {}
        # Annotation listeners (the fleet SLO aggregator's subscription
        # seam): fn(trace, event_name), called synchronously after the
        # event is recorded. Tuple, not list — ``annotate`` is on the
        # reconcile path and the empty-tuple check is one truthiness test.
        self._listeners: tuple = ()

    def add_listener(self, fn) -> None:
        """Subscribe ``fn(trace, event_name)`` to every trace annotation.
        Listener exceptions are logged and swallowed — a broken aggregator
        must not fail the reconcile that happened to go Ready."""
        if fn not in self._listeners:
            self._listeners = self._listeners + (fn,)

    def remove_listener(self, fn) -> None:
        self._listeners = tuple(f for f in self._listeners if f is not fn)

    # -- manual pair (PL012: must be closed via try/finally) ---------------
    def span_begin(self, claim: str, name: str, **attrs) -> Optional[_OpenSpan]:
        if not self.enabled:
            return None
        tr = self.store.get_or_create(claim)
        cur = _CURRENT.get()
        parent = cur[1] if cur is not None and cur[0] == tr.trace_id else ""
        # attrs is this call's own kwargs dict — no defensive copy needed
        sp = Span(span_id=_new_id(), parent_id=parent, name=name,
                  start=_mono(), attrs=attrs)
        cv_token = _CURRENT.set((tr.trace_id, sp.span_id))
        return _OpenSpan(tr, sp, cv_token)

    def span_end(self, token: Optional[_OpenSpan], **attrs) -> None:
        if token is None:
            return
        token.span.end = _mono()
        if attrs:
            token.span.attrs.update(attrs)
        token.trace.add_span(token.span)
        _CURRENT.reset(token.cv_token)

    # -- context-manager form (the one real code uses) ---------------------
    def span(self, claim: str, name: str, **attrs) -> _SpanScope:
        if not self.enabled:
            return _NULL_SCOPE
        # provlint: disable=unclosed-span — the token goes straight into
        # _SpanScope, whose __exit__ IS the finally-guaranteed span_end
        return _SpanScope(self, self.span_begin(claim, name, **attrs))

    def reconcile_span(self, controller: str, claim: str,
                       queue_wait: Optional[float] = None,
                       wake_source: Optional[str] = None) -> _SpanScope:
        """The controller trace seam body: record the queue-wait that ended
        at this dequeue as a completed span, then cover the reconcile.
        ``wake_source`` (what put the item into the ready queue — watch,
        node, lro, timer, stockout, status-flush) is stamped as a ``wake``
        attr on the queue-wait span; the critical-path analyzer uses the
        queue-wait's *start* as the moment the preceding idle gap ended, so
        the attr lets it split requeue-idle-gap into woken-early vs
        timer-fired.

        This is the hottest tracer entry point — once per dequeue on every
        controller — so it inlines ``span_begin`` against a single trace
        lookup and a cached span name instead of composing the public
        helpers (which would pay the lookup twice and an f-string per
        reconcile)."""
        if not self.enabled:
            return _NULL_SCOPE
        tr = self.store.get_or_create(claim)
        start = _mono()
        waited = queue_wait is not None and queue_wait > 0
        if waited:
            qattrs = {"controller": controller}
            if wake_source:
                qattrs["wake"] = wake_source
            tr.add_span(Span(_new_id(), "", "queue-wait",
                             start - queue_wait, start, qattrs))
        name = self._span_names.get(controller)
        if name is None:
            name = self._span_names[controller] = f"reconcile:{controller}"
        cur = _CURRENT.get()
        parent = cur[1] if cur is not None and cur[0] == tr.trace_id else ""
        attrs = {"controller": controller}
        if wake_source and not waited:
            # Zero queue-wait dequeues still carry their wake cause — stamp
            # it on the reconcile span so attribution sees every wake.
            attrs["wake"] = wake_source
        sp = Span(_new_id(), parent, name, start, 0.0, attrs)
        cv_token = _CURRENT.set((tr.trace_id, sp.span_id))
        return _SpanScope(self, _OpenSpan(tr, sp, cv_token))

    # -- cross-task phases with known timestamps ---------------------------
    def record_span(self, claim: str, name: str, start: float, end: float,
                    parent_id: str = "", **attrs) -> None:
        """Record an already-completed interval (LRO resolution from the
        tracker's ``started``/``completed_at``, queue-wait from the
        workqueue's enqueue stamp). Never touches the contextvar."""
        if not self.enabled:
            return
        tr = self.store.get_or_create(claim)
        tr.add_span(Span(span_id=_new_id(), parent_id=parent_id, name=name,
                         start=start, end=max(end, start), attrs=attrs))

    def annotate(self, claim: str, name: str, **attrs) -> None:
        """Zero-duration trace event (ready, registered, adopted)."""
        if not self.enabled:
            return
        tr = self.store.get_or_create(claim)
        tr.add_event(TraceEvent(name=name, at=_mono(), attrs=attrs))
        if self._listeners:
            for fn in self._listeners:
                try:
                    fn(tr, name)
                except Exception:  # noqa: BLE001 — observability only
                    logging.getLogger("claimtrace").warning(
                        "trace listener failed on %s/%s", claim, name,
                        exc_info=True)

    def set_trace_attrs(self, claim: str, **attrs) -> None:
        if not self.enabled:
            return
        self.store.get_or_create(claim).attrs.update(attrs)

    def reanchor(self, claim: str, **attrs) -> None:
        """Restart re-anchor: start a fresh trace for an adopted claim (the
        pre-crash trace died with the old process) and mark the adoption so
        the waterfall shows the discontinuity."""
        if not self.enabled:
            return
        tr = self.store.replace(claim)
        tr.attrs.update(attrs)
        tr.attrs["reanchored"] = True
        tr.add_event(TraceEvent(name="adopted-on-restart", at=_mono(),
                                attrs=dict(attrs)))


# ------------------------------------------------------------ log stitching

def install_log_record_factory() -> None:
    """Stamp ``trace_id``/``span_id`` on every LogRecord created while a
    span is active. Record-creation-time stamping means caplog sees the ids
    in tests and the JSONFormatter's generic extra-attr loop emits them
    with no formatter change. Idempotent — wrapping twice would stamp
    twice-removed factories forever."""
    old = logging.getLogRecordFactory()
    if getattr(old, "_claimtrace", False):
        return

    def factory(*args, **kwargs):
        record = old(*args, **kwargs)
        cur = _CURRENT.get()
        if cur is not None:
            record.trace_id, record.span_id = cur
        return record

    factory._claimtrace = True
    logging.setLogRecordFactory(factory)


# ------------------------------------------------------------- waterfall

def render_waterfall(trace: Trace, width: int = 48) -> str:
    """Plain-text waterfall for ``/traces/{claim}?format=text`` and the
    ``make trace`` summary: one bar per span scaled to the trace window,
    annotations as point markers."""
    t0 = trace.t0()
    rows: list[str] = [
        f"claim={trace.claim} trace={trace.trace_id} "
        + " ".join(f"{k}={v}" for k, v in sorted(trace.attrs.items()))]
    if t0 is None:
        rows.append("  (no spans recorded)")
        return "\n".join(rows)
    ends = [s.end for s in trace.spans] + [e.at for e in trace.events]
    window = max(max(ends) - t0, 1e-9)
    items: list[tuple[float, str]] = []
    for s in sorted(trace.spans, key=lambda s: s.start):
        off, dur = s.start - t0, s.duration
        lo = int((off / window) * width)
        hi = max(lo + 1, int(((off + dur) / window) * width))
        bar = " " * lo + "█" * min(hi - lo, width - lo)
        attrs = " ".join(f"{k}={v}" for k, v in sorted(s.attrs.items()))
        items.append((off, f"  {off * 1000:9.1f}ms {dur * 1000:9.1f}ms "
                           f"|{bar:<{width}}| {s.name}"
                           + (f" [{attrs}]" if attrs else "")))
    for e in sorted(trace.events, key=lambda e: e.at):
        off = e.at - t0
        lo = min(int((off / window) * width), width - 1)
        bar = " " * lo + "▼"
        items.append((off, f"  {off * 1000:9.1f}ms {'·':>11} "
                           f"|{bar:<{width}}| @{e.name}"))
    rows += [line for _, line in sorted(items, key=lambda t: t[0])]
    if trace.dropped_spans:
        rows.append(f"  ({trace.dropped_spans} spans dropped at the "
                    f"{trace.max_spans}-span trace bound)")
    return "\n".join(rows)
