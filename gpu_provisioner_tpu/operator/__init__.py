"""Operator / process runtime (L5): options, logging, servers, assembly."""

from .options import Options, parse_options  # noqa: F401
from .logging import setup_logging  # noqa: F401
