"""Process entry: ``python -m gpu_provisioner_tpu.operator``.

The analog of cmd/controller/main.go:34-59 — build config, cloud client,
instance provider, metrics-decorated cloud provider, register the controller
set, start manager + servers, block. ``--simulate`` swaps the cloud client
seams for the in-process simulator (envtest) so the full operator can run on
a laptop: with ``--simulate-claims N`` it provisions N NodeClaims, prints
lifecycle transitions, and exits 0 when all are Ready (the verify handle).
"""

from __future__ import annotations

import asyncio
import logging
import sys

from ..apis import labels as wk
from ..apis.karpenter import NodeClaim
from ..apis.meta import CONDITION_READY
from ..envtest import Env, EnvtestOptions
from ..fake import make_nodeclaim
from ..runtime.store import MODIFIED
from .logging import setup_logging
from .options import parse_options
from .server import start_servers

log = logging.getLogger("operator")


async def run_simulate(opts) -> int:
    env_opts = EnvtestOptions(
        create_latency=0.5, node_join_delay=0.1, node_ready_delay=0.2,
        gc_interval=opts.gc_interval_seconds,
        leak_grace=opts.gc_leak_grace_seconds,
        repair_toleration=opts.repair_toleration_seconds)
    env_opts.repair_max_unhealthy_fraction = opts.repair_max_unhealthy_fraction
    env_opts.repair_breaker_min_unhealthy = opts.repair_breaker_min_unhealthy
    env_opts.repair_flap_threshold = opts.repair_flap_threshold
    env_opts.repair_flap_window = opts.repair_flap_window_seconds
    env_opts.repair_heartbeat_bound = opts.repair_heartbeat_bound_seconds
    env_opts.repair_drain_deadline = opts.repair_drain_deadline_seconds
    env_opts.repair_rate = opts.repair_rate
    env_opts.repair_rate_interval = opts.repair_rate_interval_seconds
    env_opts.repair_burst = opts.repair_burst
    env_opts.repair_max_concurrent = opts.repair_max_concurrent
    env_opts.lifecycle.liveness_enabled = opts.liveness_enabled
    env_opts.lifecycle.launch_timeout = opts.launch_timeout_seconds
    env_opts.lifecycle.registration_timeout = opts.registration_timeout_seconds
    env_opts.lifecycle.termination_requeue = opts.termination_requeue_seconds
    env_opts.termination.instance_requeue = opts.instance_requeue_seconds
    env_opts.max_concurrent_reconciles = opts.max_concurrent_reconciles
    env_opts.shards = opts.shards
    env_opts.shard_index = opts.shard_index
    env_opts.tracing = opts.tracing_enabled
    env_opts.trace_buffer = opts.trace_buffer
    env_opts.fleet = opts.fleet_enabled
    if opts.fleet_enabled:
        from ..observability import SLOObjective
        env_opts.slo_objectives = (SLOObjective(
            target=opts.slo_target_seconds,
            burn_threshold=opts.slo_fast_burn_threshold),)
    env_opts.flight_recorder = opts.flight_recorder_enabled
    env_opts.recorder_capacity = opts.recorder_capacity
    env_opts.bundle_dir = opts.bundle_dir or None

    async with Env(env_opts) as env:
        runners = await start_servers(env.manager, opts.metrics_port,
                                      opts.health_probe_port,
                                      opts.enable_profiling,
                                      trace_store=env.trace_store,
                                      fleet=env.fleet,
                                      recorder=env.flight_recorder)
        log.info("simulated operator up",
                 extra={"metrics_port": opts.metrics_port,
                        "health_port": opts.health_probe_port})

        watcher = asyncio.create_task(_log_transitions(env))
        try:
            if opts.simulate_claims > 0:
                from ..controllers.utils import shard_owns
                names = [f"sim{i}" for i in range(opts.simulate_claims)]
                for i, name in enumerate(names):
                    await env.client.create(make_nodeclaim(
                        name, opts.simulate_shape, workspace=f"ws{i}"))
                # a sharded simulate run only reconciles its own claims —
                # waiting on foreign ones would time out by design
                owned = [n for n in names
                         if shard_owns(n, opts.shards, opts.shard_index)]
                for name in owned:
                    nc = await env.wait_ready(name, timeout=120)
                    log.info("nodeclaim ready", extra={
                        "nodeclaim": nc.metadata.name,
                        "providerID": nc.status.provider_id,
                        "topology": nc.metadata.labels.get(wk.TPU_TOPOLOGY_LABEL)})
                log.info("all owned claims ready; exiting",
                         extra={"count": len(owned),
                                "claims_created": len(names)})
                return 0
            await asyncio.Event().wait()
            return 0
        finally:
            watcher.cancel()
            for r in runners:
                await r.cleanup()


async def _log_transitions(env: Env) -> None:
    seen: dict[str, str] = {}
    w = env.client.watch(NodeClaim)
    try:
        async for ev in w:
            nc = ev.object
            ready = nc.status_conditions.get(CONDITION_READY)
            state = "/".join(
                f"{c.type}={c.status}" for c in nc.status.conditions
                if c.type != CONDITION_READY) or "(pending)"
            key = f"{nc.metadata.name}:{state}"
            if ev.type == MODIFIED and seen.get(nc.metadata.name) != state:
                seen[nc.metadata.name] = state
                log.info("transition", extra={
                    "nodeclaim": nc.metadata.name, "conditions": state,
                    "ready": ready.status if ready else "Unknown"})
    finally:
        w.close()


async def run_real(opts) -> int:
    """Assemble against a real cluster (cmd/controller/main.go:34-59 analog):
    config from env → credentials → GKE/CloudTPU clients → instance provider
    → metrics-decorated cloud provider → controller set → manager."""
    import signal

    from ..apis.core import Node
    from ..auth.config import ConfigError, build_config
    from ..auth.credentials import new_credential
    from ..cloudprovider import MetricsDecorator, TPUCloudProvider
    from ..controllers.gc import GCOptions
    from ..controllers.health import HealthOptions
    from ..controllers.lifecycle import LifecycleOptions
    from ..controllers.registry import build_controllers
    from ..providers.instance import InstanceProvider, ProviderConfig
    from ..providers.rest import CloudTPUQueuedResourcesClient, GKENodePoolsClient
    from ..runtime import Manager
    from ..runtime.events import Recorder
    from ..runtime.rest import KubeConnection, RestClient

    try:
        cfg = build_config()  # validates before returning
    except ConfigError as e:
        # fail fast with an actionable message (pkg/operator/operator.go:46)
        print(f"error: {e}", file=sys.stderr)
        return 2

    try:
        conn = KubeConnection.in_cluster()
    except Exception:
        try:
            conn = KubeConnection.from_kubeconfig()
        except Exception as e:
            print(f"error: no in-cluster service account and no usable "
                  f"kubeconfig: {e}", file=sys.stderr)
            return 2
    from ..runtime.informer import CachedListClient

    rest = RestClient(conn)
    # Informer-backed reads for the list-heavy kinds: both GC loops re-scan
    # Nodes + NodeClaims every cycle; the cache turns that into watch
    # maintenance instead of repeated full LISTs (the reference reads
    # through controller-runtime's cached client the same way).
    kube = CachedListClient(rest, (Node, NodeClaim))
    kube.add_index(Node, "spec.providerID", lambda o: [o.spec.provider_id])

    from ..providers import rest as gcprest

    cred = new_credential(cfg)
    nodepools = GKENodePoolsClient(
        cred, cfg.project_id, cfg.location, cfg.cluster_name,
        endpoint=cfg.gke_api_endpoint or gcprest.GKE_ENDPOINT)
    queued = CloudTPUQueuedResourcesClient(
        cred, cfg.project_id, cfg.location,
        endpoint=cfg.tpu_api_endpoint or gcprest.TPU_ENDPOINT)
    from ..observability import Tracer, TraceStore, current_ids

    # claimtrace: passive per-claim span tracer (bounded ring buffer,
    # no background tasks) served at /traces on the metrics port
    tracer = trace_store = trace_ids = None
    if opts.tracing_enabled:
        trace_store = TraceStore(max_traces=opts.trace_buffer)
        tracer = Tracer(trace_store)
        trace_ids = current_ids

    # fleetscope: SLO aggregator (trace listener, needs tracing) + flight
    # recorder (probes sink). Both passive; served at /slo and
    # /debugz/bundle on the metrics port.
    fleet = recorder = None
    if opts.fleet_enabled and tracer is not None:
        from ..observability import FleetAggregator, SLOObjective
        fleet = FleetAggregator(
            objectives=(SLOObjective(
                target=opts.slo_target_seconds,
                burn_threshold=opts.slo_fast_burn_threshold),),
            shard=opts.shard_index)
        tracer.add_listener(fleet.on_trace_event)
    if opts.flight_recorder_enabled:
        from ..observability import FlightRecorder
        from ..runtime import probes
        from ..transport import add_breaker_listener
        recorder = FlightRecorder(capacity=opts.recorder_capacity,
                                  bundle_dir=opts.bundle_dir or None)
        probes.add_sink(recorder.probe)
        add_breaker_listener(recorder.breaker_opened)
        if fleet is not None:
            fleet.on_fast_burn = recorder.slo_fast_burn

    from ..runtime.wakehub import WakeHub

    # Event-driven wake graph: every requeue-producing path (tracker LRO
    # completions, Node watch events, stockout parking, status-flush) wakes
    # the lifecycle queue through this hub; requeue_after becomes the
    # safety-net deadline rather than the primary wake-up.
    wakehub = WakeHub()
    provider = InstanceProvider(
        nodepools, kube,
        ProviderConfig(project=cfg.project_id, zone=cfg.location,
                       cluster=cfg.cluster_name),
        queued=queued, tracer=tracer)
    provider.wakehub = wakehub
    from ..providers.operations import OperationTracker

    # Non-blocking provisioning: one background poller multiplexes every
    # in-flight create/delete LRO off a single batched nodepools.list per
    # tick; lifecycle workers are never parked for a slice-create duration.
    tracker = OperationTracker(provider.nodepools, kube,
                               interval=provider.cfg.node_wait_interval)
    provider.tracker = tracker
    cloudprovider = MetricsDecorator(TPUCloudProvider(
        provider, repair_toleration=opts.repair_toleration_seconds))

    from ..controllers.termination import TerminationOptions

    lifecycle = LifecycleOptions(
        liveness_enabled=opts.liveness_enabled,
        launch_timeout=opts.launch_timeout_seconds,
        registration_timeout=opts.registration_timeout_seconds,
        termination_requeue=opts.termination_requeue_seconds)
    from ..controllers.statusbatch import StatusWriteBatcher

    # Status-write coalescing: per-claim meta+status flushes batch over the
    # flush window (latest-wins); fence assigned post-election like the
    # provider's. window <= 0 keeps the legacy synchronous flush.
    status_batcher = None
    if lifecycle.status_flush_window > 0:
        status_batcher = StatusWriteBatcher(
            kube, window=lifecycle.status_flush_window,
            tracer=tracer, wakehub=wakehub)
    controllers, eviction = build_controllers(
        kube, cloudprovider, Recorder(kube, trace_ids=trace_ids),
        lifecycle_options=lifecycle,
        termination_options=TerminationOptions(
            instance_requeue=opts.instance_requeue_seconds),
        gc_options=GCOptions(interval=opts.gc_interval_seconds,
                             leak_grace=opts.gc_leak_grace_seconds),
        health_options=HealthOptions(
            max_unhealthy_fraction=opts.repair_max_unhealthy_fraction,
            breaker_min_unhealthy=opts.repair_breaker_min_unhealthy,
            flap_threshold=opts.repair_flap_threshold,
            flap_window=opts.repair_flap_window_seconds,
            heartbeat_bound=opts.repair_heartbeat_bound_seconds,
            drain_deadline=opts.repair_drain_deadline_seconds,
            repair_rate=opts.repair_rate,
            repair_interval=opts.repair_rate_interval_seconds,
            repair_burst=opts.repair_burst,
            max_concurrent_repairs=opts.repair_max_concurrent),
        max_concurrent_reconciles=opts.max_concurrent_reconciles,
        node_repair=opts.feature_gates.node_repair,
        cluster=cfg.cluster_name,
        shards=opts.shards, shard_index=opts.shard_index,
        tracker=tracker, tracer=tracer,
        wakehub=wakehub, status_batcher=status_batcher)
    manager = Manager(kube).register(*controllers)
    if recorder is not None:
        from ..observability import wire_default_sources
        # diagnostic-bundle sources: live state snapshotted when an anomaly
        # trigger fires (queue depths, inflight LROs, placement memos,
        # recent traces)
        wire_default_sources(recorder, manager=manager, tracker=tracker,
                             placement=provider.placement,
                             trace_store=trace_store)

    stop = asyncio.Event()
    elector = None
    if not opts.disable_leader_election:  # default OFF (options.go:117)
        from ..runtime.leaderelection import LeaderElector
        # per-shard lease: shards are active-active ACROSS indices,
        # active-passive within one (N replicas per shard still fail over)
        lease = ("tpu-provisioner" if opts.shards == 1
                 else f"tpu-provisioner-shard-{opts.shard_index}")
        elector = LeaderElector(kube, lease_name=lease,
                                namespace=conn.namespace,
                                on_lost=stop.set)
        log.info("waiting for leadership",
                 extra={"identity": elector.identity})
        await elector.run_until_leading()
        # Leader fencing: the token captured at acquisition gates every
        # cloud mutation (provider) and every reconcile dequeue
        # (controllers). on_lost→stop tears the process down, but fencing
        # closes the window where reconciles already in flight — or items
        # already dequeued — would keep mutating the cloud while the next
        # leader acts. Nothing has started yet, so assignment here is safe.
        fence = elector.fence()
        provider.fence = fence
        if status_batcher is not None:
            status_batcher.fence = fence
        for c in controllers:
            c.fence = fence

    await kube.start()  # informers sync before the first reconcile
    tracker.start()
    if status_batcher is not None:
        status_batcher.start()
    eviction.start()
    await manager.start()
    runners = await start_servers(manager, opts.metrics_port,
                                  opts.health_probe_port,
                                  opts.enable_profiling,
                                  trace_store=trace_store,
                                  fleet=fleet, recorder=recorder)
    log.info("operator up", extra={"project": cfg.project_id,
                                   "location": cfg.location,
                                   "cluster": cfg.cluster_name})
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix
            pass
    try:
        await stop.wait()
    finally:
        if recorder is not None:
            # detach first: shutdown chatter (hub stops, fence drops) must
            # not land in the ring after the servers stop serving it
            from ..runtime import probes
            from ..transport import remove_breaker_listener
            probes.remove_sink(recorder.probe)
            remove_breaker_listener(recorder.breaker_opened)
        await manager.stop()
        # final drain flushes the last batch before the store goes away;
        # the hub stops after the tracker, whose subscribers call its wake
        if status_batcher is not None:
            await status_batcher.stop()
        await eviction.stop()
        await tracker.stop()
        await wakehub.stop()
        await kube.stop()
        if elector is not None:
            await elector.stop()
        for r in runners:
            await r.cleanup()
        await rest.aclose()
    return 0


def main(argv=None) -> int:
    opts = parse_options(argv)
    setup_logging(opts.log_level)
    if opts.simulate:
        return asyncio.run(run_simulate(opts))
    return asyncio.run(run_real(opts))


if __name__ == "__main__":
    sys.exit(main())
