"""Structured JSON logging (zap analog, operator/logging/logging.go:42-79).

One JSON object per line with level/ts/logger/msg plus any ``extra`` fields —
the same shape the reference's zap production config emits, so log pipelines
built for it keep working.
"""

from __future__ import annotations

import json
import logging
import sys
import time

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warn": logging.WARNING, "warning": logging.WARNING,
           "error": logging.ERROR}

_RESERVED = set(logging.LogRecord("", 0, "", 0, "", (), None).__dict__) | {"message"}


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "level": record.levelname.lower(),
            "ts": round(time.time(), 3),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for k, v in record.__dict__.items():
            if k not in _RESERVED and not k.startswith("_"):
                out[k] = v
        if record.exc_info and record.exc_info[0] is not None:
            out["error"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(level: str = "info", stream=None) -> None:
    # claimtrace correlation: every record emitted under an active span
    # carries trace_id/span_id attrs, which the generic extra-field loop
    # above serializes into the JSON line with no formatter change
    from ..observability import install_log_record_factory
    install_log_record_factory()
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JSONFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(_LEVELS.get(level.lower(), logging.INFO))
    # HTTP wire-level spam drowns the operator's own lines at debug level
    # (200 lines of httpcore per reconcile); these stay at WARNING always.
    for noisy in ("httpcore", "httpx"):
        logging.getLogger(noisy).setLevel(logging.WARNING)
