"""Operator options: flags with env-var fallback (V9 analog).

Mirrors vendor/.../operator/options/options.go:67-141 — notable defaults kept:
leader election DISABLED by default (:117, DISABLE_LEADER_ELECTION=true),
metrics on 8080 (:112), health probes on 8081 (:113), feature gates parsed
from a comma string with NodeRepair defaulting true (:134, chart value).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    node_repair: bool = True


@dataclass
class Options:
    metrics_port: int = 8080
    health_probe_port: int = 8081
    disable_leader_election: bool = True
    enable_profiling: bool = False
    log_level: str = "info"
    feature_gates: FeatureGates = field(default_factory=FeatureGates)
    # lifecycle knobs (SURVEY.md §7 step 5: liveness behind a flag, generous)
    liveness_enabled: bool = True
    launch_timeout_seconds: float = 1800.0
    registration_timeout_seconds: float = 2400.0
    gc_interval_seconds: float = 120.0
    gc_leak_grace_seconds: float = 30.0
    termination_requeue_seconds: float = 5.0   # lifecycle controller.go:246
    instance_requeue_seconds: float = 5.0      # node termination await-instance
    repair_toleration_seconds: float = 600.0   # cloudprovider.go:103-116
    # Cluster repair circuit breaker: skip auto-repair when more than this
    # fraction of managed nodes is unhealthy AND at least
    # repair_breaker_min_unhealthy nodes are unhealthy (so a one-slice
    # fleet can still be repaired). DEFAULT ON (the reference's breaker is
    # commented out at health/controller.go:130-151): one bad rollout or
    # maintenance wave marking many slices unhealthy must not trigger a
    # mass delete of expensive capacity. 0 = off.
    repair_max_unhealthy_fraction: float = 0.5
    repair_breaker_min_unhealthy: int = 3
    # Flap hysteresis: N observed condition transitions inside the window ==
    # unhealthy, even though each individual Ready=False interval is short.
    repair_flap_threshold: int = 5
    repair_flap_window_seconds: float = 600.0
    # Stale-heartbeat repair (lastHeartbeatTime older than bound → kubelet
    # treated as dead even while Ready reads a stale True). 0 = off, the
    # safe default where the node-lifecycle-controller marks silent nodes
    # Unknown; enable on clusters where that signal is missing or slow.
    repair_heartbeat_bound_seconds: float = 0.0
    # Drain-first escalation: cordon + evict with this deadline before the
    # NodeClaim force-delete.
    repair_drain_deadline_seconds: float = 300.0
    # RepairBudget: token bucket (rate per interval, burst cap) + max
    # concurrently-active repairs. Slice-group serialization is always on.
    repair_rate: float = 6.0
    repair_rate_interval_seconds: float = 3600.0
    repair_burst: int = 3
    repair_max_concurrent: int = 2
    # Capacity-aware placement: comma-separated zone candidate list in
    # preference order ("" = single-zone legacy behavior, no fallback walk),
    # the per-zone stockout-memo TTL, and the spot-zone demotion hysteresis
    # (N preemptions inside the window sink the zone to the back of the
    # spot candidate order).
    zones: tuple = ()
    stockout_memo_ttl_seconds: float = 5.0
    spot_demote_threshold: int = 3
    spot_demote_window_seconds: float = 60.0
    max_concurrent_reconciles: int = 64
    # Claim-shard horizontal scaling (controllers/registry.py): run N
    # replicas, each with a distinct SHARD_INDEX; per-claim work partitions
    # by name hash, cluster singletons (GC, slice groups) stay on shard 0,
    # and each shard's leader-election lease is suffixed -shard-{i} so
    # shards are active-active while replicas WITHIN a shard stay
    # active-passive.
    shards: int = 1
    shard_index: int = 0
    # claimtrace (observability/): per-claim lifecycle traces served at
    # /traces on the metrics port. Default on — the tracer is passive
    # (bounded ring buffer, no background tasks).
    tracing_enabled: bool = True
    trace_buffer: int = 512
    # fleetscope (observability/fleet.py + flightrecorder.py): fleet SLO
    # digests served at /slo, anomaly bundles at /debugz/bundle. Default on
    # like tracing — both passive. The SLO objective: time-to-ready p95 ≤
    # slo_target_seconds with multi-window burn alerts (fast 5m / slow 1h).
    fleet_enabled: bool = True
    slo_target_seconds: float = 600.0
    slo_fast_burn_threshold: float = 14.4
    flight_recorder_enabled: bool = True
    recorder_capacity: int = 2048
    # Where anomaly bundles are written ("" = memory only, HTTP serving
    # still works).
    bundle_dir: str = ""
    simulate: bool = False
    simulate_claims: int = 0
    simulate_shape: str = "tpu-v5e-8"


def _env_bool(e, key: str, default: bool) -> bool:
    raw = e.get(key, "").strip().lower()
    return default if raw == "" else raw in ("1", "true", "yes")


def _shard_index_env(e) -> int:
    """SHARD_INDEX with a named failure for the chart's fieldRef source:
    on Kubernetes < 1.28 the apps.kubernetes.io/pod-index label doesn't
    exist and the downward API resolves it to an EMPTY string — int('')
    would crash-loop with a cryptic traceback; name the requirement
    instead."""
    raw = e.get("SHARD_INDEX", "0").strip()
    if "SHARD_INDEX" in e and raw == "":
        raise SystemExit(
            "SHARD_INDEX is set but empty — the chart sources it from the "
            "pod-ordinal label (apps.kubernetes.io/pod-index), which "
            "requires Kubernetes >= 1.28; on older clusters set "
            "SHARD_INDEX explicitly per replica")
    return int(raw or "0")


def parse_feature_gates(raw: str, base: FeatureGates) -> FeatureGates:
    """Parse "NodeRepair=true,Other=false" (options.go:177-204)."""
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        if k.strip() == "NodeRepair":
            base.node_repair = v.strip().lower() == "true"
    return base


def parse_options(argv=None, env=None) -> Options:
    e = env if env is not None else os.environ
    o = Options(
        metrics_port=int(e.get("METRICS_PORT", "8080")),
        health_probe_port=int(e.get("HEALTH_PROBE_PORT", "8081")),
        disable_leader_election=_env_bool(e, "DISABLE_LEADER_ELECTION", True),
        enable_profiling=_env_bool(e, "ENABLE_PROFILING", False),
        log_level=e.get("LOG_LEVEL", "info"),
        liveness_enabled=_env_bool(e, "LIVENESS_ENABLED", True),
        launch_timeout_seconds=float(e.get("LAUNCH_TIMEOUT_SECONDS", "1800")),
        registration_timeout_seconds=float(e.get("REGISTRATION_TIMEOUT_SECONDS", "2400")),
        gc_interval_seconds=float(e.get("GC_INTERVAL_SECONDS", "120")),
        gc_leak_grace_seconds=float(e.get("GC_LEAK_GRACE_SECONDS", "30")),
        termination_requeue_seconds=float(
            e.get("TERMINATION_REQUEUE_SECONDS", "5")),
        instance_requeue_seconds=float(
            e.get("INSTANCE_REQUEUE_SECONDS", "5")),
        repair_toleration_seconds=float(
            e.get("REPAIR_TOLERATION_SECONDS", "600")),
        repair_max_unhealthy_fraction=float(
            e.get("REPAIR_MAX_UNHEALTHY_FRACTION", "0.5")),
        repair_breaker_min_unhealthy=int(
            e.get("REPAIR_BREAKER_MIN_UNHEALTHY", "3")),
        repair_flap_threshold=int(e.get("REPAIR_FLAP_THRESHOLD", "5")),
        repair_flap_window_seconds=float(
            e.get("REPAIR_FLAP_WINDOW_SECONDS", "600")),
        repair_heartbeat_bound_seconds=float(
            e.get("REPAIR_HEARTBEAT_BOUND_SECONDS", "0")),
        repair_drain_deadline_seconds=float(
            e.get("REPAIR_DRAIN_DEADLINE_SECONDS", "300")),
        repair_rate=float(e.get("REPAIR_RATE", "6")),
        repair_rate_interval_seconds=float(
            e.get("REPAIR_RATE_INTERVAL_SECONDS", "3600")),
        repair_burst=int(e.get("REPAIR_BURST", "3")),
        repair_max_concurrent=int(e.get("REPAIR_MAX_CONCURRENT", "2")),
        zones=tuple(z.strip() for z in e.get("ZONES", "").split(",")
                    if z.strip()),
        stockout_memo_ttl_seconds=float(
            e.get("STOCKOUT_MEMO_TTL_SECONDS", "5")),
        spot_demote_threshold=int(e.get("SPOT_DEMOTE_THRESHOLD", "3")),
        spot_demote_window_seconds=float(
            e.get("SPOT_DEMOTE_WINDOW_SECONDS", "60")),
        max_concurrent_reconciles=int(e.get("MAX_CONCURRENT_RECONCILES", "64")),
        shards=int(e.get("SHARDS", "1")),
        shard_index=_shard_index_env(e),
        tracing_enabled=_env_bool(e, "TRACING_ENABLED", True),
        trace_buffer=int(e.get("TRACE_BUFFER", "512")),
        fleet_enabled=_env_bool(e, "FLEET_SLO_ENABLED", True),
        slo_target_seconds=float(e.get("SLO_TARGET_SECONDS", "600")),
        slo_fast_burn_threshold=float(
            e.get("SLO_FAST_BURN_THRESHOLD", "14.4")),
        flight_recorder_enabled=_env_bool(
            e, "FLIGHT_RECORDER_ENABLED", True),
        recorder_capacity=int(e.get("RECORDER_CAPACITY", "2048")),
        bundle_dir=e.get("DEBUG_BUNDLE_DIR", ""),
    )
    o.feature_gates = parse_feature_gates(e.get("FEATURE_GATES", ""), o.feature_gates)

    p = argparse.ArgumentParser(prog="tpu-provisioner")
    p.add_argument("--metrics-port", type=int, default=o.metrics_port)
    p.add_argument("--health-probe-port", type=int, default=o.health_probe_port)
    p.add_argument("--log-level", default=o.log_level)
    p.add_argument("--enable-profiling", action="store_true",
                   default=o.enable_profiling)
    p.add_argument("--feature-gates", default="")
    p.add_argument("--shards", type=int, default=o.shards)
    p.add_argument("--shard-index", type=int, default=o.shard_index)
    p.add_argument("--disable-tracing", action="store_true",
                   default=not o.tracing_enabled,
                   help="turn off claimtrace (per-claim lifecycle traces)")
    p.add_argument("--trace-buffer", type=int, default=o.trace_buffer)
    p.add_argument("--disable-fleet-slo", action="store_true",
                   default=not o.fleet_enabled,
                   help="turn off the fleet SLO aggregator (/slo)")
    p.add_argument("--slo-target-seconds", type=float,
                   default=o.slo_target_seconds,
                   help="time-to-ready p95 objective target")
    p.add_argument("--disable-flight-recorder", action="store_true",
                   default=not o.flight_recorder_enabled,
                   help="turn off the flight recorder (/debugz/bundle)")
    p.add_argument("--debug-bundle-dir", default=o.bundle_dir,
                   help="directory for anomaly bundles ('' = memory only)")
    p.add_argument("--simulate", action="store_true",
                   help="run against the in-process simulated cloud (envtest)")
    p.add_argument("--simulate-claims", type=int, default=0,
                   help="with --simulate: create N NodeClaims, wait Ready, exit")
    p.add_argument("--simulate-shape", default="tpu-v5e-8")
    args = p.parse_args(argv)

    o.metrics_port = args.metrics_port
    o.health_probe_port = args.health_probe_port
    o.log_level = args.log_level
    o.enable_profiling = args.enable_profiling
    o.feature_gates = parse_feature_gates(args.feature_gates, o.feature_gates)
    o.shards = args.shards
    o.shard_index = args.shard_index
    o.tracing_enabled = not args.disable_tracing
    o.trace_buffer = args.trace_buffer
    o.fleet_enabled = not args.disable_fleet_slo
    o.slo_target_seconds = args.slo_target_seconds
    o.flight_recorder_enabled = not args.disable_flight_recorder
    o.bundle_dir = args.debug_bundle_dir
    if not 0 <= o.shard_index < o.shards:
        p.error(f"--shard-index {o.shard_index} outside [0, {o.shards})")
    o.simulate = args.simulate
    o.simulate_claims = args.simulate_claims
    o.simulate_shape = args.simulate_shape
    return o
