"""Profiling endpoints: the pprof-parity subsystem (V9).

The reference exposes Go's pprof suite on the metrics server behind
``EnableProfiling`` (vendor/.../operator/operator.go:185-200): heap, CPU,
goroutine, block. The Python-native equivalents here:

- heap     → ``tracemalloc`` snapshot, top allocation sites by file:line
             (started lazily on first hit so steady-state runs pay nothing)
- profile  → a sampling CPU profiler: a short-lived background thread walks
             ``sys._current_frames()`` at the sampling rate — wall-clock
             sampling like pprof's CPU profile, emitted in collapsed-stack
             format (one ``frame;frame;frame count`` line per distinct
             stack) so it feeds straight into flamegraph tools. Sampling
             must happen off the event-loop thread: a coroutine can only
             ever observe its own frame on its own thread, so an in-loop
             sampler would show nothing but itself.
- tasks    → asyncio task dump with stacks (the goroutine-dump analog;
             wired in server.py)

Sampling instead of tracing (cProfile) keeps the overhead proportional to
the sampling rate, not to the code under observation — safe to hit on a
live controller, which is the whole point of the reference's pprof wiring.
"""

from __future__ import annotations

import asyncio
import linecache
import sys
import threading
import time
import tracemalloc
from collections import Counter

HEAP_TOP = 30
DEFAULT_SECONDS = 5.0
MAX_SECONDS = 60.0
DEFAULT_HZ = 100.0


def heap_snapshot(top: int = HEAP_TOP) -> str:
    """Top allocation sites by retained size. Starts tracemalloc on first
    call — the snapshot covers allocations from that point on, which matches
    how operators use it (hit once to arm, hit again to inspect growth)."""
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return ("tracemalloc armed; allocations are now tracked.\n"
                "Hit this endpoint again to see a snapshot.\n")
    snap = tracemalloc.take_snapshot().filter_traces([
        tracemalloc.Filter(False, tracemalloc.__file__),
        tracemalloc.Filter(False, linecache.__file__),
    ])
    stats = snap.statistics("lineno")
    total = sum(s.size for s in stats)
    lines = [f"heap: {len(stats)} allocation sites, {total / 1024:.1f} KiB traced",
             ""]
    for s in stats[:top]:
        frame = s.traceback[0]
        src = linecache.getline(frame.filename, frame.lineno).strip()
        lines.append(f"{s.size / 1024:9.1f} KiB  {s.count:7d} blocks  "
                     f"{frame.filename}:{frame.lineno}")
        if src:
            lines.append(f"{'':>12}  {src}")
    return "\n".join(lines) + "\n"


def _sample(seconds: float, hz: float,
            stacks: Counter[tuple[str, ...]]) -> int:
    """Runs on a worker thread: periodically snapshot every OTHER thread's
    Python stack (the event-loop thread included — it shows whatever
    reconcile/serialization work holds the GIL at each tick)."""
    me = threading.get_ident()
    interval = 1.0 / max(hz, 1.0)
    deadline = time.monotonic() + seconds
    samples = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({code.co_filename.rsplit('/', 1)[-1]}"
                             f":{f.f_lineno})")
                f = f.f_back
            stacks[tuple(reversed(stack))] += 1
            samples += 1
        time.sleep(interval)
    return samples


async def cpu_profile(seconds: float = DEFAULT_SECONDS,
                      hz: float = DEFAULT_HZ) -> str:
    """Sample all threads for ``seconds`` at ``hz`` and collapse identical
    stacks. The event loop keeps serving while the sampler thread runs."""
    seconds = min(max(seconds, 0.1), MAX_SECONDS)
    stacks: Counter[tuple[str, ...]] = Counter()
    samples = await asyncio.get_running_loop().run_in_executor(
        None, _sample, seconds, hz, stacks)
    lines = [f"# cpu profile: {samples} samples over {seconds:.1f}s "
             f"@ {hz:.0f} Hz (collapsed-stack format)"]
    for stack, count in stacks.most_common():
        lines.append(f"{';'.join(stack)} {count}")
    return "\n".join(lines) + "\n"
