"""Metrics + health-probe HTTP servers (V9: operator.go:157-224).

Metrics on :8080 (/metrics, Prometheus text format), probes on :8081
(/healthz 200 once the process is up — the body reports the
APIHealthGovernor's degraded mode when not HEALTHY; /readyz only after the manager's
watch caches started and required kinds are registered — the analog of the
reference's cache-sync + NodeClaim-CRD-presence readyz, operator.go:207-224).
pprof analog behind --enable-profiling: /debug/tasks dumps live asyncio tasks
with stacks (operator.go:185-200 exposes Go pprof there).

Claimtrace surface (observability/): when a TraceStore is wired, /traces
returns recent trace summaries (``?limit=`` bounds the payload, ``?since=``
filters to traces active after a loop-clock cursor — mega-wave scale makes
both necessary) and /traces/{claim} the full waterfall (JSON;
``?format=text`` renders the plain-text bars).

fleetscope surface (PR 14): /slo serves the fleet aggregator's snapshot
(digest percentiles per placement key, objective burn state) and
/debugz/bundle the flight recorder's anomaly bundles (most recent by
default, ``?trigger=`` for a specific one, ``?list=1`` for all).
"""

from __future__ import annotations

import asyncio
import traceback

import platform

from aiohttp import web
from prometheus_client import Gauge, generate_latest, CONTENT_TYPE_LATEST

from .. import __version__

from ..apis.karpenter import NodeClaim
from ..apis.meta import _KINDS
# imported for its side effect: registers the karpenter_cloudprovider_*
# metric families so /metrics always exposes them, whatever the import order
from ..cloudprovider import metrics as _cloudprovider_metrics  # noqa: F401
from ..controllers.metrics import _get_or_create, update_runtime_gauges
from ..runtime.controller import Manager


# Build-info gauge (operator.go:69-92's karpenter_build_info analog):
# constant 1, stamped with version identifiers for dashboards/alerts.
# Registered at module scope through the shared get-or-create idiom
# (controllers/metrics.py) like every other collector.
BUILD_INFO = _get_or_create(
    Gauge, "tpu_provisioner_build_info",
    "Build/runtime identifiers (constant 1).",
    ["version", "python_version"])
BUILD_INFO.labels(version=__version__,
                  python_version=platform.python_version()).set(1)


def build_apps(manager: Manager, enable_profiling: bool = False,
               trace_store=None, fleet=None, recorder=None):
    metrics = web.Application()

    async def metrics_handler(_req):
        # sample workqueue depth/backlog + circuit-breaker state at scrape
        # time — these live in runtime objects, not prometheus counters
        update_runtime_gauges(manager)
        return web.Response(body=generate_latest(),
                            content_type=CONTENT_TYPE_LATEST.split(";")[0])

    metrics.router.add_get("/metrics", metrics_handler)

    if trace_store is not None:
        from ..observability import render_waterfall

        async def traces_handler(req):
            try:
                # ?limit= is the documented name; ?n= predates it and stays
                # accepted (dashboards already link it)
                n = int(req.query.get("limit", req.query.get("n", "50")))
                since = float(req.query.get("since", "0"))
            except ValueError:
                return web.Response(status=400, text="bad limit/since")
            traces = trace_store.recent(n)
            if since > 0:
                # loop-clock cursor: only traces with activity after it —
                # pair with the summaries' own last_at for incremental polls
                traces = [t for t in traces if t.last_at() > since]
            return web.json_response(
                {"traces": [t.summary() for t in traces]})

        async def trace_handler(req):
            trace = trace_store.get(req.match_info["claim"])
            if trace is None:
                return web.Response(status=404, text="no trace for claim")
            if req.query.get("format") == "text":
                return web.Response(text=render_waterfall(trace))
            return web.json_response(trace.to_dict())

        metrics.router.add_get("/traces", traces_handler)
        metrics.router.add_get("/traces/{claim}", trace_handler)

    if fleet is not None:
        async def slo_handler(_req):
            return web.json_response(fleet.snapshot())

        metrics.router.add_get("/slo", slo_handler)

    if recorder is not None:
        async def bundle_handler(req):
            if req.query.get("list"):
                return web.json_response(
                    {"stats": recorder.stats(),
                     "bundles": recorder.bundles()})
            bundle = recorder.bundle(req.query.get("trigger"))
            if bundle is None:
                return web.Response(status=404, text="no bundle recorded")
            return web.json_response(bundle)

        async def recorder_events_handler(_req):
            return web.json_response({"stats": recorder.stats(),
                                      "events": recorder.events()})

        metrics.router.add_get("/debugz/bundle", bundle_handler)
        metrics.router.add_get("/debugz/events", recorder_events_handler)

    if enable_profiling:
        from . import profiling

        async def tasks_handler(_req):
            lines = []
            for t in asyncio.all_tasks():
                lines.append(f"== {t.get_name()} done={t.done()}")
                for frame in t.get_stack(limit=8):
                    lines.append("".join(traceback.format_stack(frame, limit=1)))
            return web.Response(text="\n".join(lines))

        async def heap_handler(_req):
            return web.Response(text=profiling.heap_snapshot())

        async def profile_handler(req):
            try:
                seconds = float(req.query.get(
                    "seconds", profiling.DEFAULT_SECONDS))
                hz = float(req.query.get("hz", profiling.DEFAULT_HZ))
            except ValueError:
                return web.Response(status=400, text="bad seconds/hz")
            return web.Response(text=await profiling.cpu_profile(seconds, hz))

        # /debug/pprof/* mirrors the reference's route names
        # (operator.go:185-200); /debug/tasks is the goroutine-dump analog
        # kept at its original path.
        metrics.router.add_get("/debug/tasks", tasks_handler)
        metrics.router.add_get("/debug/pprof/goroutine", tasks_handler)
        metrics.router.add_get("/debug/pprof/heap", heap_handler)
        metrics.router.add_get("/debug/pprof/profile", profile_handler)

    health = web.Application()

    async def healthz(_req):
        # Liveness stays 200 even degraded — restarting this process cannot
        # heal a browned-out/partitioned apiserver, and a kubelet kill loop
        # would only add catch-up load. The body carries the worst live
        # governor's degraded-mode line for humans and probes that look.
        from ..runtime import apihealth
        worst = None
        for g in list(apihealth.GOVERNORS):
            if worst is None or g.mode_value() > worst.mode_value():
                worst = g
        if worst is not None and worst.mode() != apihealth.HEALTHY:
            return web.Response(text=worst.healthz_line())
        return web.Response(text="ok")

    async def readyz(_req):
        if not manager.started.is_set():
            return web.Response(status=503, text="manager not started")
        if NodeClaim.KIND not in _KINDS:
            return web.Response(status=503, text="NodeClaim kind not registered")
        return web.Response(text="ok")

    health.router.add_get("/healthz", healthz)
    health.router.add_get("/readyz", readyz)
    return metrics, health


async def start_servers(manager: Manager, metrics_port: int, health_port: int,
                        enable_profiling: bool = False, trace_store=None,
                        fleet=None, recorder=None):
    metrics_app, health_app = build_apps(manager, enable_profiling,
                                         trace_store=trace_store,
                                         fleet=fleet, recorder=recorder)
    runners = []
    for app, port in ((metrics_app, metrics_port), (health_app, health_port)):
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "0.0.0.0", port)
        await site.start()
        runners.append(runner)
    return runners
