"""Shard worker process: one lease-owned slice of the fleet, end to end.

``python -m gpu_provisioner_tpu.operator.shardworker --socket S --identity w0``
boots a full operator stack (controllers, informer cache, workqueues, wake
hub, status batcher, operation tracker — the whole envtest Env) in its OWN
process, against the parent supervisor's store and fake cloud through the
shard IPC socket (runtime/shardipc.py):

- claim ownership comes from the **lease table** (runtime/shardlease.py):
  the worker leases claim ranges through the same (remote) kube client its
  controllers use, targets ``ceil(ranges / target_workers)``, and hands the
  registry the live ``table.owns`` predicate — dequeue fences, map-fn
  filters and the distributed singletons (GC / recovery / slice-group) all
  read the table's current holdings;
- the informer relay is **shared-nothing**: the server filters this
  worker's NodeClaim/Node watch streams and full-scan lists to its leased
  ranges, so the worker caches only its slice of the fleet. Lease handoffs
  arrive as replayed ADDED / synthesized DELETED events;
- wakes for foreign claims are **forwarded, not delivered**: the hub's
  ``route`` hook posts a wake frame and the server re-delivers it to the
  owning worker, carrying the original wake source across the process
  boundary.

This module is operator composition-root code (L5) on the worker side —
the cloud proxies live here, not in runtime/shardipc.py, so the runtime
layer stays cloud-neutral (provgraph PG001).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import logging
import signal
from typing import Optional

from ..apis.serde import from_dict as serde_from_dict, to_dict as serde_to_dict
from ..envtest import Env, EnvtestOptions
from ..observability.fleet import digest_states
from ..providers.gcp import (
    APIError, CompletedOperation, NodePool, QueuedResource,
)
from ..runtime.shardipc import RemoteError, SocketClient
from ..runtime.shardlease import ShardLeaseTable
from ..runtime.wakehub import WAKES

log = logging.getLogger("shardworker")

# Cadence of the cumulative stats snapshot pushed to the supervisor (the
# parent's /metrics fold and the bench's imbalance sampling read these).
SNAP_INTERVAL = 0.2


# ------------------------------------------------------------- cloud proxies

class _RemoteAPI:
    def __init__(self, ipc: SocketClient):
        self._ipc = ipc

    async def _call(self, op: str, **args):
        try:
            return await self._ipc.call(op, **args)
        except RemoteError as e:
            if e.cls_name == "APIError":
                # re-raise the provider taxonomy: code carries 404/409/429
                raise APIError(str(e), code=e.extra.get("code", 500)) \
                    from None
            raise


class RemoteNodePoolsAPI(_RemoteAPI):
    """The 4-method NodePoolsAPI seam over the shard socket. ``begin_*``
    execute on the server (the fake cloud's server-side LRO ledger keeps
    driving them whether or not this worker survives) and return an
    already-complete operation — workers run the non-blocking tracker path
    (``blocking_create=False``), which resolves creates/deletes against
    batched ``list()`` polls, never against the returned operation."""

    async def begin_create(self, pool: NodePool):
        await self._call("cloud.np.begin_create", pool=pool.to_dict())
        return CompletedOperation(None)

    async def get(self, name: str) -> NodePool:
        return NodePool.from_dict(await self._call("cloud.np.get", name=name))

    async def begin_delete(self, name: str):
        await self._call("cloud.np.begin_delete", name=name)
        return CompletedOperation(None)

    async def list(self) -> list[NodePool]:
        return [NodePool.from_dict(d)
                for d in await self._call("cloud.np.list")]


class RemoteQueuedResourcesAPI(_RemoteAPI):
    async def create(self, qr: QueuedResource) -> QueuedResource:
        return serde_from_dict(QueuedResource, await self._call(
            "cloud.qr.create", qr=serde_to_dict(qr)))

    async def get(self, name: str) -> QueuedResource:
        return serde_from_dict(
            QueuedResource, await self._call("cloud.qr.get", name=name))

    async def delete(self, name: str) -> None:
        await self._call("cloud.qr.delete", name=name)

    async def list(self) -> list[QueuedResource]:
        return [serde_from_dict(QueuedResource, d)
                for d in await self._call("cloud.qr.list")]


class RemoteCloud:
    """Duck-typed FakeCloud stand-in: just the two API seams the provider
    stack consumes. No chaos — fault injection stays parent-side, where the
    real cloud state lives."""

    def __init__(self, ipc: SocketClient):
        self.nodepools = RemoteNodePoolsAPI(ipc)
        self.queuedresources = RemoteQueuedResourcesAPI(ipc)
        self.chaos = None


# ---------------------------------------------------------------- the worker

def _build_options(overrides: Optional[dict]) -> EnvtestOptions:
    opts = EnvtestOptions()
    # worker-process defaults: informer ON (the relay feeds it), runtime
    # detectors OFF (a subprocess sharing one contended host with N siblings
    # trips wall-clock stall sentinels on scheduler noise, not loop abuse)
    opts.use_informer = True
    opts.stall_budget = 0.0
    opts.leak_check = False
    opts.flight_recorder = False
    for key, value in (overrides or {}).items():
        # dotted keys reach nested option dataclasses over the JSON seam:
        # "lifecycle.status_flush_window" → opts.lifecycle.status_flush_window
        target, *path, leaf = [opts, *key.split(".")]
        for part in path:
            target = getattr(target, part, None)
            if target is None:
                raise SystemExit(f"unknown EnvtestOptions path {key!r}")
        if not hasattr(target, leaf):
            raise SystemExit(f"unknown EnvtestOptions field {key!r}")
        setattr(target, leaf, value)
    return opts


def snapshot(env: Env, table: ShardLeaseTable) -> dict:
    """The cumulative stats frame pushed to the supervisor: wake ledger,
    queue depths, fleet digest states, lease + batcher counters. Everything
    cumulative-or-gauge so a re-delivered snapshot never double-counts."""
    controllers = env.manager.controllers
    data = {
        "wakes": dict(WAKES),
        "depths": {c.name: c.queue.depth() for c in controllers},
        "hub": {"delivered": env.wakehub.delivered_total,
                "forwarded": env.wakehub.forwarded_total},
        "disowned": {c.name: c.disowned_total for c in controllers
                     if getattr(c, "disowned_total", 0)},
        "lease": {"ranges": sorted(table.ranges),
                  "acquired": table.acquired_total,
                  "released": table.released_total,
                  "adopted": table.adopted_total},
        "fleet": digest_states(),
    }
    if env.status_batcher is not None:
        data["batcher"] = {"submitted": env.status_batcher.submitted,
                           "coalesced": env.status_batcher.coalesced}
    return data


async def run_worker(socket_path: str, identity: str, target: int,
                     overrides: Optional[dict] = None,
                     lease_duration: Optional[float] = None,
                     renew_interval: Optional[float] = None) -> None:
    client = await SocketClient.connect(socket_path, identity=identity)
    lease_kw = {}
    if lease_duration is not None:
        lease_kw["lease_duration"] = lease_duration
    if renew_interval is not None:
        lease_kw["renew_interval"] = renew_interval
    table = ShardLeaseTable(
        client, identity=identity, target_workers=target,
        on_change=lambda gained, lost: client.send_ranges(table.ranges),
        **lease_kw)
    # boot order matters: acquire leases and announce the range set FIRST,
    # so the informer's initial lists/watch replays (opened by Env startup
    # below) are filtered to this worker's slice from the first event
    await table.start()
    client.send_ranges(table.ranges)

    opts = _build_options(overrides)
    opts.owns_fn = table.owns
    opts.distribute_singletons = True
    opts.shards, opts.shard_index = 1, 0
    env = Env(opts, client=client, cloud=RemoteCloud(client))

    def route(name: str, source: str) -> bool:
        if table.owns(name):
            return False  # ours: deliver locally
        client.send_wake(name, source)
        return True
    env.wakehub.route = route

    stop = asyncio.Event()
    client.on_wake = lambda name, source: env.wakehub.wake_after(
        name, 0.0, source)
    client.on_target = table.set_target_workers
    client.on_stop = stop.set
    loop = asyncio.get_event_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        with contextlib.suppress(NotImplementedError):
            loop.add_signal_handler(sig, stop.set)

    async with env:
        log.info("worker %s up: %d ranges", identity, len(table.ranges))
        while not stop.is_set():
            client.send_snap(snapshot(env, table))
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(stop.wait(), timeout=SNAP_INTERVAL)
    # graceful exit: final cumulative snapshot, release leases so peers
    # adopt without waiting out the expiry, then drop the pipe
    client.send_snap(snapshot(env, table))
    await table.stop(release=True)
    await client.close()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="shard worker process")
    p.add_argument("--socket", required=True)
    p.add_argument("--identity", required=True)
    p.add_argument("--target", type=int, default=1,
                   help="initial worker-count target (fair-share divisor)")
    p.add_argument("--opts", default=None,
                   help="JSON dict of scalar EnvtestOptions overrides")
    p.add_argument("--lease-duration", type=float, default=None)
    p.add_argument("--renew-interval", type=float, default=None)
    p.add_argument("--log-level", default="WARNING")
    args = p.parse_args(argv)
    logging.basicConfig(level=args.log_level)
    overrides = json.loads(args.opts) if args.opts else None
    asyncio.run(run_worker(args.socket, args.identity, args.target,
                           overrides=overrides,
                           lease_duration=args.lease_duration,
                           renew_interval=args.renew_interval))


if __name__ == "__main__":
    main()
