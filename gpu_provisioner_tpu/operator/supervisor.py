"""ShardSupervisor: lease-owned worker processes over one store + cloud.

The parent process owns the authoritative state (the in-memory kube store
and the fake cloud's ledgers) and serves it over the shard IPC socket
(runtime/shardipc.py). Each shard is a real OS process
(operator/shardworker.py) running its own event loop, workqueues, wake hub
and informer cache over its **leased claim ranges** — breaking the
single-event-loop ceiling the in-process shard benches hit (BENCH_pr11:
10k-claim wall RISES with in-process shard count because every shard's
controllers contend for one loop).

Scaling is lease handoff, not restart: ``scale(n)`` pushes the new target
to every worker; over-share workers release ranges, under-share workers
acquire them, and nothing stops. A SIGKILLed worker's ranges expire and are
adopted by survivors (``kill()`` exists precisely so tests can prove that).

The supervisor also aggregates worker observability: each worker pushes a
cumulative stats snapshot (wake ledger, queue depths, fleet digest states)
every ``shardworker.SNAP_INTERVAL``; the /metrics scrape folds those via
the ``shardipc.SERVERS`` registry, and the supervisor's
:class:`~..observability.fleet.FleetMirror` merges worker latency digests
into the fleet SLO export.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
import tempfile
from pathlib import Path
from typing import Optional

from ..apis.core import Node
from ..apis.serde import from_dict as serde_from_dict, to_dict as serde_to_dict
from ..observability.fleet import FleetMirror
from ..providers.gcp import NodePool, QueuedResource
from ..runtime.shardipc import ShardIPCServer
from ..runtime.shardlease import NUM_RANGES

log = logging.getLogger("supervisor")


def cloud_ops(cloud) -> dict:
    """The ``cloud.*`` verb table served to workers: thin codecs over the
    parent's fake cloud APIs. ``begin_*`` drop the returned operation — the
    fake's server-side LRO ledger keeps driving it, and workers resolve
    outcomes from tracker-batched ``list`` polls (which also settle overdue
    operations on every call, crash-restart realism included)."""
    np, qr = cloud.nodepools, cloud.queuedresources

    async def np_begin_create(a):
        await np.begin_create(NodePool.from_dict(a["pool"]))
        return None

    async def np_get(a):
        return (await np.get(a["name"])).to_dict()

    async def np_begin_delete(a):
        await np.begin_delete(a["name"])
        return None

    async def np_list(a):
        return [p.to_dict() for p in await np.list()]

    async def qr_create(a):
        created = await qr.create(serde_from_dict(QueuedResource, a["qr"]))
        return serde_to_dict(created)

    async def qr_get(a):
        return serde_to_dict(await qr.get(a["name"]))

    async def qr_delete(a):
        await qr.delete(a["name"])
        return None

    async def qr_list(a):
        return [serde_to_dict(q) for q in await qr.list()]

    return {
        "cloud.np.begin_create": np_begin_create,
        "cloud.np.get": np_get,
        "cloud.np.begin_delete": np_begin_delete,
        "cloud.np.list": np_list,
        "cloud.qr.create": qr_create,
        "cloud.qr.get": qr_get,
        "cloud.qr.delete": qr_delete,
        "cloud.qr.list": qr_list,
    }


class ShardSupervisor:
    """Spawns, scales and reaps shard worker processes.

    ``worker_opts`` is a dict of scalar EnvtestOptions overrides shipped to
    every worker (timing knobs — the cloud itself lives parent-side).
    ``lease_duration``/``renew_interval`` tune the ownership table's expiry
    window (how long a SIGKILLed worker's ranges stay orphaned).
    """

    def __init__(self, client, cloud,
                 worker_opts: Optional[dict] = None,
                 num_ranges: int = NUM_RANGES,
                 lease_duration: Optional[float] = None,
                 renew_interval: Optional[float] = None,
                 socket_path: Optional[str] = None):
        self.client = client
        self.cloud = cloud
        self.worker_opts = dict(worker_opts or {})
        self.num_ranges = num_ranges
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.socket_path = socket_path
        self._tmpdir: Optional[tempfile.TemporaryDirectory] = None
        self.server = ShardIPCServer(client, num_ranges=num_ranges,
                                     extra_ops=cloud_ops(cloud))
        self.server.on_snap = self._on_snap
        # parent-side stand-in for worker aggregators in the SLO export
        self.mirror = FleetMirror()
        self.procs: dict[str, asyncio.subprocess.Process] = {}
        self.target = 0
        self._spawned = 0
        # index lists (spec.providerID lookups) arrive over IPC and execute
        # against the parent store — register the index the way Env does
        store = getattr(client, "store", None)
        if store is not None:
            store.add_index(Node, "spec.providerID",
                            lambda o: [o.spec.provider_id])

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self.socket_path is None:
            self._tmpdir = tempfile.TemporaryDirectory(prefix="shardipc-")
            self.socket_path = os.path.join(self._tmpdir.name, "shard.sock")
        await self.server.start(self.socket_path)

    async def stop(self, timeout: float = 10.0) -> None:
        self.server.broadcast_stop()
        for ident, proc in list(self.procs.items()):
            try:
                await asyncio.wait_for(proc.wait(), timeout=timeout)
            except asyncio.TimeoutError:
                log.warning("worker %s ignored stop; killing", ident)
                with _suppress_proc_errors():
                    proc.kill()
                await proc.wait()
        self.procs.clear()
        await self.server.stop()
        if self._tmpdir is not None:
            self._tmpdir.cleanup()
            self._tmpdir = None

    # -------------------------------------------------------------- scaling
    async def spawn(self, n: int) -> None:
        """Bring the fleet to ``n`` workers (initial launch or scale-up)."""
        await self.scale(n)

    async def scale(self, n: int) -> None:
        """Rebalance to ``n`` workers WITHOUT a stop: new workers acquire
        released/free ranges; on shrink, retired workers release their
        leases on the way out and survivors pick them up."""
        self.target = n
        while len(self.procs) < n:
            await self._spawn_worker()
        excess = sorted(self.procs)[n:]
        for ident in excess:
            self._stop_worker(ident)
        for ident in excess:
            proc = self.procs.pop(ident)
            try:
                await asyncio.wait_for(proc.wait(), timeout=10.0)
            except asyncio.TimeoutError:
                with _suppress_proc_errors():
                    proc.kill()
                await proc.wait()
            self.server.snapshots.pop(ident, None)
        self.server.broadcast_target(max(1, n))

    async def _spawn_worker(self) -> None:
        ident = f"w{self._spawned}"
        self._spawned += 1
        pkg_root = Path(__file__).resolve().parents[2]
        env = dict(os.environ)
        env["PYTHONPATH"] = (str(pkg_root) + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env.setdefault("JAX_PLATFORMS", "cpu")
        cmd = [sys.executable, "-m",
               "gpu_provisioner_tpu.operator.shardworker",
               "--socket", self.socket_path, "--identity", ident,
               "--target", str(max(1, self.target))]
        if self.worker_opts:
            cmd += ["--opts", json.dumps(self.worker_opts)]
        if self.lease_duration is not None:
            cmd += ["--lease-duration", str(self.lease_duration)]
        if self.renew_interval is not None:
            cmd += ["--renew-interval", str(self.renew_interval)]
        self.procs[ident] = await asyncio.create_subprocess_exec(
            *cmd, env=env)

    def _stop_worker(self, ident: str) -> None:
        for conn in self.server.conns:
            if conn.worker == ident:
                conn.post({"push": "stop"})
                return
        # never connected (or already gone): signal the process directly
        proc = self.procs.get(ident)
        if proc is not None:
            with _suppress_proc_errors():
                proc.send_signal(signal.SIGTERM)

    def kill(self, ident: str, sig: int = signal.SIGKILL) -> None:
        """Hard-kill a worker (crash-matrix harness): no lease release, no
        final snapshot — its ranges expire and survivors adopt them."""
        proc = self.procs.get(ident)
        if proc is None:
            raise KeyError(f"no worker {ident!r}")
        with _suppress_proc_errors():
            proc.send_signal(sig)

    async def reap(self, ident: str, timeout: float = 10.0) -> None:
        """Collect a dead worker and shrink the fair-share target so the
        survivors' next lease tick adopts its expired ranges."""
        proc = self.procs.pop(ident, None)
        if proc is not None:
            await asyncio.wait_for(proc.wait(), timeout=timeout)
        self.server.snapshots.pop(ident, None)
        self.target = max(1, len(self.procs))
        self.server.broadcast_target(self.target)

    # ---------------------------------------------------------- introspection
    async def wait_covered(self, timeout: float = 30.0,
                           workers: Optional[int] = None) -> None:
        """Block until every claim range is leased by a live connection
        (and, optionally, at least ``workers`` connections exist) — the
        boot/rebalance barrier tests and the bench sit on."""
        deadline = asyncio.get_event_loop().time() + timeout
        want = set(range(self.num_ranges))
        while True:
            held: set[int] = set()
            for conn in self.server.conns:
                held |= conn.ranges
            if held >= want and (workers is None
                                 or len(self.server.conns) >= workers):
                return
            if asyncio.get_event_loop().time() > deadline:
                missing = sorted(want - held)
                raise TimeoutError(
                    f"ranges uncovered after {timeout}s: {missing[:8]}"
                    f"{'...' if len(missing) > 8 else ''} "
                    f"({len(self.server.conns)} workers connected)")
            await asyncio.sleep(0.05)

    def snapshots(self) -> dict[str, dict]:
        return dict(self.server.snapshots)

    def _on_snap(self, worker: str, data: dict) -> None:
        self.mirror.load([s.get("fleet") for s in
                          self.server.snapshots.values()])


class _suppress_proc_errors:
    """ProcessLookupError-tolerant signal delivery (the worker may have
    exited between our bookkeeping and the signal)."""

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return exc_type is not None and issubclass(exc_type,
                                                   ProcessLookupError)
