"""Pallas TPU kernels for the workload's hot ops."""

from .flash_attention import flash_attention

__all__ = ["flash_attention"]
