"""Flash attention as a Pallas TPU kernel.

The hot op of the flagship model, written for the hardware (per
/opt/skills/guides/pallas_guide.md): the S×S score matrix never
materializes, all matmuls hit the MXU with fp32 accumulation, and two
variants trade HBM traffic against VMEM:

- **resident** (K/V ≤ RESIDENT_KV_BUDGET in VMEM): one K/V DMA per
  (batch·head, q-block) grid cell, inner fori_loop over tiles with the
  causal loop bound pruned — fastest at short/medium S;
- **streaming** (longer S): grid = (batch·head, q-blocks, kv-blocks), one
  (block_k, D) K/V tile per grid step with the flash running-max/
  denominator in VMEM scratch across the kv dimension — VMEM use is
  O(block), independent of S, so 32k+ context runs where the dense path
  cannot even compile.

GQA costs no memory: the KV BlockSpec index_map points q-head ``bh`` at
kv-head ``bh // group`` — no repeat materialization.

Backward pass: FlashAttention-2-style per-block recompute Pallas kernels
(no S×S materialization, so training memory is O(S·D) like the forward):
the forward also emits the per-row logsumexp L, the backward precomputes
Δ = rowsum(dO∘O) and runs two passes — a dQ kernel (grid over q-blocks,
accumulating over kv-blocks in VMEM scratch) and a dK/dV kernel (grid over
kv-blocks, accumulating over q-blocks), each rebuilding P = exp(S−L) from
the tiles. GQA folds the per-q-head dK/dV back onto kv-heads outside the
kernel.

Falls back to the lax dense path when S doesn't tile into the (aligned)
block sizes; ``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring import dense_attention_with_lse

NEG_INF = -1.0e30
# Block-size sweep on v5e (batch 4-8, D=128, bf16, causal): 128×128 leaves
# 3× on the table; 512×512 is at/near the optimum from S=2048 through 16k
# for both forward and backward (S=16k forward prefers 512×1024 by ~10%,
# not worth a shape-dependent default). Callers can still override.
DEFAULT_BLOCK = 512


def _auto_block(S: int, requested) -> int:
    """Largest hardware-aligned block ≤ DEFAULT_BLOCK that tiles S, so short
    sequences stay on the kernel instead of silently falling back to dense."""
    if requested is not None:
        return requested
    b = min(DEFAULT_BLOCK, S)
    while b >= 128:
        if S % b == 0:
            return b
        b //= 2
    return DEFAULT_BLOCK  # won't tile; flash_attention falls back to dense


# K+V bytes (in input dtype) we allow resident in VMEM before switching to
# the streaming grid: bf16 S·D ≤ 6MB/2/2 → e.g. S=12288 @ D=128 still resident.
RESIDENT_KV_BUDGET = 6 * 1024 * 1024


def _kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_k,
                     seq_len, scale, causal, window=None):
    """Whole-K/V-in-VMEM variant: one DMA of K/V per (bh, q-block), inner
    fori_loop over tiles. Fastest at short/medium S (fewer HBM round trips,
    causal loop-bound pruning); VMEM-bounded, so only used under budget.
    ``window``: the loop's LOWER bound prunes to the window band too."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                      # [BQ, D]
    if causal:
        n_blocks = (qi * block_q + block_q - 1) // block_k + 1
    else:
        n_blocks = seq_len // block_k
    lo_blocks = 0
    if window is not None:
        lo_blocks = jnp.maximum(qi * block_q - window + 1, 0) // block_k
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal or window is not None:
            kv_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            keep = jnp.ones(s.shape, jnp.bool_)
            if causal:
                keep = q_pos >= kv_pos
            if window is not None:
                keep = keep & (kv_pos > q_pos - window)
            s = jnp.where(keep, s, NEG_INF)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(lo_blocks, n_blocks, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)), NEG_INF)
    lse_ref[0] = lse                                      # [BQ, 1]


def _online_softmax_step(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                         q_pos0, kv_pos0, block_q, block_k, scale, masked,
                         window=None):
    """One flash tile from refs — see _online_softmax_tile."""
    _online_softmax_tile(
        q_ref[0].astype(jnp.float32), k_ref[0].astype(jnp.float32),
        v_ref[0].astype(jnp.float32), acc_ref, m_ref, l_ref,
        q_pos0=q_pos0, kv_pos0=kv_pos0, block_q=block_q, block_k=block_k,
        scale=scale, masked=masked, window=window)


def _online_softmax_tile(q, k, v, acc_ref, m_ref, l_ref, *,
                         q_pos0, kv_pos0, block_q, block_k, scale, masked,
                         kv_min=None, window=None, sink_hi=None):
    """One flash tile: S = qKᵀ·scale (masked below q_pos0+i ≥ kv_pos0+j when
    ``masked``; additionally below ``kv_min`` ≤ kv_pos0+j when given — the
    left-pad lower bound of ragged serving — and within the sliding
    ``window`` when given: kv_pos > q_pos − window, OR'd with the
    attention-sink range kv_pos < ``sink_hi`` when given), then the
    running-max/denominator update into VMEM scratch. Shared by the
    streaming self-attention and KV-cache kernels (incl. the int8 variant,
    which dequantizes before calling) so numerics fixes land in one place.
    q/k/v are f32 tile VALUES [BQ|BK, D]."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [BQ, BK]
    if masked or kv_min is not None or window is not None:
        kv_pos = kv_pos0 + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        q_pos = q_pos0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        keep = jnp.ones(s.shape, jnp.bool_)
        if masked:
            keep = q_pos >= kv_pos
        if kv_min is not None:
            keep = keep & (kv_pos >= kv_min)
        if window is not None:
            wkeep = kv_pos > q_pos - window
            if sink_hi is not None:
                wkeep = wkeep | (kv_pos < sink_hi)
            keep = keep & wkeep
        s = jnp.where(keep, s, NEG_INF)
    _online_update(s, v, acc_ref, m_ref, l_ref)


def _online_update(s, v, acc_ref, m_ref, l_ref):
    """Running-max/denominator update from an already-masked score tile —
    the numerics core shared by every forward kernel (self-attention,
    KV-cache prefill, and the decode-step kernel, whose row-uniform mask
    doesn't fit _online_softmax_tile's per-row iota)."""
    m_prev, l_prev = m_ref[:], l_ref[:]
    m_blk = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    p = jnp.exp(s - m_new)
    p = jnp.where(m_new > NEG_INF / 2, p, 0.0)        # fully-masked rows
    corr = jnp.exp(m_prev - m_new)
    m_ref[:] = m_new
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _init_softmax_scratch(acc_ref, m_ref, l_ref):
    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)


def _finalize_out(o_ref, acc_ref, m_ref, l_ref, lse_ref=None):
    l = l_ref[:]
    o_ref[0] = (acc_ref[:] / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)
    if lse_ref is not None:
        m = m_ref[:]
        lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)), NEG_INF)
        lse_ref[0] = lse                              # [BQ, 1]


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *,
            block_q, block_k, scale, causal, window=None):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        _init_softmax_scratch(acc_ref, m_ref, l_ref)

    # whole block above the causal diagonal → no compute
    live = (kj * block_k <= qi * block_q + block_q - 1) if causal else True
    if window is not None:
        live = live & ((kj + 1) * block_k - 1
                       >= qi * block_q - window + 1)

    @pl.when(live)
    def _step():
        _online_softmax_step(
            q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
            q_pos0=qi * block_q, kv_pos0=kj * block_k,
            block_q=block_q, block_k=block_k, scale=scale, masked=causal,
            window=window)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        _finalize_out(o_ref, acc_ref, m_ref, l_ref, lse_ref)


def _heads_to_rows(x):
    """[B, S, H, D] → [B*H, S, D] so each grid cell owns one head's sequence."""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _rows_to_heads(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _causal_kv_index(block_q, block_k, group, causal, *,
                     prefetch_start=False, pad_hq=None, window=None,
                     sinks=0):
    """kv-side index map for (bh, qi, kj) grids. Under causal masking the
    blocks past the diagonal are clamped to the last live block so the block
    index repeats across the dead tail of the kj loop and the Pallas
    pipeline skips the DMA (a revisited block is not re-fetched).
    ``prefetch_start``: the KV-cache variant, where the diagonal sits at a
    dynamic offset carried by a scalar-prefetch ref (extra trailing arg).
    ``pad_hq``: left-padded ragged batches — the prefetch ref additionally
    carries per-row pad lengths at [1 + bh // pad_hq], and leading all-pad
    blocks clamp UP to the first live block (their DMA elides too).
    ``window``: sliding-window attention — blocks entirely below the
    window's lower edge likewise clamp up and never fetch."""
    if prefetch_start:
        def idx(bh, qi, kj, meta_ref, g=group):
            last = (meta_ref[0] + qi * block_q + block_q - 1) // block_k
            lo_pos = None
            if pad_hq is not None:
                lo_pos = meta_ref[1 + bh // pad_hq]
            if window is not None:
                wlo = jnp.maximum(
                    meta_ref[0] + qi * block_q - window + 1, 0)
                lo_pos = wlo if lo_pos is None else jnp.maximum(lo_pos, wlo)
            if window is not None and sinks:
                # two live ranges: the sink blocks walk at identity, the
                # dead middle clamps forward to the window's first block
                # (consecutive repeats → single fetch)
                pad = meta_ref[1 + bh // pad_hq] if pad_hq is not None else 0
                sink_first = pad // block_k
                sink_last = jnp.minimum((pad + sinks - 1) // block_k, last)
                win_idx = jnp.clip(kj, lo_pos // block_k, last)
                return (bh // g,
                        jnp.where(kj <= sink_last,
                                  jnp.clip(kj, sink_first, sink_last),
                                  win_idx), 0)
            if lo_pos is not None:
                return (bh // g, jnp.clip(kj, lo_pos // block_k, last), 0)
            return (bh // g, jnp.minimum(kj, last), 0)
        return idx
    if not causal:
        return lambda bh, qi, kj, g=group: (bh // g, kj, 0)

    def idx(bh, qi, kj, g=group):
        last = (qi * block_q + block_q - 1) // block_k
        if window is not None:
            first = jnp.maximum(qi * block_q - window + 1, 0) // block_k
            return (bh // g, jnp.clip(kj, first, last), 0)
        return (bh // g, jnp.minimum(kj, last), 0)
    return idx


def _tri_decode(t, n_q):
    """Flattened triangular index → (qi, kj) for the causal lower triangle
    (block_q == block_k): cell t of row qi starts at qi(qi+1)/2. Inverse
    via float sqrt with a ±1 integer correction (exact for any grid that
    fits int32 — sqrt is only a seed, the corrections decide)."""
    del n_q  # shape bookkeeping only; decode is closed-form
    tf = t.astype(jnp.float32)
    qi = jnp.floor((jnp.sqrt(8.0 * tf + 1.0) - 1.0) / 2.0).astype(jnp.int32)
    qi = jnp.where(qi * (qi + 1) // 2 > t, qi - 1, qi)
    qi = jnp.where((qi + 1) * (qi + 2) // 2 <= t, qi + 1, qi)
    kj = t - qi * (qi + 1) // 2
    return qi, kj


def _kernel_tri(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, block, n_q, scale):
    """Causal streaming forward over the FLATTENED lower triangle: the grid
    holds only live (qi, kj) cells, so above-diagonal cells cost nothing at
    all — not even the predicated-off grid steps the rectangular variant
    pays (~half the grid at long S)."""
    t = pl.program_id(1)
    qi, kj = _tri_decode(t, n_q)

    @pl.when(kj == 0)
    def _init():
        _init_softmax_scratch(acc_ref, m_ref, l_ref)

    _online_softmax_step(
        q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
        q_pos0=qi * block, kv_pos0=kj * block,
        block_q=block, block_k=block, scale=scale, masked=True)

    @pl.when(kj == qi)
    def _finalize():
        _finalize_out(o_ref, acc_ref, m_ref, l_ref, lse_ref)


def _causal_q_index(block_q, block_k, causal, window=None):
    """q-side index map for (bh, kj, qi) grids (the dK/dV pass). The dead
    prefix of the qi loop (blocks strictly before the diagonal) is clamped
    UP to the first live block — the same index repeats from step 0 through
    the first live step, so those DMAs are elided too. ``window``: the
    dead TAIL (queries past the kv block's window reach) clamps DOWN
    likewise."""
    if not causal:
        return lambda bh, kj, qi: (bh, qi, 0)

    def idx(bh, kj, qi):
        first = (kj * block_k) // block_q
        if window is not None:
            last = (kj * block_k + block_k - 1 + window - 1) // block_q
            return (bh, jnp.clip(qi, first, last), 0)
        return (bh, jnp.maximum(qi, first), 0)
    return idx


def _flash(q, k, v, causal, scale, block_q, block_k, interpret,
           triangular=False, window=None):
    """Flash forward on flattened heads → (out [B,S,Hq,D], lse [B*Hq, S, 1])."""
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv

    qf, kf, vf = _heads_to_rows(q), _heads_to_rows(k), _heads_to_rows(v)

    # lse rides as [B*Hq, S, 1]: a rank-2 (1, block_q) block violates the
    # TPU tiling rule (last two block dims must divide (8, 128) or equal the
    # array dims); (1, block_q, 1) blocks of the rank-3 shape are legal.
    out_shapes = [jax.ShapeDtypeStruct((B * Hq, S, D), q.dtype),
                  jax.ShapeDtypeStruct((B * Hq, S, 1), jnp.float32)]

    # bh = b*Hq + h → kv row b*Hkv + h//group == bh // group (Hq = Hkv·group)
    kv_bytes = 2 * S * D * jnp.dtype(q.dtype).itemsize
    if kv_bytes <= RESIDENT_KV_BUDGET:
        kernel = functools.partial(
            _kernel_resident, block_q=block_q, block_k=block_k, seq_len=S,
            scale=scale, causal=causal, window=window)
        out, lse = pl.pallas_call(
            kernel,
            grid=(B * Hq, S // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, S, D), lambda bh, qi, g=group: (bh // g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, S, D), lambda bh, qi, g=group: (bh // g, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=out_shapes,
            interpret=interpret,
        )(qf, kf, vf)
        return _rows_to_heads(out, B, Hq), lse

    if causal and triangular and block_q == block_k and window is None:
        # flattened-triangle grid: above-diagonal cells don't exist at all
        # (window stays on the rectangular grids — its clamps express the
        # band directly)
        # (the rectangular variant below predicates them off and elides
        # their DMA, but still pays the grid step)
        n_q = S // block_q
        tri_q = lambda bh, t: (bh, _tri_decode(t, n_q)[0], 0)
        tri_kv = lambda bh, t, g=group: (bh // g, _tri_decode(t, n_q)[1], 0)
        out, lse = pl.pallas_call(
            functools.partial(_kernel_tri, block=block_q, n_q=n_q,
                              scale=scale),
            grid=(B * Hq, n_q * (n_q + 1) // 2),
            in_specs=[
                pl.BlockSpec((1, block_q, D), tri_q,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, D), tri_kv,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_k, D), tri_kv,
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), tri_q,
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, block_q, 1), tri_q,
                             memory_space=pltpu.VMEM),
            ],
            out_shape=out_shapes,
            scratch_shapes=[
                pltpu.VMEM((block_q, D), jnp.float32),   # acc
                pltpu.VMEM((block_q, 1), jnp.float32),   # running max
                pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
            ],
            interpret=interpret,
        )(qf, kf, vf)
        return _rows_to_heads(out, B, Hq), lse

    kernel = functools.partial(
        _kernel, block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        window=window)
    # Causal: kv blocks above the diagonal are dead. Clamping their index to
    # the last live block makes the index map constant across the dead tail
    # of the kj loop, so the pipeline elides the re-fetch — fully-masked
    # blocks cost neither compute (the `live` gate in the kernel) nor HBM
    # traffic (this clamp). At long S that halves K/V read traffic.
    kv_idx = _causal_kv_index(block_q, block_k, group, causal,
                              window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * Hq, S // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), kv_idx, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), kv_idx, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, kj: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _rows_to_heads(out, B, Hq), lse


# --- KV-cache (serving) forward --------------------------------------------

def _kernel_cached(start_ref, q_ref, k_ref, v_ref, *rest, block_q, block_k,
                   scale, int8, Hq=None, padded=False, window=None,
                   sinks=0):
    """Streaming flash where the query block sits at cache positions
    ``start + qi·BQ ..`` against a [max_len]-wide KV cache. ``start`` is a
    traced scalar riding as a scalar-prefetch argument so both the mask and
    the kv index map see it. A key block is live iff its first position is
    ≤ the query block's last position — everything past the causal frontier
    (which also bounds the written prefix, since the new tokens' keys are
    written before scoring — models/decode.py cached_forward) is neither
    computed nor fetched.

    ``int8``: k/v arrive quantized with per-token scale refs trailing them
    (models/decode.py int8 cache) — tiles dequantize in VMEM, so only the
    int8 buffers travel over HBM (the bandwidth win is the point).

    ``padded``: the prefetch ref is [start, pad_0..pad_B-1]; row b's keys
    below pad_b are masked and leading all-pad blocks are skipped (their
    DMA elided by the index-map clamp). Pad-QUERY rows (position < pad_b)
    end up fully masked and emit ZERO — the dense path emits a uniform
    V-average there instead; both are unread garbage (only real positions'
    logits are consumed), but exact-comparison tests must skip pad rows."""
    if int8:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)
    start = start_ref[0]
    pad = start_ref[1 + pl.program_id(0) // Hq] if padded else 0

    @pl.when(kj == 0)
    def _init():
        _init_softmax_scratch(acc_ref, m_ref, l_ref)

    live = kj * block_k <= start + qi * block_q + block_q - 1
    if padded:
        live = live & ((kj + 1) * block_k - 1 >= pad)
    if window is not None:
        # the union of row windows is (qmin − window, qmax]; a kv block is
        # dead when it sits entirely at/below the earliest row's lower edge
        win_live = ((kj + 1) * block_k - 1
                    >= start + qi * block_q - window + 1)
        if sinks:
            # ...unless it overlaps the sink range [pad, pad+sinks)
            win_live = win_live | (kj * block_k <= pad + sinks - 1)
        live = live & win_live

    @pl.when(live)
    def _step():
        if int8:
            k = k_ref[0].astype(jnp.float32) * ks_ref[0]
            v = v_ref[0].astype(jnp.float32) * vs_ref[0]
        else:
            k = k_ref[0].astype(jnp.float32)
            v = v_ref[0].astype(jnp.float32)
        _online_softmax_tile(
            q_ref[0].astype(jnp.float32), k, v, acc_ref, m_ref, l_ref,
            q_pos0=start + qi * block_q, kv_pos0=kj * block_k,
            block_q=block_q, block_k=block_k, scale=scale, masked=True,
            kv_min=pad if padded else None, window=window,
            sink_hi=(pad + sinks) if (window is not None and sinks)
            else None)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        _finalize_out(o_ref, acc_ref, m_ref, l_ref)


def cached_flash_supported(S: int, max_len: int, Hq: int, Hkv: int,
                           block_q: int = None, block_k: int = None) -> bool:
    """True iff flash_attention_cached can take these shapes (S and max_len
    tile into ≥128-aligned blocks, GQA divides). S=1 decode steps return
    False (they take flash_attention_decode); raggedness does NOT gate the
    kernel — left-padded batches ride in via pad_lens."""
    bq = _auto_block(S, block_q)
    bk = _auto_block(max_len, block_k)
    return (S % bq == 0 and max_len % bk == 0 and Hq % Hkv == 0
            and bq >= 128 and bk >= 128)


def flash_attention_cached(q, k_cache, v_cache, start, *, scale: float = None,
                           block_q: int = None, block_k: int = None,
                           interpret: bool = None,
                           k_scale=None, v_scale=None, pad_lens=None,
                           window: int = None, sinks: int = 0):
    """Flash attention of fresh-token queries against a KV cache — the
    serving prefill-continuation path (forward-only, no VJP; decode never
    differentiates). Replaces the dense S×max_len masked sweep of
    models/decode.py:_cached_attention when shapes tile.

    q: [B, S, Hq, D] queries at cache positions start..start+S-1;
    k_cache/v_cache: [B, Hkv, max_len, D] HEAD-MAJOR (models/decode.py's
    cache layout — each head's sequence contiguous, so the kernel's
    [B·Hkv, max_len, D] view is a free reshape; a token-major cache would
    force a transposed HBM copy of the whole cache per call, costing
    O(max_len) where this path is meant to cost O(written prefix)) with
    positions start..start+S-1 already written; ``start``: traced int32
    scalar. Returns [B, S, Hq, D]. Callers must gate on
    cached_flash_supported().

    ``k_scale``/``v_scale`` [B, Hkv, max_len, 1] f32: int8-cache mode —
    k_cache/v_cache are int8 and tiles dequantize IN VMEM, so only the
    int8 bytes cross HBM (the quantized cache's bandwidth win carries into
    the kernel instead of falling back to the dense sweep).

    ``pad_lens`` [B] int32: left-padded ragged batches — row b's keys
    below pad_lens[b] are masked in-kernel and leading all-pad blocks are
    never DMA'd. Pad-QUERY rows emit zero (see _kernel_cached); only real
    positions' outputs are meaningful, as in the dense path.

    ``window``: sliding-window attention (Mistral-style) — a query at
    position p attends keys in (p − window, p]. Blocks entirely below a
    q-block's window clamp out of the index map, so long-context SWA
    prefill fetches O(window) of the cache per q-block, not O(start).

    Sharding note: under a tensor-parallel mesh the GSPMD partitioner cannot
    split a pallas_call, so a kv-head-sharded cache is gathered around the
    kernel (results match dense on the 8-device CPU interpret-mode tp=2 test
    mesh; like every kernel here, on-chip lowering must be validated once on
    real TPU — interpret mode can't catch lowering errors). Single-replica
    serving (today's deployment shape) pays nothing; a shard_map'd serving
    wrapper is the follow-up if tp serving at large max_len becomes real."""
    B, S, Hq, D = q.shape
    Hkv, ML = k_cache.shape[1], k_cache.shape[2]
    group = Hq // Hkv
    if scale is None:
        scale = D ** -0.5
    block_q = _auto_block(S, block_q)
    block_k = _auto_block(ML, block_k)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    qf = _heads_to_rows(q)                      # O(S) transpose — tiny
    kf = k_cache.reshape(B * Hkv, ML, D)        # head-major: free reshape
    vf = v_cache.reshape(B * Hkv, ML, D)
    padded = pad_lens is not None
    start_arr = jnp.asarray(start, jnp.int32).reshape(1)
    if padded:
        start_arr = jnp.concatenate([start_arr,
                                     pad_lens.astype(jnp.int32)])

    def q_idx(bh, qi, kj, start_ref):
        return (bh, qi, 0)

    # clamp to the dynamic causal frontier: dead blocks repeat the last
    # live index, so the pipeline elides their DMA
    kv_idx = _causal_kv_index(block_q, block_k, group, True,
                              prefetch_start=True,
                              pad_hq=Hq if padded else None,
                              window=window, sinks=sinks)

    int8 = k_scale is not None
    in_specs = [
        pl.BlockSpec((1, block_q, D), q_idx, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), kv_idx, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), kv_idx, memory_space=pltpu.VMEM),
    ]
    operands = [qf, kf, vf]
    if int8:
        sspec = pl.BlockSpec((1, block_k, 1), kv_idx,
                             memory_space=pltpu.VMEM)
        in_specs += [sspec, sspec]
        operands += [k_scale.reshape(B * Hkv, ML, 1),
                     v_scale.reshape(B * Hkv, ML, 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * Hq, S // block_q, ML // block_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, D), q_idx,
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_cached, block_q=block_q, block_k=block_k,
                          scale=scale, int8=int8, Hq=Hq, padded=padded,
                          window=window, sinks=sinks),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * Hq, S, D), q.dtype),
        interpret=interpret,
    )(start_arr, *operands)
    return _rows_to_heads(out, B, Hq)


# --- KV-cache decode step (S = 1) ------------------------------------------

def _kernel_decode(meta_ref, q_ref, k_ref, v_ref, *rest, Hkv, group, block_k,
                   scale, int8, padded, n_start=1, S=1, window=None,
                   sinks=0):
    """A SHORT query block's attention against the cache: grid row bh owns
    kv head ``bh % Hkv`` of batch ``bh // Hkv`` and computes all ``S``
    query positions × ``group`` GQA queries of that head in one pass — the
    cache tile is fetched once per kv head (the dense sweep and a
    per-q-head grid both read it group× more). ``S`` is 1 for a decode
    step; speculative verify blocks and short continuations use S>1 (query
    i sits at cache position start_b+i, so the causal bound is per query
    row). ``meta_ref`` (SMEM scalar prefetch):
    [start_0..start_{n_start-1}, pad_len_0..pad_len_{B-1}]; ``n_start`` is
    1 (every row at the same ``start`` — the plain serving loop) or B
    (per-row lengths — batched speculative decoding). The mask per q-row:
    pad_len ≤ key position ≤ start_b + s_row. Blocks outside every row's
    window are neither computed (the ``live`` gate) nor fetched (the
    clamped index map)."""
    if int8:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    kj = pl.program_id(1)
    n_kv = pl.num_programs(1)
    b = pl.program_id(0) // Hkv
    start = meta_ref[b] if n_start > 1 else meta_ref[0]
    pad = meta_ref[n_start + b] if padded else 0

    @pl.when(kj == 0)
    def _init():
        _init_softmax_scratch(acc_ref, m_ref, l_ref)

    live = kj * block_k <= start + (S - 1)    # any query row reaches it
    if padded:
        live = live & ((kj + 1) * block_k - 1 >= pad)
    if window is not None:
        win_live = (kj + 1) * block_k - 1 >= start - window + 1
        if sinks:
            win_live = win_live | (kj * block_k <= pad + sinks - 1)
        live = live & win_live

    @pl.when(live)
    def _step():
        if int8:
            k = k_ref[0].astype(jnp.float32) * ks_ref[0]
            v = v_ref[0].astype(jnp.float32) * vs_ref[0]
        else:
            k = k_ref[0].astype(jnp.float32)
            v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)              # [S·group, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [S·group, BK]
        kv_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        # query row r is position start + r // group (row-major (s, g))
        q_pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (S * group, 1), 0) // group
        mask = kv_pos <= q_pos
        if padded:
            mask = mask & (kv_pos >= pad)
        if window is not None:
            wkeep = kv_pos > q_pos - window
            if sinks:
                wkeep = wkeep | (kv_pos < pad + sinks)
            mask = mask & wkeep
        _online_update(jnp.where(mask, s, NEG_INF), v, acc_ref, m_ref, l_ref)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        _finalize_out(o_ref, acc_ref, m_ref, l_ref)


DECODE_MAX_S = 16   # short-block bound: verify blocks / tiny continuations


def decode_flash_supported(max_len: int, Hq: int, Hkv: int,
                           block_k: int = None, S: int = 1) -> bool:
    """True iff flash_attention_decode can take these shapes (max_len tiles
    into ≥128-aligned kv blocks, GQA divides, query block short)."""
    bk = _auto_block(max_len, block_k)
    return (max_len % bk == 0 and bk >= 128 and Hq % Hkv == 0
            and 1 <= S <= DECODE_MAX_S)


def flash_attention_decode(q, k_cache, v_cache, start, *, scale: float = None,
                           block_k: int = None, interpret: bool = None,
                           k_scale=None, v_scale=None, pad_lens=None,
                           window: int = None, sinks: int = 0):
    """The serving decode/verify step as a Pallas kernel: a SHORT query
    block per row ([B, S, Hq, D], S ≤ DECODE_MAX_S — S=1 for a decode
    step, S=spec_k+1 for a speculative verify block, small S for short
    continuations) at cache positions ``start..start+S−1`` against a
    [B, Hkv, max_len, D] head-major cache (forward-only; serving never
    differentiates). The whole block shares ONE fetch of the live cache
    prefix per kv head, so a verify call costs O(start+S) HBM traffic
    instead of the dense sweep's O(max_len) — the same economics that
    make the S=1 step cheap, extended to the block widths speculation
    uses.

    Replaces models/decode.py:_cached_attention's S=1 dense sweep, which
    XLA must compute over the FULL static max_len width because ``start``
    is traced. Here ``start`` rides as scalar prefetch into the kv index
    map, so blocks past the live prefix are never DMA'd: a step costs
    O(start), not O(max_len) — at a 4k serving budget with a 512-token
    prompt that is ~7× less cache traffic, and the decode step is pure
    HBM bandwidth. GQA doubles down: grid rows are (batch, kv head), each
    fetching its cache tile ONCE for all ``group`` queries (the dense
    sweep's einsum reads it per q-head from HBM at small B).

    ``k_scale``/``v_scale``: int8-cache mode, dequantized in VMEM as in
    flash_attention_cached. ``pad_lens`` [B] int32: left-padded ragged
    batches — row b may only attend to positions ≥ pad_lens[b]; leading
    all-pad blocks are likewise skipped and un-fetched. ``window``:
    sliding-window attention — keys in (start − window, start]; a
    long-context SWA decode step fetches O(window), independent of how
    much history is cached. ``start`` may be scalar or [B] (per-row cache
    lengths — batched speculative decoding); per-row starts ride the same
    scalar-prefetch meta as pads, so each row's DMA still stops at its own
    live prefix. Callers gate on decode_flash_supported()."""
    B, S, Hq, D = q.shape
    assert 1 <= S <= DECODE_MAX_S, \
        f"decode kernel serves short query blocks (S<={DECODE_MAX_S}); " \
        f"got S={S}"
    Hkv, ML = k_cache.shape[1], k_cache.shape[2]
    group = Hq // Hkv
    if scale is None:
        scale = D ** -0.5
    block_k = _auto_block(ML, block_k)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    # head h = (h // group)-th kv head, (h % group)-th query of its group —
    # the same grouping _cached_attention's reshape uses; kernel rows are
    # (s, g) row-major so row // group recovers the query position
    qf = q.reshape(B, S, Hkv, group, D).transpose(0, 2, 1, 3, 4) \
        .reshape(B * Hkv, S * group, D)
    kf = k_cache.reshape(B * Hkv, ML, D)
    vf = v_cache.reshape(B * Hkv, ML, D)
    padded = pad_lens is not None
    starts = jnp.asarray(start, jnp.int32).reshape(-1)   # [1] or [B]
    n_start = starts.shape[0]
    assert n_start in (1, B), f"start must be scalar or [B]; got {n_start}"
    meta = starts
    if padded:
        meta = jnp.concatenate([meta, pad_lens.astype(jnp.int32)])

    def kv_idx(bh, kj, meta_ref):
        st = meta_ref[bh // Hkv] if n_start > 1 else meta_ref[0]
        pad = meta_ref[n_start + bh // Hkv] if padded else 0
        lo_pos = pad
        if window is not None:
            lo_pos = jnp.maximum(lo_pos,
                                 jnp.maximum(st - window + 1, 0))
        hi = (st + S - 1) // block_k       # the LAST query row's frontier
        if window is not None and sinks:
            # sink blocks walk at identity; the dead middle clamps forward
            # to the window's first block (repeats → single fetch)
            sink_first = pad // block_k
            sink_last = jnp.minimum((pad + sinks - 1) // block_k, hi)
            return (bh, jnp.where(kj <= sink_last,
                                  jnp.clip(kj, sink_first, sink_last),
                                  jnp.clip(kj, lo_pos // block_k, hi)), 0)
        return (bh, jnp.clip(kj, lo_pos // block_k, hi), 0)

    q_idx = lambda bh, kj, meta_ref: (bh, 0, 0)
    rows = S * group
    in_specs = [
        pl.BlockSpec((1, rows, D), q_idx, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), kv_idx, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), kv_idx, memory_space=pltpu.VMEM),
    ]
    operands = [qf, kf, vf]
    int8 = k_scale is not None
    if int8:
        sspec = pl.BlockSpec((1, block_k, 1), kv_idx,
                             memory_space=pltpu.VMEM)
        in_specs += [sspec, sspec]
        operands += [k_scale.reshape(B * Hkv, ML, 1),
                     v_scale.reshape(B * Hkv, ML, 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * Hkv, ML // block_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rows, D), q_idx,
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rows, D), jnp.float32),     # acc
            pltpu.VMEM((rows, 1), jnp.float32),     # running max
            pltpu.VMEM((rows, 1), jnp.float32),     # running denominator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_decode, Hkv=Hkv, group=group,
                          block_k=block_k, scale=scale, int8=int8,
                          padded=padded, n_start=n_start, S=S,
                          window=window, sinks=sinks),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * Hkv, rows, D), q.dtype),
        interpret=interpret,
    )(meta, *operands)
    return out.reshape(B, Hkv, S, group, D).transpose(0, 2, 1, 3, 4) \
        .reshape(B, S, Hq, D)


# --- backward kernels (FlashAttention-2 §3.2: per-block recompute) ---------

def _rebuild_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
                  qi, kj, block_q, block_k, scale, causal, window=None):
    """Recompute one tile's P = exp(S − lse) (fully-masked-row guarded) and
    dS = P ∘ (dP − Δ)·scale — the shared core of both backward passes
    (FlashAttention-2 §3.2); only the final accumulation matmuls differ.
    Returns (q, k, do, p, ds)."""
    q = q_ref[0].astype(jnp.float32)                    # [BQ, D]
    k = k_ref[0].astype(jnp.float32)                    # [BK, D]
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)                  # [BQ, D]
    lse = lse_ref[0]                                    # [BQ, 1]
    delta = delta_ref[0]                                # [BQ, 1]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale     # [BQ, BK]
    if causal or window is not None:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        kv_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        keep = jnp.ones(s.shape, jnp.bool_)
        if causal:
            keep = q_pos >= kv_pos
        if window is not None:
            keep = keep & (kv_pos > q_pos - window)
        s = jnp.where(keep, s, NEG_INF)
    p = jnp.exp(s - lse)
    p = jnp.where(lse > NEG_INF / 2, p, 0.0)            # fully-masked rows
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)             # [BQ, BK]
    ds = p * (dp - delta) * scale
    return q, k, do, p, ds


def _bwd_dq_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc, *,
                 qi, kj, block_q, block_k, scale, causal, window=None):
    """One dQ tile: dQ_i += dS_ij K_j. Shared by the rectangular and
    triangular dq grids."""
    _, k, _, _, ds = _rebuild_p_ds(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi=qi, kj=kj,
        block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        window=window)
    dq_acc[:] += jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, block_q, block_k, scale, causal, window=None):
    """dQ accumulated over kv-blocks in VMEM scratch (rectangular grid)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (kj * block_k <= qi * block_q + block_q - 1) if causal else True
    if window is not None:
        live = live & ((kj + 1) * block_k - 1
                       >= qi * block_q - window + 1)

    @pl.when(live)
    def _step():
        _bwd_dq_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_acc, qi=qi, kj=kj, block_q=block_q, block_k=block_k,
                     scale=scale, causal=causal, window=window)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dq_kernel_tri(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, dq_acc, *, block, n_q, scale):
    """dQ over the flattened causal lower triangle (see _kernel_tri)."""
    t = pl.program_id(1)
    qi, kj = _tri_decode(t, n_q)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    _bwd_dq_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc,
                 qi=qi, kj=kj, block_q=block, block_k=block, scale=scale,
                 causal=True)

    @pl.when(kj == qi)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_acc,
                  dv_acc, *, qi, kj, block_q, block_k, scale, causal,
                  window=None):
    """One dK/dV tile: dV_j += P_ijᵀ dO_i ; dK_j += dS_ijᵀ Q_i. Shared by
    the rectangular and reversed-triangle dkv grids."""
    q, _, do, p, ds = _rebuild_p_ds(
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qi=qi, kj=kj,
        block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        window=window)
    dv_acc[:] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [BK, D]
    dk_acc[:] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [BK, D]


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                    scale, causal, window=None):
    """dK/dV accumulated over q-blocks. Grid is (bh, kv-block, q-block)."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (qi * block_q + block_q - 1 >= kj * block_k) if causal else True
    if window is not None:
        # queries past kv_max + window − 1 can't see this kv block
        live = live & (qi * block_q
                       <= kj * block_k + block_k - 1 + window - 1)

    @pl.when(live)
    def _step():
        _bwd_dkv_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_acc, dv_acc, qi=qi, kj=kj, block_q=block_q,
                      block_k=block_k, scale=scale, causal=causal,
                      window=window)

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _tri_decode_rev(t, n_q):
    """Flattened index → (kj, qi) for the causal dkv triangle (qi ≥ kj):
    substituting u = n-1-kj, v = n-1-qi maps it onto the standard lower
    triangle, so the same decode serves. Row u iterates qi DESCENDING from
    n-1 to kj — first visit v=0 (init), last v=u i.e. qi == kj (finalize)."""
    u, v = _tri_decode(t, n_q)
    return n_q - 1 - u, n_q - 1 - v


def _bwd_dkv_kernel_tri(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dk_ref, dv_ref, dk_acc, dv_acc, *, block, n_q,
                        scale):
    """dK/dV over the flattened causal triangle (reversed coordinates)."""
    t = pl.program_id(1)
    kj, qi = _tri_decode_rev(t, n_q)

    @pl.when(qi == n_q - 1)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    _bwd_dkv_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  dk_acc, dv_acc, qi=qi, kj=kj, block_q=block,
                  block_k=block, scale=scale, causal=True)

    @pl.when(qi == kj)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                    interpret, g_lse=None, triangular=False, window=None):
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv

    qf, kf, vf = _heads_to_rows(q), _heads_to_rows(k), _heads_to_rows(v)
    dof = _heads_to_rows(g)
    of = _heads_to_rows(o)
    # Δ_i = rowsum(dO ∘ O) — cheap elementwise, XLA fuses it. Rank-3
    # [B*Hq, S, 1] like lse, for legal (1, block_q, 1) blocks.
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    if g_lse is not None:
        # lse cotangent folds straight into Δ: dS = P∘(dP − Δ + ḡ_lse)
        # because ∂lse/∂S = P — the kernels run unchanged on Δ' = Δ − ḡ.
        delta = delta - g_lse.astype(jnp.float32)

    if causal and triangular and block_q == block_k and window is None:
        return _flash_bwd_tri(qf, kf, vf, dof, lse, delta, B, S, Hq, Hkv,
                              D, group, scale, block_q, interpret, q, k, v)

    qspec = pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0),
                         memory_space=pltpu.VMEM)
    # same dead-block DMA elision as the forward (see _causal_kv_index)
    kvspec = pl.BlockSpec((1, block_k, D),
                          _causal_kv_index(block_q, block_k, group, causal,
                                           window=window),
                          memory_space=pltpu.VMEM)
    rowq = pl.BlockSpec((1, block_q, 1), lambda bh, qi, kj: (bh, qi, 0),
                        memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, window=window),
        grid=(B * Hq, S // block_q, S // block_k),
        in_specs=[qspec, kvspec, kvspec, qspec, rowq, rowq],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * Hq, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # dK/dV per q-head (grid bh spans B*Hq); GQA folds group q-heads onto
    # their kv-head after the kernel — keeps grid cells race-free.
    # q-side dead-prefix elision (see _causal_q_index); kv blocks are
    # indexed by the outer kj and already fetched once per kv grid row.
    q_idx2 = _causal_q_index(block_q, block_k, causal, window=window)
    qspec2 = pl.BlockSpec((1, block_q, D), q_idx2, memory_space=pltpu.VMEM)
    kvspec2 = pl.BlockSpec((1, block_k, D),
                           lambda bh, kj, qi, g_=group: (bh // g_, kj, 0),
                           memory_space=pltpu.VMEM)
    rowq2 = pl.BlockSpec((1, block_q, 1), q_idx2, memory_space=pltpu.VMEM)
    dkv_out = pl.BlockSpec((1, block_k, D), lambda bh, kj, qi: (bh, kj, 0),
                           memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, window=window),
        grid=(B * Hq, S // block_k, S // block_q),
        in_specs=[qspec2, kvspec2, kvspec2, qspec2, rowq2, rowq2],
        out_specs=[dkv_out, dkv_out],
        out_shape=[jax.ShapeDtypeStruct((B * Hq, S, D), jnp.float32),
                   jax.ShapeDtypeStruct((B * Hq, S, D), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    if group > 1:
        dk = dk.reshape(B, Hkv, group, S, D).sum(axis=2).reshape(B * Hkv, S, D)
        dv = dv.reshape(B, Hkv, group, S, D).sum(axis=2).reshape(B * Hkv, S, D)

    return (_rows_to_heads(dq, B, Hq),
            _rows_to_heads(dk.astype(k.dtype), B, Hkv),
            _rows_to_heads(dv.astype(v.dtype), B, Hkv))


def _flash_bwd_tri(qf, kf, vf, dof, lse, delta, B, S, Hq, Hkv, D, group,
                   scale, block, interpret, q, k, v):
    """Backward over flattened causal triangles: dq on the lower triangle,
    dk/dv on the reversed one — dead cells don't exist in either grid."""
    n_q = S // block
    T = n_q * (n_q + 1) // 2

    q_idx = lambda bh, t: (bh, _tri_decode(t, n_q)[0], 0)
    kv_idx = lambda bh, t, g_=group: (bh // g_, _tri_decode(t, n_q)[1], 0)
    qspec = pl.BlockSpec((1, block, D), q_idx, memory_space=pltpu.VMEM)
    kvspec = pl.BlockSpec((1, block, D), kv_idx, memory_space=pltpu.VMEM)
    rowq = pl.BlockSpec((1, block, 1), q_idx, memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_tri, block=block, n_q=n_q,
                          scale=scale),
        grid=(B * Hq, T),
        in_specs=[qspec, kvspec, kvspec, qspec, rowq, rowq],
        out_specs=pl.BlockSpec((1, block, D), q_idx,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * Hq, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    kv_idx2 = lambda bh, t, g_=group: \
        (bh // g_, _tri_decode_rev(t, n_q)[0], 0)
    q_idx2 = lambda bh, t: (bh, _tri_decode_rev(t, n_q)[1], 0)
    qspec2 = pl.BlockSpec((1, block, D), q_idx2, memory_space=pltpu.VMEM)
    kvspec2 = pl.BlockSpec((1, block, D), kv_idx2, memory_space=pltpu.VMEM)
    rowq2 = pl.BlockSpec((1, block, 1), q_idx2, memory_space=pltpu.VMEM)
    # dk/dv are PER-Q-HEAD (bh, not bh//group — GQA folds after the
    # kernel, exactly like the rectangular path)
    dkv_out = pl.BlockSpec(
        (1, block, D), lambda bh, t: (bh, _tri_decode_rev(t, n_q)[0], 0),
        memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_tri, block=block, n_q=n_q,
                          scale=scale),
        grid=(B * Hq, T),
        in_specs=[qspec2, kvspec2, kvspec2, qspec2, rowq2, rowq2],
        out_specs=[dkv_out, dkv_out],
        out_shape=[jax.ShapeDtypeStruct((B * Hq, S, D), jnp.float32),
                   jax.ShapeDtypeStruct((B * Hq, S, D), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block, D), jnp.float32),
                        pltpu.VMEM((block, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    if group > 1:
        dk = dk.reshape(B, Hkv, group, S, D).sum(axis=2).reshape(B * Hkv, S, D)
        dv = dv.reshape(B, Hkv, group, S, D).sum(axis=2).reshape(B * Hkv, S, D)

    return (_rows_to_heads(dq, B, Hq),
            _rows_to_heads(dk.astype(k.dtype), B, Hkv),
            _rows_to_heads(dv.astype(v.dtype), B, Hkv))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_lse_diff(q, k, v, causal, scale, block_q, block_k, interpret,
                    triangular, window):
    out, lse = _flash(q, k, v, causal, scale, block_q, block_k, interpret,
                      triangular, window)
    B, _, Hq, _ = q.shape
    return out, lse.reshape(B, Hq, -1)


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   triangular, window):
    out, lse = _flash(q, k, v, causal, scale, block_q, block_k, interpret,
                      triangular, window)
    B, _, Hq, _ = q.shape
    return (out, lse.reshape(B, Hq, -1)), (q, k, v, out, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, triangular,
                   window, res, g):
    q, k, v, o, lse = res
    g_out, g_lse = g
    B, S, Hq, _ = q.shape
    return _flash_bwd_impl(q, k, v, o, lse, g_out, causal, scale, block_q,
                           block_k, interpret,
                           g_lse=g_lse.reshape(B * Hq, S, 1),
                           triangular=triangular, window=window)


_flash_lse_diff.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(q, k, v, *, causal: bool = True,
                             scale: float = None, block_q: int = None,
                             block_k: int = None, interpret: bool = None,
                             triangular: bool = False, window: int = None):
    """flash_attention that also returns the per-row logsumexp [B, Hq, S] —
    the combination handle ring attention needs to merge partial attentions
    across ring steps (parallel/ring.py). Differentiable in both outputs.

    ``triangular=True``: causal grids flatten to their live triangles —
    above/below-diagonal dead cells vanish instead of being predicated off
    (~half the grid steps at long S). Applies to the STREAMING forward
    (K/V past RESIDENT_KV_BUDGET) and to BOTH backward passes (dq on the
    lower triangle, dk/dv on the reversed one), always requiring
    block_q == block_k and causal=True; anywhere else the flag is a no-op
    (the resident/rectangular kernels run as usual — don't benchmark it in
    the resident regime). Opt-in until validated on real TPU (staged in
    tests/test_tpu_pod.py; bench.py times it in its own guarded section) —
    flip the default once a chip has signed it off."""
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    if scale is None:
        scale = D ** -0.5
    block_q = _auto_block(S, block_q)
    block_k = _auto_block(S, block_k)
    tiles = (S % block_q == 0 and S % block_k == 0 and Hq % Hkv == 0
             and q.shape[1] == k.shape[1])
    if not tiles:
        return dense_attention_with_lse(q, k, v, causal=causal, scale=scale,
                                        window=window)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    return _flash_lse_diff(q, k, v, causal, scale, block_q, block_k,
                           interpret, triangular, window)


def flash_attention(q, k, v, *, causal: bool = True, scale: float = None,
                    block_q: int = None, block_k: int = None,
                    interpret: bool = None, triangular: bool = False,
                    window: int = None):
    """Drop-in for dense_attention: q [B,S,Hq,D], k/v [B,S,Hkv,D] → [B,S,Hq,D].

    Takes the Pallas kernel only when S tiles exactly into the given
    (hardware-aligned) block sizes and GQA divides evenly; any other shape
    gets the dense path so callers never have to think about it. One VJP
    definition serves both this and the with_lse variant: the dropped lse
    output is dead-code-eliminated and its zero cotangent folds out of Δ.
    """
    return flash_attention_with_lse(q, k, v, causal=causal, scale=scale,
                                    block_q=block_q, block_k=block_k,
                                    interpret=interpret,
                                    triangular=triangular, window=window)[0]
