"""Flash attention as a Pallas TPU kernel.

The hot op of the flagship model, written for the hardware (per
/opt/skills/guides/pallas_guide.md): the S×S score matrix never
materializes, all matmuls hit the MXU with fp32 accumulation, and two
variants trade HBM traffic against VMEM:

- **resident** (K/V ≤ RESIDENT_KV_BUDGET in VMEM): one K/V DMA per
  (batch·head, q-block) grid cell, inner fori_loop over tiles with the
  causal loop bound pruned — fastest at short/medium S;
- **streaming** (longer S): grid = (batch·head, q-blocks, kv-blocks), one
  (block_k, D) K/V tile per grid step with the flash running-max/
  denominator in VMEM scratch across the kv dimension — VMEM use is
  O(block), independent of S, so 32k+ context runs where the dense path
  cannot even compile.

GQA costs no memory: the KV BlockSpec index_map points q-head ``bh`` at
kv-head ``bh // group`` — no repeat materialization.

Backward pass: flash forward + dense recompute backward via custom_vjp —
exact gradients, with the dense memory cost paid only inside the backward.

Falls back to the lax dense path when S doesn't tile into the (aligned)
block sizes; ``interpret=True`` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring import dense_attention

NEG_INF = -1.0e30
DEFAULT_BLOCK = 128


# K+V bytes (in input dtype) we allow resident in VMEM before switching to
# the streaming grid: bf16 S·D ≤ 6MB/2/2 → e.g. S=12288 @ D=128 still resident.
RESIDENT_KV_BUDGET = 6 * 1024 * 1024


def _kernel_resident(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k,
                     seq_len, scale, causal):
    """Whole-K/V-in-VMEM variant: one DMA of K/V per (bh, q-block), inner
    fori_loop over tiles. Fastest at short/medium S (fewer HBM round trips,
    causal loop-bound pruning); VMEM-bounded, so only used under budget."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                      # [BQ, D]
    if causal:
        n_blocks = (qi * block_q + block_q - 1) // block_k + 1
    else:
        n_blocks = seq_len // block_k
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            kv_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
            block_q, block_k, scale, causal):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # whole block above the causal diagonal → no compute
    live = (kj * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)                  # [BQ, D]
        k = k_ref[0].astype(jnp.float32)                  # [BK, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            kv_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new > NEG_INF / 2, p, 0.0)        # fully-masked rows
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_kv - 1)
    def _finalize():
        l = l_ref[:]
        o_ref[0] = (acc_ref[:] / jnp.where(l > 0, l, 1.0)).astype(o_ref.dtype)


def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    group = Hq // Hkv

    # [B, S, H, D] → [B*H, S, D] so each grid cell owns one head's sequence
    qf = q.transpose(0, 2, 1, 3).reshape(B * Hq, S, D)
    kf = k.transpose(0, 2, 1, 3).reshape(B * Hkv, S, D)
    vf = v.transpose(0, 2, 1, 3).reshape(B * Hkv, S, D)

    # bh = b*Hq + h → kv row b*Hkv + h//group == bh // group (Hq = Hkv·group)
    kv_bytes = 2 * S * D * jnp.dtype(q.dtype).itemsize
    if kv_bytes <= RESIDENT_KV_BUDGET:
        kernel = functools.partial(
            _kernel_resident, block_q=block_q, block_k=block_k, seq_len=S,
            scale=scale, causal=causal)
        out = pl.pallas_call(
            kernel,
            grid=(B * Hq, S // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda bh, qi: (bh, qi, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, S, D), lambda bh, qi, g=group: (bh // g, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, S, D), lambda bh, qi, g=group: (bh // g, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda bh, qi: (bh, qi, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((B * Hq, S, D), q.dtype),
            interpret=interpret,
        )(qf, kf, vf)
        return out.reshape(B, Hq, S, D).transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _kernel, block_q=block_q, block_k=block_k, scale=scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(B * Hq, S // block_q, S // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, qi, kj, g=group: (bh // g, kj, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D),
                         lambda bh, qi, kj, g=group: (bh // g, kj, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, kj: (bh, qi, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * Hq, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, Hq, S, D).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: dense_attention(q_, k_, v_, causal=causal,
                                           scale=scale), q, k, v)
    return vjp(g)


_flash_diff.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True, scale: float = None,
                    block_q: int = DEFAULT_BLOCK, block_k: int = DEFAULT_BLOCK,
                    interpret: bool = None):
    """Drop-in for dense_attention: q [B,S,Hq,D], k/v [B,S,Hkv,D] → [B,S,Hq,D].

    Takes the Pallas kernel only when S tiles exactly into the given
    (hardware-aligned) block sizes and GQA divides evenly; any other shape
    gets the dense path so callers never have to think about it.
    """
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    if scale is None:
        scale = D ** -0.5
    tiles = (S % block_q == 0 and S % block_k == 0 and Hq % Hkv == 0)
    if not tiles:
        return dense_attention(q, k, v, causal=causal, scale=scale)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    return _flash_diff(q, k, v, causal, scale, block_q, block_k, interpret)
