"""Slice-side parallelism: topology discovery, device meshes, collectives.

The provisioner's job ends at a Ready slice carrying ``tpu.kaito.sh/*``
labels (SURVEY.md §2c, §5 "distributed communication backend"); this package
is the workload half of that contract — it turns those labels into a
``jax.sharding.Mesh`` (ICI within a slice, DCN across slices) and provides
the sequence-parallel ring attention used by the flagship model.
"""

from .topology import (AXIS_DATA, AXIS_EXPERT, AXIS_MODEL, AXIS_PIPE,
                       AXIS_SEQ, AXIS_SLICE, SliceTopology, make_mesh,
                       mesh_shape_for)
from .ring import ring_attention

__all__ = ["SliceTopology", "make_mesh", "mesh_shape_for", "ring_attention",
           "AXIS_SLICE", "AXIS_DATA", "AXIS_PIPE", "AXIS_SEQ", "AXIS_EXPERT",
           "AXIS_MODEL"]
