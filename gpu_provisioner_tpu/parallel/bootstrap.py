"""In-cluster workload bootstrap: node labels → SliceTopology → jax.distributed.

The last hop of the provisioner contract. The instance provider stamps
``tpu.kaito.sh/*`` (incl. multi-slice slice-index / num-slices / coordinator,
providers/instance.py:_slice_group_identity) onto node pools; GKE copies pool
labels onto Nodes. A workload pod cannot project *node* labels via the
downward API — only its own fields — so the supported contract is:

1. the pod projects ``spec.nodeName`` into ``NODE_NAME`` (downward API,
   see examples/jobset-multislice.yaml),
2. this module GETs that Node with the pod's in-cluster service account
   (RBAC: get on nodes) and reads the labels,
3. ``SliceTopology.from_node_labels`` + ``distributed_init_args`` feed
   ``jax.distributed.initialize`` — no manual env required.

Generalizes the reference seam where labels stamped at create
(/root/reference/pkg/providers/instance/instance.go:321-369) are synced to
nodes for workloads to consume
(vendor/sigs.k8s.io/karpenter/pkg/controllers/nodeclaim/lifecycle/registration.go:120-147).
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

from .topology import SliceTopology, TopologyError

ENV_NODE_NAME = "NODE_NAME"


async def node_labels_from_api(node_name: str,
                               connection=None) -> dict[str, str]:
    """GET the Node and return its labels using the in-cluster credentials
    (or an explicit runtime ``KubeConnection``)."""
    from ..apis.core import Node
    from ..runtime.rest import KubeConnection, RestClient

    conn = connection or KubeConnection.in_cluster()
    client = RestClient(conn)
    try:
        node = await client.get(Node, node_name)
    finally:
        aclose = getattr(client, "aclose", None)
        if aclose:
            await aclose()
    return dict(node.metadata.labels)


def topology_from_labels(labels: Mapping[str, str],
                         environ: Optional[Mapping[str, str]] = None
                         ) -> SliceTopology:
    return SliceTopology.from_node_labels(labels, environ=environ)


async def discover(environ: Optional[Mapping[str, str]] = None,
                   connection=None) -> SliceTopology:
    """SliceTopology for THIS pod: node labels via the API when NODE_NAME is
    projected, else pure-env fallback (TPU_KAITO_* downward/static vars)."""
    env = environ if environ is not None else os.environ
    node_name = env.get(ENV_NODE_NAME, "")
    if node_name:
        labels = await node_labels_from_api(node_name, connection=connection)
        return SliceTopology.from_node_labels(labels, environ=env)
    return SliceTopology.from_env(env)


def initialize_distributed(topo: SliceTopology) -> None:
    """Call ``jax.distributed.initialize`` from a discovered topology.

    Idempotent-ish: skips when a distributed client is already live (e.g.
    the runtime initialized it) and when the topology is a single-process
    slice (1 host, 1 slice) where initialization is unnecessary."""
    if topo.hosts * topo.num_slices <= 1:
        return
    import jax

    # Best-effort pre-check (private API — tolerate its absence), then a
    # message-based guard: jax 0.9 raises RuntimeError("distributed.initialize
    # should only be called once."), older versions say "already initialized".
    state = getattr(getattr(jax, "_src", None), "distributed", None)
    if state is not None and getattr(
            getattr(state, "global_state", None), "client", None) is not None:
        return
    try:
        jax.distributed.initialize(**topo.distributed_init_args())
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg or "only be called once" in msg:
            return  # double-init (e.g. bootstrap retry)
        raise


async def bootstrap(environ: Optional[Mapping[str, str]] = None,
                    connection=None) -> SliceTopology:
    """discover() + initialize_distributed(): the one-call pod entrypoint."""
    topo = await discover(environ=environ, connection=connection)
    initialize_distributed(topo)
    return topo
