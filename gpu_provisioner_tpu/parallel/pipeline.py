"""Pipeline parallelism: gpipe-style layer sharding over the ``pipe`` axis.

Stacked layer params ([L, ...] leading dim) shard over ``pipe`` so each
stage holds L/n_stages layers; activations travel stage-to-stage with
``lax.ppermute`` (neighbor ICI hop) while microbatches fill the pipeline —
the schedule is the classic gpipe ramp: T = n_micro + n_stages - 1 ticks,
bubble fraction (n_stages-1)/T. Everything is shape-static and
differentiable (ppermute transposes to the reverse permutation), so the
same construct serves the training backward pass.

Embedding and the LM head are cheap relative to blocks and stay outside the
pipeline (replicated over ``pipe``); only the decoder blocks are staged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .topology import AXIS_PIPE


def pipeline_apply(stage_fn: Callable, stage_params, x_micro, *,
                   axis_name: str = AXIS_PIPE):
    """Run microbatches through the stage pipeline (inside shard_map).

    stage_fn(stage_params, x) -> y : applies THIS stage's layers.
    x_micro: [n_micro, mb, ...] — full microbatch array (replicated input;
    only stage 0 consumes it). Returns [n_micro, mb, ...] with every stage
    holding the final outputs (broadcast from the last stage via psum so the
    loss can be computed replicated).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    state = jnp.zeros_like(x_micro[0])
    outputs = jnp.zeros_like(x_micro)

    for t in range(ticks):                      # static schedule
        feed_idx = min(t, n_micro - 1)
        feeding = jnp.logical_and(stage == 0, t < n_micro)
        state_in = jnp.where(feeding, x_micro[feed_idx], state)
        y = stage_fn(stage_params, state_in)
        out_idx = t - (n_stages - 1)            # micro finishing this tick
        if out_idx >= 0:
            is_last = stage == n_stages - 1
            outputs = outputs.at[out_idx].set(
                jnp.where(is_last, y, outputs[out_idx]))
        state = lax.ppermute(y, axis_name, perm)

    # broadcast final outputs from the last stage to every stage
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    return lax.psum(outputs, axis_name)


def pipelined_blocks(block_fn: Callable, mesh, n_layers: int,
                     n_micro: int):
    """Wrap a scanned-block body into a pipelined apply over the mesh.

    block_fn(layer_params, x) -> x : ONE layer.
    Returns fn(blocks_stacked, x [B, S, D]) -> [B, S, D] where
    ``blocks_stacked`` has leading dim L sharded over ``pipe`` and the batch
    splits into n_micro microbatches.
    """
    n_stages = mesh.shape[AXIS_PIPE]
    assert n_layers % n_stages == 0, (n_layers, n_stages)

    def stage_fn(stage_params, x):
        # this stage's L/n_stages layers, scanned
        def body(h, lp):
            return block_fn(lp, h), None
        out, _ = lax.scan(body, x, stage_params)
        return out

    def apply(blocks_stacked, x):
        from .topology import AXIS_DATA, AXIS_SLICE

        B = x.shape[0]
        assert B % n_micro == 0, (B, n_micro)
        micro = x.reshape(n_micro, B // n_micro, *x.shape[1:])
        # blocks: P(pipe) on the stacked layer dim (weights replicated over
        # model inside the pipeline — pp composes with dp here, tp is a
        # future refinement); microbatch dim stays whole, per-micro batch
        # shards over (slice, data)
        blocks_spec = jax.tree.map(lambda _: P(AXIS_PIPE), blocks_stacked)
        micro_spec = P(None, (AXIS_SLICE, AXIS_DATA),
                       *([None] * (x.ndim - 1)))
        out = jax.shard_map(
            partial(pipeline_apply, stage_fn),
            mesh=mesh,
            in_specs=(blocks_spec, micro_spec),
            out_specs=micro_spec,
            check_vma=False,
        )(blocks_stacked, micro)
        return out.reshape(B, *x.shape[1:])

    return apply
